#!/usr/bin/env bash
# Chaos gate: run the fault-injection suite standalone so the injection
# points and the recovery ladder cannot silently rot (tests/test_chaos.py
# arms every named point in robustness/inject.py and requires the query
# to answer with clean-run results).  CPU-only — the virtual 8-device
# mesh exercises the distributed demotion rungs without TPU hardware.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_ENABLE_X64=1
export XLA_FLAGS="--xla_force_host_platform_device_count=8 --xla_cpu_enable_fast_math=false ${XLA_FLAGS:-}"

echo "== chaos suite (fault injection + recovery ladder) =="
python -m pytest tests/ -q -m chaos --maxfail=5

echo "== hang/corruption spray (delay + corrupt rules, short deadlines) =="
# bounded wedges (0.2s) at EVERY registered injection point plus bit
# flips on both spill restore tiers, under tight CPU-scale watchdog
# deadlines; the query must still answer with clean-run results
python - <<'PY'
import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.robustness import inject as I

s = TpuSession({
    "spark.rapids.tpu.watchdog.defaultDeadlineMs": 500,
    "spark.rapids.tpu.watchdog.queryDeadlineMs": 30_000,
    "spark.rapids.memory.tpu.deviceLimitBytes": 65536,
    "spark.rapids.sql.recovery.backoffMs": 5,
})
rng = np.random.default_rng(0)
pdf = pd.DataFrame({"k": rng.integers(0, 50, 4000),
                    "v": rng.normal(size=4000)})
df = (s.create_dataframe(pdf).group_by("k")
      .agg(F.sum(F.col("v")).alias("sv"),
           F.count(F.col("v")).alias("c")))
want = df.to_pandas().sort_values("k", ignore_index=True)
rules = []
try:
    for point in I.injection_points():
        rules.append(I.inject(point, kind="delay", delay_s=0.2,
                              count=2, probability=0.5, seed=7,
                              all_threads=True))
    for point in ("spill.corrupt.host", "spill.corrupt.disk"):
        rules.append(I.inject(point, kind="corrupt", count=2,
                              probability=0.5, seed=11,
                              all_threads=True))
    got = df.to_pandas().sort_values("k", ignore_index=True)
finally:
    for r in rules:
        I.remove(r)
pd.testing.assert_frame_equal(got, want)
print("hang/corruption spray OK "
      f"(recovery trail: {[r['action'] for r in s.recovery_log]})")
PY

echo "== checkpoint spray (delay + corrupt + oom across exchange/spill points, checkpointing on AND off) =="
# distributed two-stage plan on the virtual 8-device mesh; sprayed
# faults land mid-plan so stage checkpoints actually resume.  Both
# checkpoint settings must answer with clean-run results — partial
# recovery is an optimization, never a correctness knob.
python - <<'PY'
import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.memory import retry as _retry  # registers memory.oom
from spark_rapids_tpu.robustness import inject as I
from spark_rapids_tpu.robustness.checkpoint import checkpoint_metrics

rng = np.random.default_rng(1)
pdf = pd.DataFrame({"k": rng.integers(0, 50, 4000),
                    "v": rng.normal(size=4000)})

SPRAY = (("shuffle.exchange", "raise"), ("shuffle.exchange", "delay"),
         ("checkpoint.write", "delay"), ("checkpoint.restore", "corrupt"),
         ("spill.corrupt.host", "corrupt"), ("memory.oom", "raise"))

for enabled in (True, False):
    s = TpuSession({
        "spark.rapids.sql.recovery.checkpoint.enabled": enabled,
        "spark.rapids.tpu.watchdog.defaultDeadlineMs": 500,
        "spark.rapids.sql.recovery.backoffMs": 5,
    }, mesh=make_mesh(8))
    df = (s.create_dataframe(pdf).group_by("k")
          .agg(F.sum(F.col("v")).alias("sv"),
               F.count(F.col("v")).alias("c")).orderBy("k"))
    want = df.to_pandas()
    checkpoint_metrics.reset()
    with I.scoped_rules():
        for point, kind in SPRAY:
            I.inject(point, kind=kind, count=2, probability=0.5,
                     seed=29, delay_s=0.2, all_threads=True)
        got = df.to_pandas()
    pd.testing.assert_frame_equal(
        got.sort_values("k", ignore_index=True),
        want.sort_values("k", ignore_index=True))
    m = checkpoint_metrics.snapshot()
    if not enabled:
        assert m["writes"] == 0, m
    print(f"checkpoint spray OK (enabled={enabled}, "
          f"writes={m['writes']} resumes={m['resumes']} "
          f"invalid={m['invalid']}, "
          f"trail: {[r['action'] for r in s.recovery_log]})")
PY

echo "== fused-wire + hash-kernel spray (both knobs on; exchange/spill/oom faults; forced slot-table overflow) =="
# two legs: (1) wire-fused distributed stages — the warm
# speculative launch folds the wire packer into the compute
# program (one launch per shard, pinned by fusedWireStages)
# and exchange faults then land on the fused program; (2) the
# hash group-by with tableSlots forced far below the live key
# count, so every launch overflows and must fall back to the
# exact sort kernel.  Gates: bit-exact answers everywhere, the
# overflow-fallback counter actually fired, clean recovery
# trails.
python - <<'PY'
import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec.fusion import fusion_metrics
from spark_rapids_tpu.memory import retry as _retry  # registers memory.oom
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.robustness import inject as I

rng = np.random.default_rng(7)
# sparse 2^40 keyspace: the coded dense-directory path refuses, so the
# hash-kernel dispatch is actually exercised (dense small keys would
# route to direct indexing and spray nothing new)
uni = np.unique(rng.integers(0, 1 << 40, 8000, dtype=np.int64))[:2000]
pdf = pd.DataFrame({"k": uni[rng.integers(0, len(uni), 4000)],
                    "v": rng.integers(0, 1000, 4000).astype(np.float64)})


def plan(s):
    return (s.create_dataframe(pdf).group_by("k")
            .agg(F.sum(F.col("v")).alias("sv"),
                 F.count(F.col("v")).alias("c")).orderBy("k"))


base = TpuSession({})
want = plan(base).to_pandas().sort_values("k", ignore_index=True)
base.stop()

# leg 1: wire-fused distributed stages under exchange faults.  Warm
# speculative launches fold the wire packer into the compute program
# (fusedWireStages pins it); sprayed faults then land on the fused
# exchange and every answer must still be bit-exact.
fusion_metrics.reset()
s = TpuSession({
    "spark.rapids.tpu.pallas.hash.enabled": True,
    "spark.rapids.tpu.pallas.hash.tableSlots": 65536,
    "spark.rapids.tpu.fusion.wire.enabled": True,
    # looser than the checkpoint spray's 500ms: the sparse-key hash
    # path is legitimately slower than the coded directory on CPU, and
    # a trip inside the demoted (last) rung has no rung left to catch it
    "spark.rapids.tpu.watchdog.defaultDeadlineMs": 2000,
    "spark.rapids.sql.recovery.backoffMs": 5,
}, mesh=make_mesh(8))
df = plan(s)
pd.testing.assert_frame_equal(
    df.to_pandas().sort_values("k", ignore_index=True), want)  # cold
pd.testing.assert_frame_equal(
    df.to_pandas().sort_values("k", ignore_index=True), want)  # warm
m = fusion_metrics.snapshot()
assert m["fusedWireStages"] >= 1, m
with I.scoped_rules():
    for point, kind in (("shuffle.exchange", "raise"),
                        ("shuffle.exchange", "delay"),
                        ("spill.corrupt.host", "corrupt"),
                        ("memory.oom", "raise")):
        I.inject(point, kind=kind, count=2, probability=0.5,
                 seed=31, delay_s=0.2, all_threads=True)
    got = df.to_pandas().sort_values("k", ignore_index=True)
pd.testing.assert_frame_equal(got, want)
m = fusion_metrics.snapshot()
print(f"fused-wire spray OK (fusedWireStages={m['fusedWireStages']}, "
      f"trail: {[r['action'] for r in s.recovery_log]})")
s.stop()

# leg 2: hash-kernel group-by under spill/oom faults, with the slot
# table forced to overflow (tableSlots=64 << 2000 live keys).  Every
# launch must come back overflowed, fall back to the exact sort
# kernel, and still answer with clean-run results — rows are never
# dropped, the fallback counter proves the rung actually fired.
fusion_metrics.reset()
s = TpuSession({
    "spark.rapids.tpu.pallas.hash.enabled": True,
    "spark.rapids.tpu.pallas.hash.tableSlots": 64,
    # no tight watchdog here: the overflow rung legitimately pays the
    # hash launch AND the full sort fallback in one pipeline step
    "spark.rapids.tpu.watchdog.defaultDeadlineMs": 5000,
    "spark.rapids.sql.recovery.backoffMs": 5,
})
df = plan(s)
with I.scoped_rules():
    for point, kind in (("spill.corrupt.host", "corrupt"),
                        ("memory.oom", "raise")):
        I.inject(point, kind=kind, count=2, probability=0.5,
                 seed=37, delay_s=0.2, all_threads=True)
    got = df.to_pandas().sort_values("k", ignore_index=True)
pd.testing.assert_frame_equal(got, want)
m = fusion_metrics.snapshot()
assert m["hashKernelLaunches"] >= 1, m
assert m["hashOverflowFallbacks"] >= 1, m
print(f"hash overflow spray OK (launches={m['hashKernelLaunches']} "
      f"fallbacks={m['hashOverflowFallbacks']}, "
      f"trail: {[r['action'] for r in s.recovery_log]})")
s.stop()
PY

echo "== continuous-ingest soak: join + window + top-N shapes (N ticks under chaos spray, exact-result + bounded-memory/state gates) =="
# THREE standing queries — join-enrich-then-aggregate with a top-N
# post chain, windowed aggregation with watermark eviction, and the
# original plain aggregate — each ingest one appended parquet file
# per tick while delay/raise/corrupt/oom rules spray every tick's
# executions (the incremental points plus the exchange and spill
# surfaces).  Gates: every tick's answer on every shape is EXACTLY
# the one-shot recompute over everything ingested so far (the
# windowed oracle filtered by the tick's own committed watermark;
# epoch rollback may degrade a tick to full recompute — never to
# wrong bytes), memory is bounded (spill-catalog device bytes and
# process RSS plateau instead of growing with tick count), and the
# windowed shape's STATE is bounded — watermark eviction holds state
# bytes at a plateau under infinite-style ingest with zero stale or
# resurrected windows.
python - <<'PY'
import os
import shutil
import tempfile

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.memory import retry as _retry  # registers memory.oom
from spark_rapids_tpu.robustness import inject as I
from spark_rapids_tpu.robustness import incremental as _inc  # registers points
from spark_rapids_tpu.robustness.incremental import incremental_metrics

TICKS = 8
SPRAY = (("io.read", dict(kind="raise", count=2, probability=0.4)),
         ("shuffle.exchange", dict(kind="raise", count=2,
                                   probability=0.4)),
         ("shuffle.exchange", dict(kind="delay", delay_s=0.2, count=1,
                                   probability=0.3)),
         ("memory.oom", dict(kind="raise", count=1, probability=0.3)),
         ("incremental.state.restore", dict(kind="corrupt", count=1,
                                            probability=0.3)),
         ("incremental.state.write", dict(kind="raise", count=1,
                                          probability=0.2)),
         ("checkpoint.restore", dict(kind="corrupt", count=1,
                                     probability=0.2)),
         ("spill.corrupt.host", dict(kind="corrupt", count=1,
                                     probability=0.3)))

def rss_mb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0

d = tempfile.mkdtemp(prefix="tpu-ingest-soak-")
rng = np.random.default_rng(13)
def write(i):
    pdf = pd.DataFrame({"k": rng.integers(0, 50, 4000),
                        "v": rng.integers(0, 1000, 4000).astype(np.float64)})
    p = os.path.join(d, f"b{i:03d}.parquet")
    pdf.to_parquet(p, index=False)
    return p

def write_win(i, tick):
    pdf = pd.DataFrame({
        "k": rng.integers(0, 10, 3000),
        "v": rng.integers(0, 1000, 3000).astype(np.float64),
        "ts": pd.to_datetime("2024-01-01") + pd.to_timedelta(
            tick * 600 + rng.integers(0, 600, 3000), unit="s")})
    p = os.path.join(d, f"w{i:03d}.parquet")
    pdf.to_parquet(p, index=False)
    return p

s = TpuSession({"spark.rapids.sql.recovery.backoffMs": 5,
                "spark.rapids.tpu.watchdog.defaultDeadlineMs": 15000,
                # ISSUE 11: the state/spill frames this soak's corrupt
                # rules flip are COMPRESSED (the shared host codec) —
                # the incremental.state.restore spray therefore covers
                # the compressed-state leg of the codec-corruption gate
                "spark.rapids.tpu.encoding.storage.hostCodec": "lz4",
                "spark.rapids.tpu.incremental.tiers": "host,disk",
                # ISSUE 14: watermark eviction two buckets behind the
                # newest event time — the bounded-state gate's knob
                "spark.rapids.tpu.incremental.watermarkDelayMs": 1200000},
               mesh=make_mesh(8))
incremental_metrics.reset()

# shape 1: join-enrich-then-aggregate with a provable top-N chain
dim = pd.DataFrame({"k": np.arange(50),
                    "w": (np.arange(50) % 7 + 1).astype(np.float64)})
dim_agg = s.create_dataframe(dim).groupBy("k").agg(F.max("w").alias("w"))
fj = [write(0), write(1)]
df_j = (s.read.parquet(*fj).join(dim_agg, "k").groupBy("k")
        .agg(F.sum((F.col("v") * F.col("w")).alias("vw")).alias("s"),
             F.count("v").alias("c"))
        .orderBy(F.col("k").desc()).limit(20))
run_j = s.incremental(df_j)
assert run_j._spec is not None and run_j._spec.join_type == "inner"
assert run_j._spec.trim_n == 20

# shape 2: windowed aggregation with watermark eviction
fw = [write_win(0, 0), write_win(1, 1)]
df_w = (s.read.parquet(*fw)
        .groupBy(F.window("ts", "10 minutes"), "k")
        .agg(F.sum("v").alias("sv"), F.count("v").alias("c"))
        .orderBy("window.start", "k"))
run_w = s.incremental(df_w)
assert run_w._spec is not None and run_w._spec.window_end == "window.end"

# shape 3: the original plain mergeable aggregate
fa = [write(100), write(101)]
df_a = (s.read.parquet(*fa).groupBy("k")
        .agg(F.sum("v").alias("sv"), F.count("v").alias("c"),
             F.avg("v").alias("av")).orderBy("k"))
run_a = s.incremental(df_a)

for r in (run_j, run_w, run_a):
    r.tick()  # cold epochs, no chaos

raised = 0
dev, rss, wstate = [], [], []
try:
    for t in range(TICKS):
        pj, pw, pa = write(2 + t), write_win(2 + t, 2 + t), write(102 + t)
        # a tick may RAISE when chaos kills both the delta attempt AND
        # the degraded recompute (e.g. a state.write fault landing on
        # the recompute path) — the PR7 contract is that the committed
        # epoch is untouched and the files re-ingest on retry; the
        # post-spray retry below exercises exactly that
        results = {}
        with I.scoped_rules():
            for point, kw in SPRAY:
                I.inject(point, seed=100 + t, all_threads=True, **kw)
            for name, runner, paths in (("j", run_j, [pj]),
                                        ("w", run_w, [pw]),
                                        ("a", run_a, [pa])):
                try:
                    results[name] = runner.tick(paths)
                except Exception:
                    results[name] = None
        for name, runner, paths in (("j", run_j, [pj]),
                                    ("w", run_w, [pw]),
                                    ("a", run_a, [pa])):
            if results[name] is None:  # spray disarmed: clean retry
                raised += 1
                results[name] = runner.tick(paths)
        got_j = results["j"].to_pandas()
        got_w = results["w"].to_pandas()
        got_a = results["a"].to_pandas()
        # one-shot recompute oracles over everything ingested (each
        # runner keeps its standing df's scan in step), chaos disarmed;
        # the windowed oracle applies the tick's OWN committed
        # watermark — stale or resurrected windows would diverge
        pd.testing.assert_frame_equal(got_j, df_j.to_pandas())
        wm = run_w.last_tick_info["watermark"]
        # canonical eviction semantics (the test helper's oracle):
        # null-window buckets never expire, so the filter keeps them
        pd.testing.assert_frame_equal(
            got_w, df_w.filter(
                F.col("window.end").isNull() |
                (F.col("window.end") > pd.Timestamp(wm, unit="us")))
            .to_pandas())
        pd.testing.assert_frame_equal(got_a, df_a.to_pandas())
        dev.append(s.memory_catalog.stats()["device_bytes"])
        rss.append(rss_mb())
        wstate.append(run_w.store.state_bytes)
finally:
    for r in (run_j, run_w, run_a):
        r.close()
    s.stop()
    shutil.rmtree(d, ignore_errors=True)

m = incremental_metrics.snapshot()
# bounded memory: state size is per-group (and per-LIVE-window), not
# per-ingested-row — device watermark and RSS plateau, not grow
assert dev[-1] <= max(dev[:2]) + (16 << 20), dev
assert rss[-1] - rss[1] < 400.0, rss
# bounded state: watermark eviction holds the windowed shape's state
# bytes at a plateau across 8 infinite-style ingest ticks
assert wstate[-1] <= max(wstate[:3]) + 4096, wstate
assert m["watermarkEvictedBuckets"] >= 4, m
assert m["commits"] >= 3 * TICKS, m
assert m["joinTicks"] + m["windowTicks"] + m["topnTicks"] >= 1, m
print(f"ingest soak OK ({TICKS} chaos ticks x 3 shapes exact, "
      f"raised+retried={raised}, "
      f"incremental={m['incrementalTicks']} full={m['fullRecomputes']} "
      f"rollbacks={m['rollbacks']} stateBytes={m['stateBytes']} "
      f"wmEvicted={m['watermarkEvictedBuckets']}bkt/"
      f"{m['watermarkEvictedBytes']}B, "
      f"device_bytes={dev[-1]} rssΔ={rss[-1]-rss[1]:.0f}MB "
      f"windowState={wstate})")
PY

echo "== jit-cache corruption/version spray (persistent tier degraded, exact results) =="
# populate a persistent jit-cache dir, then attack it every way the
# tier must survive: seeded bit flips at the jitcache.load fire_mutate
# hook, on-disk truncation, a header stamped by a different jax
# version, and raise/delay rules on the load path.  Every degraded
# load must fall back to a fresh compile — the query answers with
# clean-run results, wrong executables are never run.
python - <<'PY'
import glob
import json
import os
import shutil
import tempfile

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.ops import jit_cache
from spark_rapids_tpu.robustness import inject as I

d = tempfile.mkdtemp(prefix="tpu-jitcache-chaos-")
rng = np.random.default_rng(5)
pdf = pd.DataFrame({"k": rng.integers(0, 50, 4000),
                    "v": rng.normal(size=4000)})
try:
    s = TpuSession({"spark.rapids.tpu.jitCache.dir": d,
                    "spark.rapids.sql.recovery.backoffMs": 5})
    df = (s.create_dataframe(pdf)
          .filter(F.col("v") > -1.0)
          .select((F.col("v") * 2.0).alias("v2"), F.col("k"))
          .group_by("k").agg(F.sum(F.col("v2")).alias("sv"),
                             F.count(F.col("v2")).alias("c")))
    jit_cache.clear()
    want = df.to_pandas().sort_values("k", ignore_index=True)
    entries = glob.glob(os.path.join(d, "*.jit"))
    assert entries, "persistent tier wrote nothing"

    def fresh():  # simulate a fresh process against the same dir
        jit_cache.clear()
        jit_cache.configure_persistent(None)
        jit_cache.configure_persistent(d)

    # pass 1: seeded bit flips via the fire_mutate hook (CRC gate)
    fresh()
    with I.scoped_rules():
        I.inject("jitcache.load", kind="corrupt", count=3,
                 probability=0.7, seed=17, all_threads=True)
        got = df.to_pandas().sort_values("k", ignore_index=True)
    pd.testing.assert_frame_equal(got, want)
    inv1 = jit_cache.persistent_info()["invalid"]
    assert inv1 >= 1, "corrupt rule never hit a load"

    # pass 2: on-disk truncation + a foreign-version header
    entries = sorted(glob.glob(os.path.join(d, "*.jit")))
    assert len(entries) >= 2, entries
    with open(entries[0], "r+b") as f:
        f.truncate(max(os.path.getsize(entries[0]) // 2, 8))
    raw = open(entries[1], "rb").read()
    head, _, payload = raw.partition(b"\n")
    hdr = json.loads(head)
    hdr["env"]["jax"] = "0.0.0-elsewhere"
    with open(entries[1], "wb") as f:
        f.write(json.dumps(hdr).encode() + b"\n" + payload)
    fresh()
    got = df.to_pandas().sort_values("k", ignore_index=True)
    pd.testing.assert_frame_equal(got, want)
    assert jit_cache.persistent_info()["invalid"] >= 2, \
        jit_cache.persistent_info()

    # pass 3: raise + bounded-delay rules on the load path
    fresh()
    with I.scoped_rules():
        I.inject("jitcache.load", count=2, probability=0.5, seed=23,
                 all_threads=True)
        I.inject("jitcache.load", kind="delay", delay_s=0.2, count=2,
                 probability=0.5, seed=29, all_threads=True)
        got = df.to_pandas().sort_values("k", ignore_index=True)
    pd.testing.assert_frame_equal(got, want)
    s.stop()
    print(f"jit-cache spray OK (invalid={jit_cache.persistent_info()['invalid']}, "
          f"entries={len(glob.glob(os.path.join(d, '*.jit')))})")
finally:
    jit_cache.configure_persistent(None)
    shutil.rmtree(d, ignore_errors=True)
PY

echo "== concurrent spray (N clients, faults keyed per query, isolation gate) =="
# 8 client threads share one session through the admission layer; half
# carry injected faults scoped to THEIR query via keyed injection
# scopes.  The isolation gate: every clean client's result is
# bit-identical to solo execution, zero robustness events float
# unattributed, and no clean query's trail shows recovery/corruption.
python - <<'PY'
import threading

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.memory import retry as _retry  # registers memory.oom
from spark_rapids_tpu.robustness import inject as I
import tempfile

logdir = tempfile.mkdtemp(prefix="tpu-chaos-events-")
s = TpuSession({
    "spark.rapids.tpu.eventLog.dir": logdir,
    "spark.rapids.sql.recovery.backoffMs": 5,
    # generous: the deadline must catch only the injected wedges, not
    # honest cold-compile slowness under 8-way thread contention
    "spark.rapids.tpu.watchdog.defaultDeadlineMs": 15000,
    "spark.rapids.memory.tpu.deviceLimitBytes": 1 << 16,
})
rng = np.random.default_rng(3)
pdf = pd.DataFrame({"k": rng.integers(0, 50, 4000),
                    "v": rng.normal(size=4000)})
df = (s.create_dataframe(pdf).group_by("k")
      .agg(F.sum(F.col("v")).alias("sv"),
           F.count(F.col("v")).alias("c")))
want = df.to_pandas().sort_values("k", ignore_index=True)
FLAVORS = {1: ("memory.oom", dict(count=8, all_threads=True)),
           # wedge LONGER than the 15s deadline so the timeout path is
           # genuinely exercised under concurrency: the trip must
           # cancel THIS client's token only (adds ~2x15s to the pass)
           3: ("memory.oom", dict(count=2, kind="delay", delay_s=20.0,
                                  all_threads=True)),
           5: ("spill.corrupt.host", dict(count=2, kind="corrupt",
                                          all_threads=True)),
           7: ("io.read", dict(count=2, all_threads=True))}
results, failures = {}, {}

def client(i):
    try:
        if i in FLAVORS:
            point, kw = FLAVORS[i]
            with I.scoped_rules(key=f"client{i}"):
                I.inject(point, **kw)
                got = df.to_pandas()
        else:
            got = df.to_pandas()
        results[i] = got.sort_values("k", ignore_index=True)
    except Exception as e:
        failures[i] = e

ts = [threading.Thread(target=client, args=(i,)) for i in range(8)]
[t.start() for t in ts]
[t.join() for t in ts]
for i in range(8):
    if i in results:
        pd.testing.assert_frame_equal(results[i], want)
    else:
        assert i in FLAVORS, f"clean client {i} failed: {failures[i]}"
        from spark_rapids_tpu.robustness.faults import classify
        assert classify(failures[i]).kind != "unknown", failures[i]
s.stop()
from spark_rapids_tpu.tools.eventlog import load_logs
app = load_logs(logdir)[0]
assert app.recovery == [], f"unattributed recovery: {app.recovery}"
assert app.corruption == [], f"unattributed corruption: {app.corruption}"
INJECTED = {"device_oom", "io_read", "spill_corruption", "timeout"}
dirty = [q.query_id for q in app.queries
         if q.recovery or q.corruption or q.budget]
for q in app.queries:
    kinds = {r.get("fault") for r in q.recovery}
    assert kinds <= INJECTED, (q.query_id, q.recovery)
clean_ok = [q.query_id for q in app.queries
            if q.succeeded and not q.recovery and not q.corruption
            and not q.watchdog and not q.budget]
assert len(clean_ok) >= 8 - len(FLAVORS) + 1, clean_ok
print(f"concurrent spray OK ({len(results)}/8 answered, "
      f"dirty queries={dirty}, maxConcurrent={app.max_concurrent()})")
PY

echo "== async exchange spray (2 concurrent clients, faults keyed per query, overlap + staging paths) =="
# Two client threads share one MESH session with the PR-9 data-movement
# features live (async exchange window + ragged slots, then host-RAM
# staging).  One client carries raise/delay rules scoped to ITS query on
# the async-exchange injection points; the other runs clean.  The gate:
# zero wrong results (both clients bit-identical to solo execution),
# zero unattributed robustness events, and the clean client's trail
# shows no recovery — cross-query interference is a failure.
python - <<'PY'
import tempfile
import threading

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.robustness import inject as I

rng = np.random.default_rng(9)
n = 4000
keys = np.where(rng.random(n) < 0.8, 1,
                rng.integers(0, 200, n)).astype(np.int64)
pdf = pd.DataFrame({"k": keys, "v": rng.normal(size=n)})
dim = pd.DataFrame({"k": np.arange(200, dtype=np.int64),
                    "w": rng.normal(size=200)})

def q(s):
    return (s.create_dataframe(pdf)
            .join(s.create_dataframe(dim), on="k")
            .group_by("k").agg(F.sum(F.col("v")).alias("sv"),
                               F.sum(F.col("w")).alias("sw"))
            .to_pandas().sort_values("k", ignore_index=True))

PASSES = [
    ("async+ragged", {
        "spark.rapids.tpu.exchange.async.enabled": True,
        "spark.rapids.tpu.shuffle.slot.ragged.enabled": True,
    }, [("exchange.async.resolve", dict(count=2, probability=0.7)),
        ("exchange.async.resolve", dict(count=1, kind="delay",
                                        delay_s=0.3)),
        ("dist.host_sync", dict(count=1, probability=0.5))]),
    ("host-staging", {
        "spark.rapids.tpu.exchange.hostStaging.thresholdBytes": 1,
    }, [("exchange.host_staging", dict(count=2, probability=0.7)),
        ("exchange.host_staging", dict(count=1, kind="delay",
                                       delay_s=0.3))]),
]
for name, extra, spray in PASSES:
    logdir = tempfile.mkdtemp(prefix="tpu-async-chaos-")
    s = TpuSession({
        "spark.rapids.tpu.eventLog.dir": logdir,
        "spark.rapids.sql.recovery.backoffMs": 5,
        "spark.rapids.sql.join.broadcastThresholdRows": 1,
        "spark.rapids.tpu.watchdog.defaultDeadlineMs": 15000,
        **extra}, mesh=make_mesh(8))
    want = q(s)  # solo warm-up is also the oracle
    results, failures = {}, {}

    def client(i):
        try:
            if i == 0:
                with I.scoped_rules(key="faulted"):
                    for point, kw in spray:
                        I.inject(point, seed=41 + i, **kw)
                    results[i] = q(s)
            else:
                results[i] = q(s)
        except Exception as e:  # noqa: BLE001 — gate below
            failures[i] = e

    ts = [threading.Thread(target=client, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not failures, f"{name}: {failures}"
    for i in range(2):
        pd.testing.assert_frame_equal(results[i], want)
    s.stop()
    from spark_rapids_tpu.tools.eventlog import load_logs
    app = load_logs(logdir)[0]
    assert app.recovery == [], f"unattributed recovery: {app.recovery}"
    dirty = [qq.query_id for qq in app.queries if qq.recovery]
    for qq in app.queries:
        kinds = {r.get("fault") for r in qq.recovery}
        assert kinds <= {"shuffle", "host_sync", "timeout"}, \
            (qq.query_id, qq.recovery)
    clean_ok = [qq.query_id for qq in app.queries
                if qq.succeeded and not qq.recovery
                and not qq.corruption]
    # warm-up + at least the clean client answered without recovery
    assert len(clean_ok) >= 2, (name, clean_ok, dirty)
    ov = s.exchange_overlap_metrics.snapshot()
    print(f"async exchange spray [{name}] OK (2 clients exact, "
          f"dirty={dirty}, async={int(ov['asyncExchanges'])} "
          f"staged={int(ov['hostStagedExchanges'])})")
PY

echo "== codec-corruption spray (compressed storage frames + wire dictionary, encoded knobs ON) =="
# ISSUE 11 gate: with every encoding knob on — compressed HOST spill
# frames (storage.hostCodec), encoded execution, and the compressed
# wire — bit flips in compressed spill/checkpoint/state frames and the
# wire dictionary-delta broadcast must degrade to recompute/decoded
# paths with typed events and EXACT results; never wrong bytes.
python - <<'PY'
import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.memory.spill import integrity_metrics
from spark_rapids_tpu.robustness import inject as I

# -- compressed spill frames --------------------------------------------
integrity_metrics.reset()
s = TpuSession({
    "spark.rapids.tpu.encoding.storage.hostCodec": "lz4",
    "spark.rapids.tpu.encoding.execution.enabled": True,
    "spark.rapids.memory.tpu.deviceLimitBytes": 65536,
    "spark.rapids.sql.recovery.backoffMs": 5,
})
rng = np.random.default_rng(3)
pdf = pd.DataFrame({"k": np.array(["g%02d" % v for v in
                                   rng.integers(0, 40, 6000)]),
                    "v": rng.normal(size=6000)})
df = (s.create_dataframe(pdf).group_by("k")
      .agg(F.sum(F.col("v")).alias("sv"), F.count(F.col("v")).alias("c")))
want = df.to_pandas().sort_values("k", ignore_index=True)
rules = []
try:
    # single-process spill tiers only here — compressed checkpoint and
    # incremental-state frames are sprayed by the continuous-ingest
    # soak above, whose session now runs the host codec
    for point in ("spill.corrupt.host", "spill.corrupt.disk"):
        rules.append(I.inject(point, kind="corrupt", count=3,
                              probability=0.7, seed=13,
                              all_threads=True))
    got = df.to_pandas().sort_values("k", ignore_index=True)
finally:
    for r in rules:
        I.remove(r)
pd.testing.assert_frame_equal(got, want)
corr = sum(integrity_metrics.snapshot().values())
assert corr >= 1, "no compressed-frame corruption was ever detected"
print("codec storage spray OK (compressed-frame corruptions "
      f"detected={corr}, recovery trail: "
      f"{[r['action'] for r in s.recovery_log]})")
s.stop()

# -- wire dictionary-delta broadcast ------------------------------------
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.parallel.shuffle import metrics_for_session

s = TpuSession({
    "spark.rapids.tpu.encoding.wire.enabled": True,
    "spark.rapids.sql.recovery.backoffMs": 5,
}, mesh=make_mesh(8))
df2 = (s.create_dataframe(pdf).group_by("k")
       .agg(F.sum(F.col("v")).alias("sv")))
# corrupt the FIRST launch's delta (it carries the full dictionary; a
# later launch's delta would be empty — nothing left to broadcast)
with I.scoped_rules():
    I.inject("shuffle.wire.dict", kind="corrupt", count=2,
             probability=1.0, seed=17, all_threads=True)
    got2 = df2.to_pandas().sort_values("k", ignore_index=True)
wm = metrics_for_session(s).snapshot()
assert wm["wireDictFallbacks"] >= 1, wm
want2 = df2.to_pandas().sort_values("k", ignore_index=True)
pd.testing.assert_frame_equal(got2, want2)
wm2 = metrics_for_session(s).snapshot()
assert wm2["encodedBytesSaved"] > wm["encodedBytesSaved"], \
    "post-corruption launch did not return to the encoded wire"
print("codec wire-dict spray OK (fallbacks="
      f"{wm['wireDictFallbacks']}, encoded wire re-armed)")
s.stop()
PY

echo "== tracing-on spray (raise/delay/corrupt with trace.dir set: results bit-identical, traces well-formed even for faulted queries, truncation marker honored at maxEvents=64) =="
# ISSUE 12 gate: the span runtime must be a pure observer.  The same
# spray as the hang/corruption pass runs with tracing ARMED and a tiny
# maxEvents bound; the answer must equal the tracing-off clean run,
# every exported trace (including the faulted attempts') must validate
# against the Chrome trace-event schema, and the bounded buffers must
# announce truncation explicitly.
python - <<'PY'
import glob
import os
import tempfile

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.robustness import inject as I
from spark_rapids_tpu.tools.traceview import load_trace, validate_chrome_trace
from spark_rapids_tpu.utils import tracing

rng = np.random.default_rng(0)
pdf = pd.DataFrame({"k": rng.integers(0, 50, 4000),
                    "v": rng.normal(size=4000)})
# many batches (4 files x 256-row reader batches) so one attempt
# yields well over 64 spans — maxEvents=64 must really truncate
ddir = tempfile.mkdtemp(prefix="tpu-trace-chaos-data-")
paths = []
for i in range(4):
    p = os.path.join(ddir, f"part-{i}.parquet")
    pdf.iloc[i * 1000:(i + 1) * 1000].to_parquet(p, index=False)
    paths.append(p)

def build(s):
    return (s.read.parquet(*paths)
            .filter(F.col("v") > -3.0)
            .group_by("k")
            .agg(F.sum(F.col("v")).alias("sv"),
                 F.count(F.col("v")).alias("c")))

# oracle: tracing OFF, no chaos
s0 = TpuSession({"spark.rapids.sql.reader.batchSizeRows": 256})
want = build(s0).to_pandas().sort_values("k", ignore_index=True)
s0.stop()

td = tempfile.mkdtemp(prefix="tpu-trace-chaos-")
s = TpuSession({
    "spark.rapids.tpu.trace.dir": td,
    "spark.rapids.tpu.trace.maxEvents": 64,
    "spark.rapids.sql.reader.batchSizeRows": 256,
    "spark.rapids.tpu.watchdog.defaultDeadlineMs": 500,
    "spark.rapids.memory.tpu.deviceLimitBytes": 65536,
    "spark.rapids.sql.recovery.backoffMs": 5,
})
df = build(s)
with I.scoped_rules():
    for point in I.injection_points():
        I.inject(point, kind="delay", delay_s=0.2, count=2,
                 probability=0.5, seed=7, all_threads=True)
    for point in ("spill.corrupt.host", "spill.corrupt.disk"):
        I.inject(point, kind="corrupt", count=2, probability=0.5,
                 seed=11, all_threads=True)
    got = df.to_pandas().sort_values("k", ignore_index=True)
pd.testing.assert_frame_equal(got, want)  # bit-identical under tracing
sp = s.last_span_stats
assert sp and sp["events"], sp
s.stop()
tracing.configure(enabled=False)
files = glob.glob(os.path.join(td, "*.json"))
assert files, "no trace files under chaos"
truncated = 0
for f in files:
    obj = load_trace(f)
    problems = validate_chrome_trace(obj)
    assert not problems, (f, problems)
    if obj.get("truncated"):
        truncated += 1
        assert any(e.get("name") == "trace-truncated"
                   for e in obj["traceEvents"]), f
assert truncated >= 1, \
    "maxEvents=64 under a recovery ladder never truncated"
print(f"tracing-on spray OK (exact results, {len(files)} trace(s) "
      f"well-formed, {truncated} truncated with marker, "
      f"recovery trail: {[r['action'] for r in s.recovery_log]})")
PY

echo "== shared-cache spray (8 clients, file mutation + corrupt/raise/delay on resultcache.load + shared-store restore: exact answers, zero stale reads) =="
# ISSUE 13 gate: with the fair interleaver + result cache + shared
# stage cache ON, 8 client threads hammer a shared store while (a)
# corrupt/raise/delay rules rot the resultcache.load and
# checkpoint.restore (shared-store restore) paths and (b) an input
# file is REWRITTEN between waves.  Every answer must exactly match
# the oracle for the file set it ran against — a degraded load is a
# recompute MISS, a moved fingerprint is an invalidation, NEVER stale
# bytes — and invalidations must actually fire (>= 1 per pass).
python - <<'PY'
import os
import tempfile
import threading

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.robustness import inject as I

ddir = tempfile.mkdtemp(prefix="tpu-shared-cache-data-")
path = os.path.join(ddir, "fact.parquet")

def write_fact(scale):
    rng = np.random.default_rng(23)
    pd.DataFrame({
        "k": rng.integers(0, 32, 4000).astype(np.int64),
        "v": rng.normal(size=4000) * scale,
    }).to_parquet(path)

def oracle():
    pdf = pd.read_parquet(path)
    pdf = pdf[pdf.v > -1.0]
    out = pdf.groupby("k", as_index=False).v.sum()
    out = out.rename(columns={"v": "sv"})
    return out.sort_values("k", ignore_index=True)

write_fact(1.0)
s = TpuSession({
    "spark.rapids.tpu.serving.interleave.enabled": True,
    "spark.rapids.tpu.serving.resultCache.enabled": True,
    "spark.rapids.tpu.serving.sharedStage.enabled": True,
    "spark.rapids.sql.recovery.backoffMs": 5,
}, mesh=make_mesh(8))

def query():
    return (s.read.parquet(path).filter(F.col("v") > -1.0)
            .group_by("k").agg(F.sum(F.col("v")).alias("sv")))

def wave(n=8, per_client=3):
    want = oracle()
    errors = []

    def client():
        try:
            for _ in range(per_client):
                got = query().to_pandas().sort_values(
                    "k", ignore_index=True)
                pd.testing.assert_frame_equal(got, want)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:1]

with I.scoped_rules():
    # rot both reuse load paths while the clients hammer the store
    I.inject("resultcache.load", kind="corrupt", count=2,
             probability=0.5, seed=29, all_threads=True)
    I.inject("resultcache.load", count=2, probability=0.3, seed=31,
             all_threads=True)
    I.inject("resultcache.load", kind="delay", delay_s=0.05, count=2,
             probability=0.3, seed=37, all_threads=True)
    I.inject("checkpoint.restore", kind="corrupt", count=2,
             probability=0.5, seed=41, all_threads=True)
    wave()
    # file MUTATION between waves: every post-mutation answer must
    # match the fresh oracle (fingerprint drift -> invalidation ->
    # recompute; a stale hit would fail the frame compare)
    write_fact(3.0)
    wave()
    write_fact(5.0)
    wave()

rc = s.result_cache.snapshot()
ss = s.shared_stages.snapshot()
assert rc["hits"] >= 1, rc
assert rc["invalidations"] >= 1, rc  # mutation + corrupt rules fired
assert ss["writes"] >= 1, ss
print("shared-cache spray OK (8 clients x 3 waves exact, "
      f"resultCache={rc}, sharedStages(writes={ss['writes']}, "
      f"splices={ss['resumes']}, invalid={ss['invalid']}))")
s.stop()
PY

echo "== cost-model spray (decisions on, corrupt store + raise/delay/corrupt over costmodel.load + exchange/read faults: answers bit-identical to knobs-off) =="
# ISSUE 15 gate: with spark.rapids.tpu.costModel.enabled the model
# decides every knob while (a) its evidence store starts CORRUPT, (b)
# raise/delay/corrupt rules rot every costmodel.load (evidence load +
# the QueryEnd ledger/persistence writes), and (c) exchange/read
# faults drive the recovery ladder mid-query — including through the
# model's own ReplanRequested path.  Every answer must be bit-
# identical to a knobs-off session's; a degraded load is built-in
# defaults with CostModelInvalid, never a failed or wrong query.
python - <<'PY'
import os
import tempfile

import numpy as np
import pandas as pd

import spark_rapids_tpu.plan.costmodel  # registers costmodel.load
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.robustness import inject as I

ddir = tempfile.mkdtemp(prefix="tpu-costmodel-data-")
store = tempfile.mkdtemp(prefix="tpu-costmodel-store-")
n = 512
rng = np.random.default_rng(17)
fact = pd.DataFrame({"a": np.arange(n, dtype=np.int64),
                     "j": np.zeros(n, dtype=np.int64),
                     "x": rng.uniform(size=n)})
paths = []
for i in range(8):
    p = os.path.join(ddir, f"fact-{i}.parquet")
    fact.iloc[i * n // 8:(i + 1) * n // 8].to_parquet(p, index=False)
    paths.append(p)
dim = pd.DataFrame({"j": np.arange(16, dtype=np.int64),
                    "w": np.arange(16) * 1.5})

def queries(s):
    f = s.read.parquet(*paths)
    d = s.create_dataframe(dim)
    agg = f.groupBy("a").agg(F.max("j").alias("j"),
                             F.sum("x").alias("sx"))
    skew_join = agg.join(d, "j")          # skewed: replan territory
    grand = f.filter(F.col("x") > 0.1).agg(F.sum("x").alias("t"))
    return [("join", skew_join, ["a"]), ("agg", grand, ["t"])]

conf = {"spark.rapids.sql.join.broadcastThresholdRows": 4,
        "spark.rapids.sql.recovery.backoffMs": 5}
off = TpuSession(dict(conf), mesh=make_mesh(8))
want = {name: q.to_pandas().sort_values(keys, ignore_index=True)
        for name, q, keys in queries(off)}
off.stop()

# CORRUPT store from the start: a torn record plus valid lines
with open(os.path.join(store, "observations.jsonl"), "w") as fh:
    fh.write('{"site": "cm:aa", "rows": 64, "skew": 0.5}\n'
             '{"site": "cm:bb", "ro')

with I.scoped_rules():
    # corrupt applies at the construction-time fire_mutate (the ONLY
    # mutate site): the evidence bytes rot on top of the torn line;
    # the raise rule skips that load so it lands on the first
    # QueryEnd ledger/persistence write instead, and delays cover
    # later writes — every costmodel.load flavor really executes
    I.inject("costmodel.load", kind="corrupt", count=1,
             all_threads=True)
    I.inject("costmodel.load", count=1, skip=1, all_threads=True)
    I.inject("costmodel.load", kind="delay", delay_s=0.05, count=2,
             skip=2, all_threads=True)
    I.inject("shuffle.exchange", count=1, skip=2, all_threads=True)
    I.inject("io.read", count=1, skip=12, all_threads=True)
    s = TpuSession(dict(conf, **{
        "spark.rapids.tpu.costModel.enabled": True,
        "spark.rapids.tpu.costModel.dir": store,
    }), mesh=make_mesh(8))
    assert s.cost_model.invalid_loads >= 1, "corrupt load undetected"
    for round_ in range(2):  # round 2 runs on converged evidence
        for name, q, keys in queries(s):
            got = q.to_pandas().sort_values(keys, ignore_index=True)
            pd.testing.assert_frame_equal(
                got[want[name].columns], want[name],
                check_dtype=False)
    # both degrade legs fired: the corrupt/torn evidence LOAD and the
    # raise on a QueryEnd ledger write
    assert s.cost_model.invalid_loads >= 2, s.cost_model.invalid_loads
    print("cost-model spray OK (2 rounds exact, "
          f"invalid={s.cost_model.invalid_loads}, "
          f"replans={s.cost_model.replan_count}, "
          f"recovery={[r['fault'] for r in s.recovery_log]})")
    s.stop()
PY

echo "== fleet soak: 8 standing subscribers, shared-ingest rounds under kill/delay/corrupt spray (exactly-once sinks, bit-identical answers, fault isolation) =="
# ISSUE 16: an 8-subscriber fleet (4 join-enrich, 2 windowed with
# DIFFERENT watermark delays, 2 plain aggregates) ticks shared-ingest
# rounds while raise/delay/corrupt rules spray every surface a round
# crosses — the source read, exchanges, state write/restore,
# checkpoint restore, and the NEW incremental.sink.commit window
# between compute and epoch commit.  Gates: every committed tick's
# answer is bit-identical to its one-shot oracle (the windowed ones
# under their OWN committed watermark); every committed epoch emitted
# its SinkCommit exactly once (replays re-emit the same epoch,
# flagged; the eventlog health check proves zero duplicates); a
# faulted subscriber's co-subscribers commit clean answers in the
# same round and the faulted one catches up from its backlog on the
# next round.
python - <<'PY'
import os
import shutil
import tempfile

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.memory import retry as _retry  # registers memory.oom
from spark_rapids_tpu.robustness import inject as I
from spark_rapids_tpu.robustness import incremental as _inc  # registers points
from spark_rapids_tpu.robustness.incremental import incremental_metrics

ROUNDS = 6
SPRAY = (("io.read", dict(kind="raise", count=2, probability=0.3)),
         ("shuffle.exchange", dict(kind="raise", count=2,
                                   probability=0.3)),
         ("shuffle.exchange", dict(kind="delay", delay_s=0.2, count=1,
                                   probability=0.2)),
         ("incremental.state.write", dict(kind="raise", count=1,
                                          probability=0.25)),
         ("incremental.state.restore", dict(kind="corrupt", count=1,
                                            probability=0.25)),
         ("checkpoint.restore", dict(kind="corrupt", count=1,
                                     probability=0.2)),
         # the exactly-once window: kill between compute and commit,
         # and rot the staged payload so the CRC gate must catch it
         ("incremental.sink.commit", dict(kind="raise", count=1,
                                          probability=0.35)),
         ("incremental.sink.commit", dict(kind="corrupt", count=1,
                                          probability=0.35)))

d = tempfile.mkdtemp(prefix="tpu-fleet-soak-")
logdir = os.path.join(d, "events")
rng = np.random.default_rng(23)

# ONE append-only stream all 8 subscribers share: k/v for the join
# and plain-agg shapes, event-time ts for the windowed ones (each
# round's file lives in that round's 10-minute bucket)
def write(i, tick):
    n = 3000
    pdf = pd.DataFrame({
        "k": rng.integers(0, 40, n),
        "v": rng.integers(0, 1000, n).astype(np.float64),
        "ts": pd.to_datetime("2024-01-01") + pd.to_timedelta(
            tick * 600 + rng.integers(0, 600, n), unit="s")})
    p = os.path.join(d, f"b{i:03d}.parquet")
    pdf.to_parquet(p, index=False)
    return p

s = TpuSession({"spark.rapids.sql.recovery.backoffMs": 5,
                "spark.rapids.tpu.watchdog.defaultDeadlineMs": 15000,
                "spark.rapids.tpu.eventLog.dir": logdir,
                # cross-subscriber splices ride the epoch tier
                "spark.rapids.tpu.serving.sharedStage.enabled": True},
               mesh=make_mesh(8))
incremental_metrics.reset()

dim = pd.DataFrame({"k": np.arange(40),
                    "w": (np.arange(40) % 7 + 1).astype(np.float64)})
pdim = os.path.join(d, "dim.parquet")
dim.to_parquet(pdim, index=False)

fact0 = write(0, 0)
fleet = s.fleet()
dfs, wdfs = {}, {}
for i in range(4):  # join-enrich subscribers share the dim subtree
    dim_agg = (s.read.parquet(pdim).groupBy("k")
               .agg(F.max("w").alias("w")))
    dfs[f"j{i}"] = (s.read.parquet(fact0).join(dim_agg, "k")
                    .groupBy("k")
                    .agg(F.sum((F.col("v") * F.col("w")).alias("vw"))
                         .alias("sx"),
                         F.count("v").alias("c")).orderBy("k"))
    fleet.subscribe(dfs[f"j{i}"], name=f"j{i}", fact=fact0)
for i, delay in ((0, 1_200_000), (1, 3_600_000)):  # independent horizons
    wdfs[f"w{i}"] = (s.read.parquet(fact0)
                     .groupBy(F.window("ts", "10 minutes"), "k")
                     .agg(F.sum("v").alias("sv"),
                          F.count("v").alias("c"))
                     .orderBy("window.start", "k"))
    fleet.subscribe(wdfs[f"w{i}"], name=f"w{i}",
                    watermark_delay_ms=delay)
for i in range(2):
    dfs[f"a{i}"] = (s.read.parquet(fact0).groupBy("k")
                    .agg(F.sum("v").alias("sv"),
                         F.count("v").alias("c"),
                         F.avg("v").alias("av")).orderBy("k"))
    fleet.subscribe(dfs[f"a{i}"], name=f"a{i}")

fleet.tick()  # cold epochs, no chaos

# per-subscriber exactly-once ledger: committed epoch -> payload crc
ledger = {n: {} for n in fleet.subscribers}
raised = retried = 0
try:
    for t in range(ROUNDS):
        p = write(1 + t, 1 + t)  # the round's ONE appended file
        with I.scoped_rules():
            for point, kw in SPRAY:
                I.inject(point, seed=300 + t, all_threads=True, **kw)
            commits = fleet.tick([p])
        info = dict(fleet.last_round_info)

        def record(batch):
            for n, sc in batch.items():
                if sc is None:
                    continue
                led = ledger[n]
                if sc.replayed:  # sanctioned: SAME epoch, SAME crc
                    assert led.get(sc.epoch) == sc.crc, (n, sc)
                else:  # a NEW emission rides a NEVER-emitted epoch
                    assert sc.epoch not in led, (n, sc, sorted(led))
                    led[sc.epoch] = sc.crc

        record(commits)
        if info["failures"]:
            # isolation gate: a faulted subscriber is ALONE — every
            # co-subscriber still committed this round
            raised += info["failures"]
            for n, sc in commits.items():
                assert (sc is None) == \
                    (n in fleet.last_round_errors), (n, info)
            # catch-up round, chaos disarmed: backlogged files
            # re-offer and the faulted subscribers re-ingest
            commits = fleet.tick()
            retried += 1
            assert not fleet.last_round_errors, fleet.last_round_errors
            record(commits)
        for n, sc in commits.items():
            assert sc is not None, (n, info)
        # bit-identical gate: every subscriber's committed answer is
        # its one-shot recompute oracle, chaos disarmed (the runners
        # keep each standing df's scan in step)
        for n, df in dfs.items():
            pd.testing.assert_frame_equal(
                commits[n].df.to_pandas(), df.to_pandas())
        for n, df in wdfs.items():
            h = fleet._handles[n]
            wm = h.runner.last_tick_info["watermark"]
            pd.testing.assert_frame_equal(
                commits[n].df.to_pandas(),
                df.filter(
                    F.col("window.end").isNull() |
                    (F.col("window.end") > pd.Timestamp(wm, unit="us"))
                ).to_pandas())
    # the two windowed subscribers evicted on their OWN schedules
    tight = fleet._handles["w0"].runner.store
    loose = fleet._handles["w1"].runner.store
    assert tight.state_watermark > loose.state_watermark
    # every subscriber holds at most one sink record per committed
    # data round (replay rounds added none)
    for n in fleet.subscribers:
        st = fleet._handles[n].runner.store
        assert len(st._sink) <= 1 + ROUNDS, (n, sorted(st._sink))
finally:
    fleet.close()
    s.stop()
    m = incremental_metrics.snapshot()

# eventlog health: the duplicate-emission detector stayed quiet over
# the WHOLE soak trail (and the sink/fleet rollups flowed through)
from spark_rapids_tpu.tools.eventlog import load_logs
from spark_rapids_tpu.tools.profiling import (_incremental_problems,
                                              incremental_stats)
apps = load_logs(logdir)
stats = incremental_stats(apps)
assert stats["sink_commits"] >= 8 * (1 + ROUNDS) - ROUNDS, stats
assert stats["fleet_rounds"] >= 1 + ROUNDS, stats
for a in apps:
    evs = list(a.incremental) + [e for q in a.queries
                                 for e in q.incremental]
    dups = [p for p in _incremental_problems(a.session_id, evs)
            if "duplicate sink emission" in p]
    assert not dups, dups
shutil.rmtree(d, ignore_errors=True)
print(f"fleet soak OK ({ROUNDS} chaos rounds x 8 subscribers exact, "
      f"faulted+retried={raised}/{retried}, "
      f"sinkCommits={m['sinkCommits']} sinkReplays={m['sinkReplays']} "
      f"rollbacks={m['rollbacks']} "
      f"sourcePulls={stats['fleet_source_pulls']} "
      f"splices={stats['fleet_splices']})")
PY

echo "== template spray (prepared statements + template cache under corrupt/raise/delay on templatecache.load: exact answers, zero planning passes, rot invalidates then re-stores) =="
# ISSUE 17 gate: a prepared handle serves randomized literal bindings
# while corrupt/raise/delay rules rot every templatecache.load.  A
# degraded load is a recompute MISS on the handle's cached physical
# plan — never a wrong answer, never a failed query, and never a
# planning pass (prepare paid for planning once; cache rot must not
# smuggle one back in).  Corruption must actually land (CRC-gated
# invalidations >= 1) and the clean wave after the spray must hit
# again (rot evicts entries, it does not poison the tier).
python - <<'PY'
import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.plan import overrides as OV
from spark_rapids_tpu.robustness import inject as I

rng = np.random.default_rng(7)
pdf = pd.DataFrame({"k": rng.integers(0, 16, 4000),
                    "v": rng.normal(size=4000),
                    "q": rng.uniform(1.0, 50.0, 4000)})
s = TpuSession({
    "spark.rapids.tpu.template.enabled": True,
    "spark.rapids.tpu.serving.resultCache.enabled": True,
    "spark.rapids.tpu.template.resultCache.enabled": True,
    "spark.rapids.sql.recovery.backoffMs": 5,
})
df = (s.create_dataframe(pdf)
      .filter((F.col("q") >= F.lit(5.0)) & (F.col("q") < F.lit(20.0)))
      .select((F.col("v") * F.col("q")).alias("rev"))
      .agg(F.sum(F.col("rev")).alias("revenue")))
h = s.prepare(df)
assert h.param_count == 2 and not h.refusals, h.describe()
VECTORS = [(5.0, 20.0), (7.5, 30.0), (2.0, 44.0), (11.0, 13.0)]
# warm wave: each binding computes once and stores a template entry
want = {vec: h.run(*vec) for vec in VECTORS}
p0 = OV.planning_passes()
with I.scoped_rules():
    I.inject("templatecache.load", kind="corrupt", count=3,
             probability=0.6, seed=43, all_threads=True)
    I.inject("templatecache.load", count=2, probability=0.4, seed=47,
             all_threads=True)
    I.inject("templatecache.load", kind="delay", delay_s=0.2, count=2,
             probability=0.4, seed=53, all_threads=True)
    for _ in range(2):
        for vec in VECTORS:
            assert h.run(*vec) == want[vec], vec
snap = s.result_cache.snapshot()
assert snap["templateHits"] >= 1, snap
assert snap["invalidations"] >= 1, "corrupt rule never rotted a load"
assert OV.planning_passes() == p0, \
    "cache rot smuggled a planning pass into a prepared repeat"
# clean wave: rot-invalidated entries were re-stored and hit again
for vec in VECTORS:
    assert h.run(*vec) == want[vec], vec
snap2 = s.result_cache.snapshot()
assert snap2["templateHits"] > snap["templateHits"], (snap, snap2)
s.stop()
print("template spray OK (4 bindings x 3 waves exact, "
      f"templateHits={snap2['templateHits']} "
      f"templateStores={snap2['templateStores']} "
      f"invalidations={snap2['invalidations']}, planning passes 0)")
PY

echo "== multi-host fleet spray (logical-host fleet, injected host loss + host_sync delays: shrink-rung recovery bit-identical, co-hosted queries clean, stale writer fenced) =="
# ISSUE 18 gate: a 2-host logical fleet (8-device mesh partitioned by
# fleet.logicalHosts, real HostMembership registry) loses a host
# mid-query — an injected HostLossFault on the fleet.heartbeat point,
# with bounded delays sprayed on dist.host_sync — and must recover
# through the ladder's SHRINK rung: mesh rebuilt over the survivors,
# answer bit-identical to the clean full-fleet run.  Co-hosted clean
# queries are counter-pinned at ZERO attributed recovery events, zero
# robustness events float unattributed, and a zombie writer still
# holding the pre-shrink fence token is REJECTED by the fleet cache
# (entry never written, FleetCacheFence health trail recorded).
python - <<'PY'
import tempfile

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.robustness import inject as I

logdir = tempfile.mkdtemp(prefix="tpu-fleet-chaos-events-")
s = TpuSession({
    "spark.rapids.sql.distributed.numShards": "8",
    "spark.rapids.tpu.fleet.logicalHosts": "2",
    "spark.rapids.tpu.fleet.membershipDir":
        tempfile.mkdtemp(prefix="tpu-fleet-chaos-members-"),
    "spark.rapids.tpu.fleet.cache.dir":
        tempfile.mkdtemp(prefix="tpu-fleet-chaos-cache-"),
    # un-rate-limit the heartbeat so the injected loss lands on the
    # query path's first membership check
    "spark.rapids.tpu.fleet.heartbeatMs": 1,
    "spark.rapids.tpu.eventLog.dir": logdir,
    "spark.rapids.sql.recovery.backoffMs": 5,
})
rng = np.random.default_rng(19)
pdf = pd.DataFrame({"k": rng.integers(0, 50, 4000),
                    "v": rng.normal(size=4000)})
df = (s.create_dataframe(pdf).group_by("k")
      .agg(F.sum(F.col("v")).alias("sv"),
           F.count(F.col("v")).alias("c")))
want = df.to_pandas().sort_values("k", ignore_index=True)
assert s.mesh.devices.size == 8
stale_tok = s.fleet_epoch  # the token a zombie would still hold
s.recovery_log.clear()
with I.scoped_rules():
    I.inject("fleet.heartbeat", count=1, all_threads=True)
    I.inject("dist.host_sync", kind="delay", delay_s=0.2, count=2,
             probability=0.5, seed=61, all_threads=True)
    got = df.to_pandas().sort_values("k", ignore_index=True)
pd.testing.assert_frame_equal(got, want)  # survivor bit-identical
actions = [r["action"] for r in s.recovery_log]
assert "shrink" in actions, actions
assert s.mesh.devices.size == 4, "mesh did not shrink to survivors"
# co-hosted clean queries: ZERO new attributed recovery events
n_events = len(s.recovery_log)
again = df.to_pandas().sort_values("k", ignore_index=True)
pd.testing.assert_frame_equal(again, want)
assert len(s.recovery_log) == n_events, s.recovery_log[n_events:]
# the zombie's publish: pre-shrink fence token, REJECTED + never read
assert not s.fleet_cache.publish("zombie-entry", {"x": 1}, stale_tok)
assert s.fleet_cache.counters["fenced"] == 1
assert s.fleet_cache.lookup("zombie-entry") is None
s.stop()
from spark_rapids_tpu.tools.eventlog import load_logs
app = load_logs(logdir)[0]
assert app.recovery == [], f"unattributed recovery: {app.recovery}"
for q in app.queries:
    kinds = {r.get("fault") for r in q.recovery}
    assert kinds <= {"host_loss"}, (q.query_id, q.recovery)
fleet_kinds = [e["kind"] for e in app.fleet]
for k in ("join", "shrink", "fence"):
    assert k in fleet_kinds, fleet_kinds
print("multi-host fleet spray OK (shrink recovery exact, "
      f"trail={actions}, fleet events={fleet_kinds}, "
      f"fenced={s.fleet_cache.counters['fenced']})")
PY

echo "== fail-slow spray (gray failure: one slow host, sub-deadline delays -> hedge + quarantine/rejoin, bit-identical) =="
# fail-SLOW, not fail-stop: host 1's staging/host_sync walls stretch via
# sub-hard-deadline delay rules and gossiped slow walls — no heartbeat
# loss ever trips.  Gates: every query bit-identical to the clean run,
# the mitigation rungs actually fire (hedge AND quarantine->rejoin),
# and co-hosted clean queries attribute ZERO recovery entries (a hedge
# is not a fault; the ladder stays silent throughout).
python - <<'PY'
import time

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.robustness import grayfailure as gf
from spark_rapids_tpu.robustness import inject as I

s = TpuSession({
    "spark.rapids.sql.distributed.numShards": "8",
    "spark.rapids.tpu.fleet.logicalHosts": "2",
    "spark.rapids.tpu.fleet.grayFailure.enabled": True,
    "spark.rapids.tpu.fleet.suspectWindow": 8,
    "spark.rapids.tpu.fleet.quarantineAfterMs": 30,
    "spark.rapids.tpu.fleet.rejoinAfterMs": 30,
    "spark.rapids.tpu.fleet.hedgeFloorMs": 25,
    "spark.rapids.tpu.exchange.hostStaging.thresholdBytes": 1,
    "spark.rapids.sql.join.broadcastThresholdRows": 1,
    # logical hosts auto-pick the DCN gather strategy, which never
    # host-stages; pin the ICI collective so the hedgeable tier runs
    "spark.rapids.tpu.shuffle.topology.strategy": "all_to_all",
    "spark.rapids.sql.recovery.backoffMs": 1,
})
rng = np.random.default_rng(23)
fact = pd.DataFrame({"k": rng.integers(0, 300, 4000),
                     "v": rng.normal(size=4000)})
dim = pd.DataFrame({"k": np.arange(300), "w": rng.normal(size=300)})

def q():
    return (s.create_dataframe(fact)
            .join(s.create_dataframe(dim), on="k")
            .group_by("k")
            .agg(F.sum(F.col("v")).alias("sv"),
                 F.sum(F.col("w")).alias("sw"))
            .to_pandas().sort_values("k", ignore_index=True))

want = q()  # clean oracle (already on the staging path)
assert s.exchange_overlap_metrics.snapshot()["hostStagedExchanges"] >= 2

t = s.gray_health
# host 1 turns fail-slow: its gossiped beat walls stretch 10x on every
# evidence point while host 0 stays at fleet speed — the exact payload
# a degraded peer's beat records would carry
rules = []
try:
    for _ in range(8):
        t.observe_wall(0, "exchange.host_staging", 10.0)
        t.observe_wall(0, "dist.host_sync", 5.0)
        t.observe_peer_walls(1, {"exchange.host_staging": 100.0,
                                 "dist.host_sync": 50.0})
    t.observe_beat(1, 1000.0)
    t.observe_beat(1, 1000.9)  # stretched beat interval, NOT silence
    t.poll()
    assert t.is_suspect(1), t.state
    # sub-hard-deadline wedges on the sick host's staging/sync writes
    # (far below any watchdog deadline: these are delays, not hangs)
    rules.append(I.inject("exchange.host_staging", kind="delay",
                          delay_s=0.4, count=1))
    rules.append(I.inject("dist.host_sync", kind="delay",
                          delay_s=0.05, count=2, probability=0.5,
                          seed=3, all_threads=True))
    got = q()  # hedged: healthy re-dispatch answers
    pd.testing.assert_frame_equal(got, want)
    c = t.query_counters()
    assert c["hedgesFired"] >= 1 and c["hedgesWon"] >= 1, c
    time.sleep(0.05)  # outlast quarantineAfterMs
    got = q()  # boundary drains the sick host (soft shrink)
    pd.testing.assert_frame_equal(got, want)
    assert int(s.mesh.devices.size) == 4, s.mesh.devices.size
    assert t.state[1] == gf.QUARANTINED
    assert 1 not in s.fleet_membership.lost  # slow, never judged lost
    # the host recovers: its gossiped walls come back to the fleet's
    # OWN observed medians on every evidence point (one still-slow
    # point would keep the score pinned) -> rejoin at the next boundary
    for _ in range(8):
        t.observe_peer_walls(1, t.local_walls())
    t.poll()
    time.sleep(0.05)
    got = q()
    pd.testing.assert_frame_equal(got, want)
    assert int(s.mesh.devices.size) == 8, s.mesh.devices.size
finally:
    for r in rules:
        I.remove(r)
# co-hosted clean queries: ZERO attributed recovery entries — the
# whole fail-slow story ran without ever engaging the fault ladder.
# Both TPC-H shapes: the join+group-by (q3-like) and a
# filter+aggregate (q6-like) on the restored full mesh.
assert s.recovery_log == [], s.recovery_log
again = q()
pd.testing.assert_frame_equal(again, want)
q6 = (s.create_dataframe(fact).filter(F.col("v") >= 0.0)
      .group_by("k").agg(F.sum(F.col("v")).alias("rev"))
      .to_pandas().sort_values("k", ignore_index=True))
q6_want = (fact[fact["v"] >= 0.0].groupby("k", as_index=False)
           .agg(rev=("v", "sum")).sort_values("k", ignore_index=True))
pd.testing.assert_frame_equal(q6, q6_want, check_dtype=False)
assert s.recovery_log == [], s.recovery_log
cc = t.query_counters()
print("fail-slow spray OK (hedges "
      f"{cc['hedgesFired']}/{cc['hedgesWon']}, quarantines "
      f"{cc['quarantines']}, rejoins {cc['rejoins']}, ladder silent)")
s.stop()
PY

echo "CHAOS OK"
