#!/usr/bin/env bash
# Chaos gate: run the fault-injection suite standalone so the injection
# points and the recovery ladder cannot silently rot (tests/test_chaos.py
# arms every named point in robustness/inject.py and requires the query
# to answer with clean-run results).  CPU-only — the virtual 8-device
# mesh exercises the distributed demotion rungs without TPU hardware.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_ENABLE_X64=1
export XLA_FLAGS="--xla_force_host_platform_device_count=8 --xla_cpu_enable_fast_math=false ${XLA_FLAGS:-}"

echo "== chaos suite (fault injection + recovery ladder) =="
python -m pytest tests/ -q -m chaos --maxfail=5

echo "CHAOS OK"
