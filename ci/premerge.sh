#!/usr/bin/env bash
# Local premerge runner — the same gate as .github/workflows/ci.yml for
# environments without GitHub runners (reference analog:
# jenkins/spark-premerge-build.sh:31-52).  Fails on: any test failure,
# generated-doc drift, or public-API manifest drift.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_ENABLE_X64=1
export XLA_FLAGS="--xla_force_host_platform_device_count=8 --xla_cpu_enable_fast_math=false ${XLA_FLAGS:-}"

echo "== unit tests (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q --maxfail=20 -m 'not chaos'

echo "== chaos suite (fault injection + recovery ladder + hang/corruption + concurrent spray w/ isolation gate) =="
bash ci/chaos.sh

echo "== perf smoke (deterministic budgets: host-sync counts + shuffle collective-count — packed q3-shape exchange <= 3 all_to_all vs >= 8 unpacked; no timing) =="
python -m pytest tests/ -q -m perf --maxfail=5

echo "== trace-validation smoke (distributed TPC-H q3 with tracing on: export parses, rollup sums within wall, unattributed < 20%, span-derived overlap matches exchangeOverlapMs) =="
python - <<'PY'
import glob
import os
import tempfile

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.models import tpch
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.tools.traceview import (load_trace, summarize,
                                              validate_chrome_trace)

td = tempfile.mkdtemp(prefix="tpu-trace-smoke-")
s = TpuSession({"spark.rapids.tpu.trace.dir": td,
                "spark.rapids.tpu.exchange.async.enabled": True},
               mesh=make_mesh(8))
q3 = tpch.q3(tpch.load(s, tpch.gen_tables(sf=0.01)))
rows = q3.to_pandas()
assert len(rows), "q3 returned nothing"
sp = s.last_span_stats
assert sp and sp["events"], sp
# the exclusive-time rollup must sum WITHIN the wall budget (spans on
# the single distributed driving thread cannot attribute more time
# than the envelope measured) and cover >= 80% of it
assert sp["exclusiveMs"] <= sp["wallMs"] * 1.05, sp
assert sp["unattributedFrac"] < 0.20, sp
# the PR9 overlap number, reproduced from spans alone (within 10%)
sh = s.last_shuffle_stats or {}
ov = sh.get("exchangeOverlapMs", 0.0)
assert ov > 0, sh
assert abs(sp["overlapMs"] - ov) <= 0.10 * ov + 0.5, (sp["overlapMs"], ov)
files = glob.glob(os.path.join(td, "*.json"))
assert files, "no trace exported"
for f in files:
    problems = validate_chrome_trace(load_trace(f))
    assert not problems, (f, problems)
s.stop()
print(summarize(load_trace(files[-1]), top=6))
print(f"trace smoke OK (unattributed={sp['unattributedFrac']:.1%}, "
      f"span overlap={sp['overlapMs']:.1f}ms vs metric {ov:.1f}ms, "
      f"{len(files)} file(s) valid)")
PY

echo "== cost-model zero-conf smoke (reduced TPC-H A/B: hand-tuned confs vs every tuned conf unset + costModel on — every answer matched, decisions ledgered; bench.py --zero-conf runs the full sweep) =="
BENCH_ZERO_CONF_QUERIES="q1,q3,q6" python bench.py --zero-conf

echo "== docgen drift check =="
tmp=$(mktemp -d)
python -m spark_rapids_tpu.tools.docgen "$tmp"
diff -u docs/configs.md "$tmp/configs.md"
diff -u docs/supported_ops.md "$tmp/supported_ops.md"
rm -rf "$tmp"

echo "== API manifest audit =="
python -m spark_rapids_tpu.tools.api_validation

echo "== driver entry compile check =="
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
g.dryrun_multichip(8)
print("entry + dryrun_multichip OK")
PY

echo "PREMERGE OK"
