#!/usr/bin/env bash
# Local premerge runner — the same gate as .github/workflows/ci.yml for
# environments without GitHub runners (reference analog:
# jenkins/spark-premerge-build.sh:31-52).  Fails on: any test failure,
# generated-doc drift, or public-API manifest drift.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_ENABLE_X64=1
export XLA_FLAGS="--xla_force_host_platform_device_count=8 --xla_cpu_enable_fast_math=false ${XLA_FLAGS:-}"

echo "== unit tests (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q --maxfail=20 -m 'not chaos'

echo "== chaos suite (fault injection + recovery ladder + hang/corruption + concurrent spray w/ isolation gate) =="
bash ci/chaos.sh

echo "== perf smoke (deterministic budgets: host-sync counts + shuffle collective-count — packed q3-shape exchange <= 3 all_to_all vs >= 8 unpacked; no timing) =="
python -m pytest tests/ -q -m perf --maxfail=5

echo "== docgen drift check =="
tmp=$(mktemp -d)
python -m spark_rapids_tpu.tools.docgen "$tmp"
diff -u docs/configs.md "$tmp/configs.md"
diff -u docs/supported_ops.md "$tmp/supported_ops.md"
rm -rf "$tmp"

echo "== API manifest audit =="
python -m spark_rapids_tpu.tools.api_validation

echo "== driver entry compile check =="
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
g.dryrun_multichip(8)
print("entry + dryrun_multichip OK")
PY

echo "PREMERGE OK"
