"""Cross-process file locks with crashed-holder reaping.

The ObservationStore introduced the repo's lock-file discipline
(O_CREAT|O_EXCL beside the protected file, stale break by atomic
rename); the fleet-scoped caches (serving/fleetcache.py) generalize it
to shared storage that many hosts mutate.  This module is the one
implementation both use, hardened against the failure mode the original
left open: a kill-9'd merger's lock file wedged the next writer until
the 30s mtime-staleness window expired.  Locks here are **pid-stamped**
— the holder writes its pid into the lock file at acquire, and a waiter
that finds the holder's pid dead reaps the lock immediately (atomic
rename, exactly one reaper wins) instead of waiting out the window.
The mtime window remains as the fallback for unreadable/empty stamps
and for holders on OTHER machines (a shared-filesystem fleet cannot
probe a remote pid; the stamp records host+pid so same-host death is
still provable).

Acquire polls with **jittered exponential backoff** (not the fixed
10ms spin the ObservationStore used): N processes all hammering one
lock after a fleet-wide event de-synchronize instead of retrying in
lockstep.
"""

from __future__ import annotations

import json
import os
import random
import socket
import time
from typing import Optional

_BACKOFF_START_S = 0.002
_BACKOFF_CAP_S = 0.05


def _pid_alive(pid: int) -> bool:
    """Is ``pid`` a live process on THIS host?  Signal 0 probes without
    delivering; EPERM means alive-but-not-ours."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return True  # unknowable: never reap on doubt


class InterProcessLock:
    """Advisory cross-process lock file.

    ``acquire(timeout_s)`` returns True when held; ``release()`` must
    follow (use as a context manager for scoped regions).  Best-effort
    by design — callers treat a failed acquire as "skip/retry later",
    never as corruption: every protected artifact is independently
    verified (CRC) by its readers.
    """

    def __init__(self, path: str, stale_s: float = 30.0,
                 seed: Optional[int] = None):
        self.path = path
        self.stale_s = stale_s
        self._rng = random.Random(
            seed if seed is not None else (os.getpid() << 16) ^ id(self))
        self._held = False

    # ------------------------------------------------------------- stamping --
    def _stamp(self, fd: int) -> None:
        """Write the holder's identity into the lock file so waiters
        can prove a same-host holder dead and reap immediately."""
        try:
            os.write(fd, json.dumps(
                {"pid": os.getpid(),
                 "host": socket.gethostname()}).encode())
        except OSError:
            pass  # an unstamped lock still works via the mtime window

    def _holder_dead(self) -> bool:
        """True when the lock's stamp names a provably-dead same-host
        holder.  Unreadable/foreign stamps return False — the mtime
        staleness window handles those."""
        try:
            with open(self.path, encoding="utf-8") as f:
                stamp = json.loads(f.read() or "{}")
        except (OSError, ValueError):
            return False
        if stamp.get("host") != socket.gethostname():
            return False
        try:
            return not _pid_alive(int(stamp.get("pid", 0)))
        except (TypeError, ValueError):
            return False

    def _reap(self) -> None:
        """Break the lock by atomic rename: exactly one reaper wins the
        rename, so two waiters can never each unlink the other's
        freshly re-created lock and both enter the critical section."""
        tomb = f"{self.path}.stale.{os.getpid()}"
        os.rename(self.path, tomb)
        os.unlink(tomb)

    # -------------------------------------------------------------- acquire --
    def acquire(self, timeout_s: float = 2.0) -> bool:
        deadline = time.monotonic() + timeout_s
        backoff = _BACKOFF_START_S
        while True:
            try:
                fd = os.open(self.path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                try:
                    self._stamp(fd)
                finally:
                    os.close(fd)
                try:
                    # anchor the staleness window to THIS acquire (the
                    # create time could predate a queued wait on some
                    # filesystems)
                    os.utime(self.path)
                except OSError:
                    pass
                self._held = True
                return True
            except FileExistsError:
                try:
                    if self._holder_dead():
                        # crashed same-host holder: reap NOW — this is
                        # the kill-9'd-merger case the mtime window
                        # made every waiter sit out
                        self._reap()
                        continue
                    if time.time() - os.path.getmtime(self.path) > \
                            self.stale_s:
                        self._reap()
                        continue
                except OSError:
                    continue  # lock vanished / another reaper won
                if time.monotonic() >= deadline:
                    return False
                # jittered exponential backoff: a herd of waiters
                # de-synchronizes instead of polling in lockstep
                time.sleep(backoff * (0.5 + 0.5 * self._rng.random()))
                backoff = min(backoff * 2, _BACKOFF_CAP_S)
            except OSError:
                return False  # unwritable dir: no lock to be had

    def release(self) -> None:
        self._held = False
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __enter__(self) -> "InterProcessLock":
        self.acquire(timeout_s=float("inf"))
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False
