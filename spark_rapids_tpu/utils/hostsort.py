"""Host-side (pandas) multi-key sort with PER-KEY null placement.

pandas ``sort_values`` accepts only one ``na_position`` for all keys;
Spark orders allow NULLS FIRST/LAST per key.  When the placement is
uniform this is one multi-key call; otherwise stable single-key passes
compose in reverse key order (classic lexicographic composition).
Shared by the CPU-fallback external sort (exec/fallback.py) and the
window-in-pandas group sort (udf/python_exec.py) — the round-4 advisor
found the same per-key bug independently at both sites.
"""

from typing import Sequence

import pandas as pd


def sort_per_key_nulls(df: pd.DataFrame, names: Sequence[str],
                       ascending: Sequence[bool],
                       nulls_first: Sequence[bool],
                       reset_index: bool = True) -> pd.DataFrame:
    if len(set(nulls_first)) <= 1:
        out = df.sort_values(
            by=list(names), ascending=list(ascending),
            na_position="first" if (not nulls_first or nulls_first[0])
            else "last",
            kind="stable")
    else:
        out = df
        for name, asc, nf in zip(reversed(list(names)),
                                 reversed(list(ascending)),
                                 reversed(list(nulls_first))):
            out = out.sort_values(
                name, ascending=asc,
                na_position="first" if nf else "last",
                kind="stable")
    return out.reset_index(drop=True) if reset_index else out
