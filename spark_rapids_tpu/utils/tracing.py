"""Span tracing: wall-clock attribution for every engine phase.

The engine's counters (PR2 host syncs, PR4 wire bytes, PR9 overlap,
PR10 encoded savings) say *what happened*; nothing until now said
*where the time went*.  This module is the structured span runtime (the
NVTX-range analog, NvtxWithMetrics.scala, carried host-side so it works
on any backend):

* ``span(point, site=..., op=...)`` wraps a region.  Spans are
  **thread-aware** — each thread appends to its own buffer (list
  appends under the GIL; no lock on the hot path) — and
  **query-attributed**: each record is stamped with the *effective*
  owner ident (adopted worker threads resolve to their driving query
  via the PR6 ident-adoption discipline, serving/context.py), so two
  concurrent queries' spans never smear.
* Nesting is tracked per thread: a span's **exclusive** time is its
  duration minus its direct children's durations, so rollups never
  double count (the ``opTimeSelf`` discipline, at span granularity).
* Tracing is DEFAULT-OFF and, when off, every span site costs a single
  branch (``span`` returns a shared no-op; hot loops read ``_armed``
  directly and skip even the call).  Tracing changes no data path —
  chaos proves results bit-identical with it on.
* At QueryEnd ``finish_query`` drains the owner's closed records into
  (a) a Chrome-trace-event JSON file per query under
  ``spark.rapids.tpu.trace.dir`` (tools/traceview.py — load it in
  Perfetto), (b) an exclusive-time rollup per point / operator /
  structural site id that rides the QueryEnd ``spans`` dict, and (c)
  the persisted per-site :class:`ObservationStore` below.

**Observation store** (ROADMAP item 3's producer half): per-site
evidence — ``site_id -> {rows, bytes, skew, compile_ms, overlap_ms,
span_ms}`` — keyed by the SAME structural site ids the jit cache uses
(``site_id(sig)`` over the jit signature / exchange-site object),
persisted as JSONL beside the AOT cache dir, so a warm start has warm
evidence before any cost model exists.  Values are exponentially
smoothed (alpha 0.5) except ``compile_ms`` which keeps the max.

The runtime is process-global (the persistent-jit-tier discipline): the
last-constructed session's ``spark.rapids.tpu.trace.*`` conf wins.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# --------------------------------------------------------------- state --

_armed = False
_trace_dir: Optional[str] = None
_max_events = 100_000
_obs: Optional["ObservationStore"] = None
_reg_lock = threading.Lock()
_bufs: List["_Buf"] = []
_tls = threading.local()
# per-process trace file sequence for drains without a query id
_seq_lock = threading.Lock()
_seq = 0

# record tuple indices (tuples, not objects: the hot path allocates one
# per span and the drain touches thousands)
R_POINT, R_SITE, R_OP, R_T0, R_DUR, R_EXCL, R_OWNER, R_TID, R_ASYNC = \
    range(9)

# span points that measure DEVICE-side in-flight time overlapping host
# work (the async exchange window): exported and summed as overlapMs,
# but excluded from the exclusive-attribution sums — counting them
# toward "attributed wall" would let real blind spots hide under
# overlap credit
ASYNC_POINTS = frozenset({"exchange.async.inflight"})

# phase classification for timeline stripes / bench fractions: every
# span point maps to one of compile | exchange | spill | wait | compute
# (docs/observability.md "span taxonomy")
_PHASE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("jit.", "compile"),
    ("shuffle.exchange", "exchange"),
    ("exchange.", "exchange"),
    ("spill.", "spill"),
    ("checkpoint.", "spill"),
    ("incremental.commit", "spill"),
    # state maintenance, not answer compute: watermark eviction is the
    # windowed tick's state-bounding pass (incremental.join.delta /
    # .topn.merge stay "compute" — they ARE the steady-tick work)
    ("incremental.window.evict", "spill"),
    ("admission.wait", "wait"),
    ("scheduler.", "wait"),
    ("udf.worker", "wait"),
    ("pipeline.worker", "wait"),
    ("hostsync.", "wait"),
    ("dist.host_sync", "wait"),
)


def phase_of(point: str) -> str:
    for prefix, phase in _PHASE_PREFIXES:
        if point.startswith(prefix):
            return phase
    return "compute"


def site_id(site: Any) -> str:
    """Stable short id for a structural site object — the jit-cache
    signature (or exchange-site / checkpoint stage id) hashed the same
    way everywhere, so the observation store, the spans rollup, and
    any future cost model key on identical strings."""
    return hashlib.sha256(repr(site).encode()).hexdigest()[:16]


class _Buf:
    """One thread's append-only span storage.

    ``items`` holds closed records; appends are plain ``list.append``
    (GIL-atomic).  The drain compacts with one slice assignment —
    also a single atomic list op — so no lock is ever taken on the
    recording path."""

    __slots__ = ("items", "stack", "dropped", "tid", "name", "thread")

    def __init__(self):
        t = threading.current_thread()
        self.items: List[tuple] = []
        # open spans: [point, site, op, t0_ns, child_ns, owner]
        self.stack: List[list] = []
        self.dropped = 0
        self.tid = t.ident or 0
        self.name = t.name
        # held so the drain can prune buffers of finished threads (the
        # pipeline spawns one worker per drive — without pruning the
        # registry grows one buffer per query for the process life)
        self.thread = t


def _buf() -> _Buf:
    b = getattr(_tls, "buf", None)
    if b is None:
        b = _Buf()
        _tls.buf = b
        with _reg_lock:
            _bufs.append(b)
    return b


def _owner_ident() -> int:
    from spark_rapids_tpu.serving import context as qc
    return qc.effective_ident()


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _SpanCtx:
    __slots__ = ("point", "site", "op", "observe")

    def __init__(self, point: str, site, op, observe):
        self.point = point
        self.site = site
        self.op = op
        self.observe = observe

    def __enter__(self):
        b = _buf()
        b.stack.append([self.point, self.site, self.op,
                        time.perf_counter_ns(), 0, _owner_ident()])
        return self

    def __exit__(self, *exc):
        b = _buf()
        end = time.perf_counter_ns()
        point, site, op, t0, child_ns, owner = b.stack.pop()
        dur = end - t0
        excl = dur - child_ns
        if b.stack:
            b.stack[-1][4] += dur
        if not _armed:
            return False  # disarmed mid-span: unwind, record nothing
        if len(b.items) < _max_events:
            b.items.append((point, site, op, t0, dur, excl, owner,
                            b.tid, False))
        else:
            b.dropped += 1
        if self.observe is not None and site is not None and \
                _obs is not None:
            _obs.observe(site_id(site), **{self.observe: dur / 1e6})
        return False


def span(point: str, site=None, op=None, observe: Optional[str] = None):
    """Trace the enclosed region.  One branch when tracing is off.

    ``site``: structural site object (jit signature / stage id) —
    hashed into the rollup's per-site breakdown and, with ``observe``
    set to an observation-store field name (e.g. ``"compile_ms"``),
    the span's duration is recorded as that site observation."""
    if not _armed:
        return _NOOP
    return _SpanCtx(point, site, op, observe)


def emit_span(point: str, t0_ns: int, dur_ns: int, site=None, op=None,
              is_async: bool = True) -> None:
    """Append an already-timed record (no stack interaction): the async
    exchange in-flight window, incremental tick phases — regions whose
    endpoints the caller times itself."""
    if not _armed:
        return
    b = _buf()
    if len(b.items) < _max_events:
        b.items.append((point, site, op, int(t0_ns), int(dur_ns),
                        int(dur_ns), _owner_ident(), b.tid, is_async))
    else:
        b.dropped += 1


def observe_site(site, **fields) -> None:
    """Record per-site evidence (rows/bytes/skew/...) into the
    observation store.  ``site`` is the raw structural object; no-op
    when tracing is off or no store is configured."""
    if not _armed or _obs is None:
        return
    _obs.observe(site_id(site), **fields)


def observe_host(host: int, point: str, **fields) -> None:
    """Record per-HOST evidence into the observation store — the
    gray-failure per-host axis beside the structural per-site axis.
    Sites are sha-hashed structural ids; host records use the stable
    human-readable ``host<h>@<point>`` form so the profiling per-host
    history and a fresh process's HostHealthTracker can read them
    back without a reverse mapping.  No-op when tracing is off or no
    store is configured."""
    if not _armed or _obs is None:
        return
    _obs.observe(f"host{int(host)}@{point}", **fields)


# ------------------------------------------------------------ configure --

def configure(enabled: bool, trace_dir: Optional[str] = None,
              max_events: int = 100_000,
              obs_dir: Optional[str] = None) -> None:
    """(Re)arm the process-global runtime from a session's conf.
    ``enabled=False`` disarms (buffers drop their backlog so a
    disarmed process holds no span memory)."""
    global _armed, _trace_dir, _max_events, _obs
    _trace_dir = trace_dir or None
    _max_events = max(int(max_events), 1)
    if enabled and obs_dir:
        if _obs is None or _obs.dir != obs_dir:
            _obs = ObservationStore(obs_dir)
    else:
        # enabled without a store dir must DISABLE the store, not
        # silently keep writing beside a previous session's cache dir
        _obs = None
    _armed = bool(enabled)
    if not _armed:
        with _reg_lock:
            for b in _bufs:
                del b.items[:]
                b.dropped = 0


def armed() -> bool:
    return _armed


# ---------------------------------------------------------------- drain --

def _drain(owner: int) -> Tuple[List[tuple], int]:
    """Collect (and remove) every CLOSED record attributed to
    ``owner`` across all thread buffers.  Open spans stay on their
    stacks and surface in a later drain."""
    out: List[tuple] = []
    dropped = 0
    # the whole drain holds _reg_lock: recording stays lock-free
    # (appends land at >= n and the slice assignment preserves them),
    # but two concurrent QueryEnd drains must not interleave their
    # snapshot/compact sequences on a shared buffer — a stale
    # compaction would resurrect the other query's already-drained
    # records into its next trace (cross-query duplication)
    with _reg_lock:
        # prune finished threads' drained buffers (one pipeline worker
        # is born per drive; its buffer must die with it once emptied)
        _bufs[:] = [b for b in _bufs
                    if b.thread.is_alive() or b.items or b.stack]
        for b in _bufs:
            n = len(b.items)
            mine = ()
            if n:
                snapshot = b.items[:n]
                mine = [r for r in snapshot if r[R_OWNER] == owner]
                if mine:
                    keep = [r for r in snapshot
                            if r[R_OWNER] != owner]
                    # single slice assignment: atomic under the GIL,
                    # racing appends land at >= n and are preserved
                    b.items[:n] = keep
                    out.extend(mine)
            # drop accounting is per-buffer, so attribution is
            # best-effort: charge a buffer's drops to the drain that
            # owns the buffer's thread or harvested records from it —
            # an unrelated query's drain must not zero the counter and
            # make the owner's truncated trace read as complete
            if b.dropped and (mine or b.tid == owner):
                dropped += b.dropped
                b.dropped = 0
    out.sort(key=lambda r: r[R_T0])
    return out, dropped


def rollup(records: List[tuple], wall_ms: float,
           dropped: int = 0) -> Dict[str, Any]:
    """Exclusive-time rollup: per point, per operator, per structural
    site, plus the phase stripes and the unattributed-time health
    metric (wall - sum(exclusive); > 20% of wall = an instrumentation
    blind spot)."""
    points: Dict[str, Dict[str, float]] = {}
    operators: Dict[str, Dict[str, float]] = {}
    sites: Dict[str, Dict[str, float]] = {}
    phases: Dict[str, float] = {}
    total_excl = 0.0
    overlap_ms = 0.0
    for r in records:
        dur_ms = r[R_DUR] / 1e6
        excl_ms = max(r[R_EXCL], 0) / 1e6
        point = r[R_POINT]
        if r[R_ASYNC] or point in ASYNC_POINTS:
            overlap_ms += dur_ms
            p = points.setdefault(point, {"count": 0, "ms": 0.0,
                                          "exclusiveMs": 0.0})
            p["count"] += 1
            p["ms"] += dur_ms
            continue
        p = points.setdefault(point, {"count": 0, "ms": 0.0,
                                      "exclusiveMs": 0.0})
        p["count"] += 1
        p["ms"] += dur_ms
        p["exclusiveMs"] += excl_ms
        total_excl += excl_ms
        ph = phase_of(point)
        phases[ph] = phases.get(ph, 0.0) + excl_ms
        if r[R_OP]:
            o = operators.setdefault(r[R_OP], {"count": 0, "ms": 0.0,
                                               "exclusiveMs": 0.0})
            o["count"] += 1
            o["ms"] += dur_ms
            o["exclusiveMs"] += excl_ms
        if r[R_SITE] is not None:
            # one key derivation everywhere (jit sigs, exchange sites,
            # stage ids): the observation store and the rollup must
            # agree on the string a site hashes to
            sid = site_id(r[R_SITE])
            s = sites.setdefault(sid, {"count": 0, "ms": 0.0})
            s["count"] += 1
            s["ms"] += excl_ms
    unattributed = max(wall_ms - total_excl, 0.0)
    out = {
        "wallMs": round(wall_ms, 3),
        "exclusiveMs": round(total_excl, 3),
        "unattributedMs": round(unattributed, 3),
        "unattributedFrac": round(unattributed / wall_ms, 4)
        if wall_ms > 0 else 0.0,
        "overlapMs": round(overlap_ms, 3),
        "events": len(records),
        "dropped": dropped,
        "phases": {k: round(v, 3) for k, v in sorted(phases.items())},
        "points": {k: {"count": v["count"], "ms": round(v["ms"], 3),
                       "exclusiveMs": round(v["exclusiveMs"], 3)}
                   for k, v in sorted(points.items())},
    }
    if operators:
        out["operators"] = {
            k: {"count": v["count"], "ms": round(v["ms"], 3),
                "exclusiveMs": round(v["exclusiveMs"], 3)}
            for k, v in sorted(operators.items())}
    if sites:
        out["sites"] = {k: {"count": v["count"], "ms": round(v["ms"], 3)}
                        for k, v in sorted(sites.items())}
    return out


def finish_query(session, qid: Optional[int], wall_ms: float,
                 status: str = "success",
                 label: Optional[str] = None) -> Dict[str, Any]:
    """The QueryEnd drain: collect this thread's query's spans, export
    the per-query Chrome trace file (trace.dir), fold per-site span
    time into the observation store, and return the rollup dict for the
    QueryEnd ``spans`` field.  Cheap no-op ({}) when tracing is off —
    faulted and fatal envelopes call it too, so their trace files are
    still well-formed."""
    if not _armed:
        return {}
    records, dropped = _drain(_owner_ident())
    roll = rollup(records, wall_ms, dropped)
    roll["status"] = status
    if _obs is not None:
        for sid, s in (roll.get("sites") or {}).items():
            _obs.observe(sid, span_ms=s["ms"])
        _obs.flush()
    if _trace_dir and (records or qid is not None):
        global _seq
        with _seq_lock:
            _seq += 1
            seq = _seq
        sid = getattr(session, "session_id", "nosession")
        name = label or (f"q{qid}" if qid is not None else f"s{seq}")
        path = os.path.join(_trace_dir,
                            f"trace-{sid}-{name}-{seq}.json")
        try:
            from spark_rapids_tpu.tools.traceview import write_trace
            write_trace(records, path, qid=qid, max_events=_max_events,
                        dropped=dropped, wall_ms=wall_ms, status=status)
            roll["traceFile"] = path
        except Exception:
            pass  # trace export must never fail the query
    try:
        session.last_span_stats = roll
    except Exception:
        pass
    return roll


def finish_scope(session, label: str, wall_ms: float) -> Dict[str, Any]:
    """Drain a non-query scope (an incremental tick's phase spans,
    emitted between query envelopes) into its own trace file."""
    return finish_query(session, None, wall_ms, status="scope",
                        label=label)


# ---------------------------------------------------- observation store --

# observation fields that keep the MAX across observations (compile
# cost per site is the worst-case trace+compile); everything else
# exponentially smooths
_OBS_MAX_FIELDS = frozenset({"compile_ms"})
_OBS_ALPHA = 0.5
OBS_FILE = "observations.jsonl"


class ObservationStore:
    """Persisted per-site observations: one JSONL file beside the AOT
    jit-cache dir.  Load-merge-rewrite on flush (atomic replace), so a
    fresh process reads the prior process's evidence — the ROADMAP
    item 3 producer contract.

    Flushes are serialized across PROCESSES by a lock file
    (O_CREAT|O_EXCL beside the store) and each flush RE-READS the
    on-disk file under the lock, merging records it did not itself
    observe — two concurrent sessions sharing one AOT cache dir can no
    longer drop each other's observations in the read-rewrite window
    (each used to overwrite the file with only its own snapshot).
    Only sites this store OBSERVED since its last flush are written —
    a site merely loaded at construction is a stale copy and must not
    clobber another session's fresher on-disk record.  For a site both
    observed, the flushing store's smoothed values win (freshest
    evidence) except ``compile_ms`` (max — worst-case cost) and
    ``n``/``ts`` (max — monotone counters).  A lock that cannot be
    acquired within the timeout re-marks the snapshot dirty and
    retries at the next flush; a lock file older than ``LOCK_STALE_S``
    is broken by an atomic rename (exactly one breaker wins — two
    sessions both unlinking could otherwise delete each other's FRESH
    locks and run the merge concurrently)."""

    LOCK_TIMEOUT_S = 2.0
    # generous: the stale break exists for CRASHED holders only.  A
    # live-but-slow holder whose merge outruns this window could have
    # its lock stolen (two concurrent merges, lost updates) — the
    # holder stamps the lock's mtime at acquire so the window measures
    # from the start of ITS flush, and a flush that takes longer than
    # this on an optimization-only store is an acceptable residual
    # risk (the store degrades, it never corrupts queries)
    LOCK_STALE_S = 30.0

    def __init__(self, dirpath: str):
        self.dir = dirpath
        self.path = os.path.join(dirpath, OBS_FILE)
        self._lock = threading.Lock()
        self._file_lock = None  # lazy InterProcessLock (pid-stamped)
        self.records: Dict[str, Dict[str, float]] = {}
        self._dirty = False
        # sites THIS store observed since its last successful flush —
        # only these may overwrite the on-disk record: a site merely
        # LOADED at construction is a stale copy, and flushing it
        # ours-win would revert a concurrent session's fresher values
        self._dirty_sids: set = set()
        try:
            os.makedirs(dirpath, exist_ok=True)
            self.records = self.read(dirpath)
        except Exception:
            self.records = {}

    def observe(self, sid: str, **fields) -> None:
        with self._lock:
            rec = self.records.setdefault(sid, {"n": 0})
            rec["n"] = int(rec.get("n", 0)) + 1
            for k, v in fields.items():
                v = float(v)
                prev = rec.get(k)
                if prev is None:
                    rec[k] = round(v, 3)
                elif k in _OBS_MAX_FIELDS:
                    rec[k] = round(max(float(prev), v), 3)
                else:
                    rec[k] = round(_OBS_ALPHA * v +
                                   (1 - _OBS_ALPHA) * float(prev), 3)
            rec["ts"] = round(time.time(), 3)
            self._dirty = True
            self._dirty_sids.add(sid)

    def _acquire_file_lock(self) -> bool:
        """Best-effort cross-process lock beside the store.  False when
        another holder kept it past the timeout — the caller retries at
        the next flush.  Delegates to the shared pid-stamped
        InterProcessLock: a kill-9'd merger's lock is reaped as soon as
        any waiter observes the dead pid, instead of wedging every
        writer for the full LOCK_STALE_S window."""
        from spark_rapids_tpu.utils.locking import InterProcessLock
        if self._file_lock is None:
            self._file_lock = InterProcessLock(self.path + ".lock",
                                               stale_s=self.LOCK_STALE_S)
        return self._file_lock.acquire(timeout_s=self.LOCK_TIMEOUT_S)

    def _release_file_lock(self) -> None:
        if self._file_lock is not None:
            self._file_lock.release()

    @classmethod
    def _merge_record(cls, disk: Dict[str, float],
                      ours: Dict[str, float]) -> Dict[str, float]:
        """Field-wise merge for a site both stores observed: our
        smoothed values win (freshest evidence), except max-semantics
        fields (compile_ms worst case; n/ts monotone)."""
        out = dict(disk)
        out.update(ours)
        for k in list(_OBS_MAX_FIELDS) + ["n", "ts"]:
            if k in disk and k in ours:
                out[k] = max(disk[k], ours[k])
        return out

    def flush(self) -> None:
        with self._lock:
            if not self._dirty:
                return
            # only sites observed since load/last flush: a record this
            # store merely loaded must never clobber a concurrent
            # session's fresher on-disk copy of the same site
            snapshot = {k: dict(self.records[k])
                        for k in self._dirty_sids
                        if k in self.records}
            taken = set(self._dirty_sids)
            self._dirty_sids.clear()
            self._dirty = False
        if not self._acquire_file_lock():
            with self._lock:
                self._dirty = True  # nothing lost: retry next flush
                self._dirty_sids |= taken
            return
        try:
            # merge under the lock: a concurrent session's flush since
            # our load must survive ours (sites only it observed keep
            # its record; shared sites merge field-wise)
            merged = self.read(self.dir)
            for sid, rec in snapshot.items():
                prev = merged.get(sid)
                merged[sid] = self._merge_record(prev, rec) \
                    if prev else rec
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for sid in sorted(merged):
                    rec = {"site": sid}
                    rec.update(merged[sid])
                    f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            pass  # persistence is an optimization, never a failure
        finally:
            self._release_file_lock()

    @staticmethod
    def read(dirpath: str) -> Dict[str, Dict[str, float]]:
        """Parse a store directory's observations (empty dict when
        absent) — the consumer half used by tools/profiling.py's
        per-site history section and any future cost model."""
        path = os.path.join(dirpath, OBS_FILE)
        out: Dict[str, Dict[str, float]] = {}
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail of a live store
                    sid = rec.pop("site", None)
                    if sid:
                        out[sid] = rec
        except OSError:
            pass
        return out


def observation_store() -> Optional[ObservationStore]:
    return _obs
