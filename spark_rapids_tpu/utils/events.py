"""Query event log: JSON-lines records of plans, metrics, and spill stats.

The observability backbone (reference analogs: GpuMetric -> Spark
SQLMetrics surfaced in the UI/event log, and the NVTX range taxonomy,
NvtxWithMetrics.scala).  One file per session in
``spark.rapids.tpu.eventLog.dir``; each line is one event:

  {"event": "SessionStart", "ts": ..., "conf": {...}}
  {"event": "QueryStart",  "queryId": n, "logicalPlan": "...",
   "physicalPlan": "...", "explain": "..."}
  {"event": "QueryEnd",    "queryId": n, "durationMs": ..., "status": ...,
   "metrics": {"TpuHashAggregateExec": {"opTime": ...}, ...},
   "spill": {"hostBytes": ..., "diskBytes": ...}}

The qualification and profiling tools (tools/) consume these files the way
the reference's tools consume Spark event logs (SURVEY.md section 2.8).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional


# events that always flush through to disk immediately, whatever
# flushMs says: the records a crash post-mortem cannot live without
_FLUSH_EVENTS = frozenset({"QueryEnd", "QueryFatal", "SessionEnd"})


class EventLogger:
    """Append-only JSON-lines writer; no-op when dir is empty.

    ``flush_ms`` (spark.rapids.tpu.eventLog.flushMs) batches flushes:
    lines still write() immediately (a crash loses at most the OS
    buffer tail), but the explicit flush() — which hot-path emitters
    like the watchdog monitor and spill integrity used to pay per
    line under the lock — is coalesced to one per window.  0 keeps
    flush-per-line; QueryEnd/QueryFatal/SessionEnd and close() always
    flush so the tail is durable at every envelope boundary."""

    def __init__(self, log_dir: Optional[str], session_id: str,
                 conf_snapshot: Optional[Dict[str, Any]] = None,
                 flush_ms: int = 0):
        self._lock = threading.Lock()
        self._fh = None
        self.flush_ms = max(int(flush_ms), 0)
        self._last_flush = 0.0
        self.path: Optional[str] = None
        if log_dir:
            import atexit
            os.makedirs(log_dir, exist_ok=True)
            self.path = os.path.join(log_dir,
                                     f"tpu-events-{session_id}.jsonl")
            self._fh = open(self.path, "a", encoding="utf-8")
            self.emit("SessionStart", conf=conf_snapshot or {},
                      sessionId=session_id)
            # sessions without an explicit stop() still close their log
            # (and emit SessionEnd) at interpreter shutdown
            atexit.register(self.close)

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def emit(self, event: str, **fields: Any) -> None:
        if self._fh is None:
            return
        rec = {"event": event, "ts": time.time()}
        rec.update(fields)
        line = json.dumps(rec, default=str)
        with self._lock:
            # re-check under the lock: the watchdog monitor thread may
            # emit concurrently with a close() on the session thread
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            now = time.monotonic()
            if self.flush_ms == 0 or event in _FLUSH_EVENTS or \
                    (now - self._last_flush) * 1e3 >= self.flush_ms:
                self._fh.flush()
                self._last_flush = now

    def flush(self) -> None:
        """Force the buffered tail to disk (QueryEnd/close do this
        implicitly)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._last_flush = time.monotonic()

    def close(self) -> None:
        if self._fh is not None:
            import atexit
            self.emit("SessionEnd")
            with self._lock:
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None
            try:  # release the atexit pin so the logger can be GC'd
                atexit.unregister(self.close)
            except Exception:
                pass


def emit_on_session(event: str, session=None, **fields: Any) -> None:
    """Emit ``event`` on the given (or active) session's event log,
    stamped with the in-flight query id.  No-op without an enabled
    logger.  The one shared resolver for subsystems that emit from
    arbitrary threads (the watchdog monitor, spill integrity) — keeps
    the session lookup / torn-interpreter guard in one place."""
    if session is None:
        try:
            from spark_rapids_tpu.api.session import TpuSession
            session = TpuSession._active
        except ImportError:  # torn-down interpreter only
            return
    ev = getattr(session, "events", None) if session is not None else None
    if ev is not None and ev.enabled:
        fields.setdefault("queryId",
                          getattr(session, "_current_qid", None))
        ev.emit(event, **fields)
