"""Shared plan-tree rendering (one renderer for logical and physical
trees; tools/profiling.plan_dot reconstructs the hierarchy from the
2-space indentation, so the format is load-bearing)."""

from __future__ import annotations

from typing import List

INDENT = "  "


def render_tree(node) -> str:
    """Indent-by-depth rendering of any node with .describe() and
    .children."""
    lines: List[str] = []

    def rec(n, depth):
        lines.append(INDENT * depth + n.describe())
        for c in n.children:
            rec(c, depth + 1)
    rec(node, 0)
    return "\n".join(lines)
