"""Host-sync accounting: every device->host scalar/buffer fetch counts.

A device->host synchronization costs a full tunnel round trip on real
TPU hardware (the r05 bench attributes the group-by path's 10x gap to
per-batch ``int(n)`` syncs), so the engine treats syncs as a budgeted
resource: every site that materializes device data on the host goes
through :func:`fetch` / :func:`count_sync`, and the counters surface in
QueryEnd events (``pipeline.hostSyncCount``), ``bench.py`` JSON and
``tests/test_pipeline.py``'s regression assertions.

The discipline for when a sync is allowed lives in
``docs/performance.md`` ("when is ``int(x)`` on a device value
allowed"); the short form: only at true host decision points —
coded-vs-sort dispatch, spill/merge sizing, string re-decode, and the
final collect.

Process-wide totals plus a thread-local mirror (the RetryMetrics
pattern): a query runs its operator pipeline on one thread, so
per-query deltas read the thread-local view and concurrent sessions
don't contaminate each other's attribution.
"""

from __future__ import annotations

import threading
from typing import Sequence


class HostSyncMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.sync_count = 0
        self._per_thread = {}  # effective thread ident -> count
        self._owner = {}       # worker ident -> owning (driving) ident

    def _effective_ident(self) -> int:
        ident = threading.get_ident()
        return self._owner.get(ident, ident)

    def bump(self, n: int = 1) -> None:
        with self._lock:
            self.sync_count += n
            ident = self._effective_ident()
            self._per_thread[ident] = self._per_thread.get(ident, 0) + n

    def snapshot(self) -> int:
        with self._lock:
            return self.sync_count

    def snapshot_local(self) -> int:
        with self._lock:
            return self._per_thread.get(self._effective_ident(), 0)

    def adopt(self, owner_ident: int) -> None:
        """Attribute this thread's syncs to ``owner_ident``'s view.
        The pipeline worker (exec/pipeline.py) adopts its driving
        thread so per-query deltas keep working when the operator
        iterator runs on the worker."""
        with self._lock:
            self._owner[threading.get_ident()] = owner_ident

    def release(self) -> None:
        with self._lock:
            self._owner.pop(threading.get_ident(), None)

    def disown(self, ident: int) -> None:
        """Sever ``ident``'s adoption from the outside (a driver
        abandoning a wedged worker thread)."""
        with self._lock:
            self._owner.pop(ident, None)

    def purge_owner(self, owner_ident: int) -> None:
        """Drop every adoption mapping TO ``owner_ident`` — the
        query-exit counterpart of disown(): the OS reuses idents, so a
        stale entry would attribute a NEW query's syncs to this dead
        query's view (serving/context.QueryContext.__exit__).  The
        per-thread counters themselves survive: callers take deltas
        across queries on long-lived client threads."""
        from spark_rapids_tpu.robustness.inject import purge_adoptions
        with self._lock:
            purge_adoptions(self._owner, owner_ident)

    def reset(self) -> None:
        with self._lock:
            self.sync_count = 0
            self._per_thread.clear()


host_sync_metrics = HostSyncMetrics()


def _charge_budget(n: int) -> None:
    """Serving-layer sync budget: the owning QueryContext counts every
    sync against spark.rapids.tpu.serving.syncBudget and rejects THIS
    query (typed BudgetExhaustedFault) past the limit — a runaway sync
    loop in one tenant must not serialize the shared tunnel.  Free
    (one dict probe) when no context is active."""
    from spark_rapids_tpu.serving import context as qc
    ctx = qc.current()
    if ctx is not None:
        ctx.charge_syncs(n)


def count_sync(n: int = 1) -> None:
    """Record ``n`` device->host synchronizations.  Every counted sync
    is also a watchdog cancellation checkpoint — host syncs are the
    places the driving thread provably touches the host, so a tripped
    deadline surfaces here rather than after minutes of dead pipeline.
    """
    from spark_rapids_tpu.robustness import watchdog
    watchdog.checkpoint()
    host_sync_metrics.bump(n)
    _charge_budget(n)


# ------------------------------------------------------ upload accounting --
# Thread-local sink for host->device upload timing: the pipeline worker
# (exec/pipeline.py) registers its PipelineStats here, and the columnar
# materialization sites (columnar/column.py ``jnp.asarray``) report in.
# Measures host-side dispatch+staging time (device transfer itself is
# async) — the work the sequential loop would serialize against
# consumption.
_upload_sink = threading.local()


def watch_uploads(stats) -> None:
    """Route this thread's upload timings into ``stats``
    (any object with an ``upload_overlap_ns`` attribute)."""
    _upload_sink.sink = stats


def unwatch_uploads() -> None:
    _upload_sink.sink = None


def note_upload(ns: int) -> None:
    sink = getattr(_upload_sink, "sink", None)
    if sink is not None:
        sink.upload_overlap_ns += ns


def _globalize(buffers):
    """Replicate non-fully-addressable buffers before the device_get:
    in a multi-controller fleet each process holds only its shards of
    a global array, and ``jax.device_get`` on one raises instead of
    fetching — route those through ``mesh.to_host`` (a cross-fleet
    replicate, every controller gets the identical full copy the SPMD
    contract needs).  Single-controller arrays pass through untouched,
    so this is one attribute probe per buffer on the common path."""
    import jax
    out = list(buffers)
    for i, b in enumerate(out):
        if isinstance(b, jax.Array) and not b.is_fully_addressable:
            from spark_rapids_tpu.parallel.mesh import to_host
            out[i] = to_host(b)
    return out


def fetch(*buffers):
    """Fetch device buffers to host in ONE transfer (one counted sync).

    Per-buffer ``np.asarray`` pays a full round trip each — dominant
    with a remote-tunnel device; batching through ``jax.device_get``
    amortizes them into a single sync.  Returns numpy arrays in input
    order (a single buffer returns the bare array).
    """
    import jax
    from spark_rapids_tpu.robustness import watchdog
    from spark_rapids_tpu.utils import tracing
    watchdog.checkpoint()
    host_sync_metrics.bump(1)
    _charge_budget(1)
    with tracing.span("hostsync.fetch"):
        got = jax.device_get(_globalize(buffers))
    return got[0] if len(buffers) == 1 else got


def fetch_all(buffers: Sequence):
    """List form of :func:`fetch` (always returns a list)."""
    import jax
    from spark_rapids_tpu.robustness import watchdog
    from spark_rapids_tpu.utils import tracing
    if not buffers:
        return []
    watchdog.checkpoint()
    host_sync_metrics.bump(1)
    _charge_budget(1)
    with tracing.span("hostsync.fetch"):
        return jax.device_get(_globalize(buffers))
