"""Multi-file reader strategies.

Counterpart of ``GpuMultiFileReader.scala`` (1,039 LoC) and the three parquet
reader types (``GpuParquetScan.scala:786,973``; conf
``spark.rapids.sql.format.parquet.reader.type``):

* PERFILE       — one file at a time, host parse then device upload;
* MULTITHREADED — a thread pool reads+decodes files to host Arrow tables in
  the background, overlapping host IO/decode with device compute (the
  MultiFileCloudPartitionReader analog); bounded in-flight files;
* COALESCING    — many small files are stitched into one host table before a
  single device upload (the MultiFileCoalescingPartitionReader analog);
* AUTO          — COALESCING for many small local files, else MULTITHREADED.

All strategies push down column pruning and pyarrow-expression filters to
the format reader (footer/row-group pruning + exact filtering).
"""

from __future__ import annotations

import concurrent.futures
import os
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa

from spark_rapids_tpu.columnar.batch import ColumnarBatch

_FORMAT_EXT = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv"}


def _read_file_to_table(path: str, file_format: str,
                        columns: Optional[List[str]],
                        filter_expr, batch_rows: int) -> pa.Table:
    import pyarrow.dataset as ds
    dataset = ds.dataset([path], format=file_format)
    return dataset.to_table(columns=columns, filter=filter_expr)


def _decode_bytes(blob: bytes, file_format: str,
                  columns: Optional[List[str]], filter_expr) -> pa.Table:
    """Decode one file's raw bytes (from the native prefetcher) to Arrow."""
    reader = pa.BufferReader(blob)
    if file_format == "parquet":
        import pyarrow.parquet as pq
        # filters= keeps row-group statistics pruning even from a buffer
        t = pq.read_table(reader, columns=columns, filters=filter_expr)
        filter_expr = None
    elif file_format == "orc":
        import pyarrow.orc as orc
        t = orc.ORCFile(reader).read(columns=columns)
    else:
        import pyarrow.csv as pacsv
        t = pacsv.read_csv(reader)
        if columns is not None:
            t = t.select(columns)
    if filter_expr is not None:
        t = t.filter(filter_expr)
    return t


def iter_file_tables(paths: Sequence[str], file_format: str,
                     columns: Optional[List[str]], filter_expr,
                     reader_type: str, batch_rows: int,
                     num_threads: int = 8,
                     max_files_parallel: int = 4,
                     coalesce_target_bytes: int = 128 << 20
                     ) -> Iterator[pa.Table]:
    """Yield host Arrow tables per strategy; caller uploads to device."""
    if reader_type == "AUTO":
        small = all(_safe_size(p) < 32 << 20 for p in paths[:16])
        reader_type = "COALESCING" if len(paths) > 1 and small else \
            ("MULTITHREADED" if len(paths) > 1 else "PERFILE")

    if reader_type == "PERFILE" or len(paths) == 1:
        for p in paths:
            yield _read_file_to_table(p, file_format, columns, filter_expr,
                                      batch_rows)
        return

    if reader_type == "MULTITHREADED":
        from spark_rapids_tpu import native
        if native.available() and file_format in ("parquet", "orc", "csv"):
            # native thread pool reads raw bytes (GIL-free IO) while this
            # thread decodes prior files — the background-read + decode
            # overlap of MultiFileCloudParquetPartitionReader.  A sliding
            # window of max_files_parallel bounds resident raw bytes (and
            # teardown work on early generator close, e.g. LIMIT queries).
            pf = native.FilePrefetcher(num_threads)
            try:
                all_paths = list(paths)
                window = max(max_files_parallel, 1)
                submitted = min(window, len(all_paths))
                pf.submit(all_paths[:submitted])
                for i in range(len(all_paths)):
                    blob = pf.get(i)
                    if submitted < len(all_paths):
                        pf.submit([all_paths[submitted]])
                        submitted += 1
                    yield _decode_bytes(blob, file_format, columns,
                                        filter_expr)
            finally:
                pf.close()
            return
        with concurrent.futures.ThreadPoolExecutor(num_threads) as pool:
            pending = []
            it = iter(paths)
            for p in it:
                pending.append(pool.submit(
                    _read_file_to_table, p, file_format, columns,
                    filter_expr, batch_rows))
                if len(pending) >= max_files_parallel:
                    yield pending.pop(0).result()
            for f in pending:
                yield f.result()
        return

    if reader_type == "COALESCING":
        acc: List[pa.Table] = []
        acc_bytes = 0
        with concurrent.futures.ThreadPoolExecutor(num_threads) as pool:
            futures = [pool.submit(_read_file_to_table, p, file_format,
                                   columns, filter_expr, batch_rows)
                       for p in paths]
            for f in futures:
                t = f.result()
                if t.num_rows == 0:
                    continue
                acc.append(t)
                acc_bytes += t.nbytes
                if acc_bytes >= coalesce_target_bytes:
                    yield pa.concat_tables(acc)
                    acc, acc_bytes = [], 0
        if acc:
            yield pa.concat_tables(acc)
        return

    raise ValueError(f"unknown reader type {reader_type}")


def _safe_size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 1 << 40
