"""File writers: parquet / orc / csv with dynamic partitioning.

Counterpart of ``GpuParquetFileFormat`` / ``GpuOrcFileFormat`` /
``ColumnarOutputWriter`` / ``GpuFileFormatWriter`` (SURVEY.md section 2.4
"Writers"): batches leave the device once, are encoded host-side via
pyarrow, with hive-style dynamic partitioning (the reference sorts by
partition columns then splits; pyarrow's dataset writer does the same
bucketing) and write-stats tracking (BasicColumnarWriteStatsTracker analog).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch


@dataclasses.dataclass
class WriteStats:
    """numFiles/numBytes/numRows (BasicColumnarWriteStatsTracker.scala)."""
    num_files: int = 0
    num_bytes: int = 0
    num_rows: int = 0
    num_partitions: int = 0


def write_batches(batches: Iterator[ColumnarBatch], path: str,
                  file_format: str, mode: str = "error",
                  partition_by: Optional[List[str]] = None,
                  bucket_by: Optional[tuple] = None,
                  max_rows_per_file: int = 1 << 22) -> WriteStats:
    import pyarrow as pa
    import pyarrow.dataset as ds

    exists = os.path.isdir(path) and bool(os.listdir(path)) or \
        os.path.isfile(path)
    if exists:
        if mode == "error":
            raise FileExistsError(f"path {path} already exists")
        if mode == "ignore":
            return WriteStats()
        if mode == "overwrite":
            import shutil
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
        # mode == "append": fall through, write additional files

    tables = [b.to_arrow() for b in batches]
    if not tables:
        os.makedirs(path, exist_ok=True)
        return WriteStats()
    table = pa.concat_tables(tables)
    stats = WriteStats(num_rows=table.num_rows)

    if bucket_by is not None:
        if partition_by:
            raise ValueError("bucketBy cannot combine with partitionBy")
        if mode == "append" and exists:
            # bucket files have deterministic names; appending would
            # silently replace them
            raise ValueError(
                "append mode is unsupported for bucketed tables")
        return _write_bucketed(table, path, file_format, bucket_by, stats)

    if file_format == "orc":
        # pyarrow's dataset writer has no ORC support; write files directly
        # (dynamic partitioning by hive-style directory split)
        _write_orc(table, path, partition_by, stats)
        return stats

    fmt = {"parquet": "parquet", "csv": "csv"}[file_format]
    partitioning = None
    if partition_by:
        partitioning = ds.partitioning(
            pa.schema([table.schema.field(c) for c in partition_by]),
            flavor="hive")
    import uuid
    ext = {"parquet": "parquet", "orc": "orc", "csv": "csv"}[file_format]
    ds.write_dataset(
        table, path, format=fmt, partitioning=partitioning,
        max_rows_per_file=max_rows_per_file,
        max_rows_per_group=min(1 << 20, max_rows_per_file),
        basename_template=f"part-{uuid.uuid4().hex[:8]}-{{i}}.{ext}",
        existing_data_behavior="overwrite_or_ignore")
    for root, _dirs, files in os.walk(path):
        for f in files:
            stats.num_files += 1
            stats.num_bytes += os.path.getsize(os.path.join(root, f))
    if partition_by:
        parts = set()
        for root, dirs, _files in os.walk(path):
            for d in dirs:
                if "=" in d:
                    parts.add(os.path.join(root, d))
        stats.num_partitions = len(parts)
    return stats


def _write_bucketed(table, path: str, file_format: str, bucket_by,
                    stats: WriteStats) -> WriteStats:
    """Hash-route rows to part-bucket-N files + the _bucket_spec.json
    sidecar (Spark bucketBy; see io/bucketing.py for read-side
    pruning)."""
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io import bucketing as B
    num_buckets, column = bucket_by
    if column not in table.column_names:
        raise KeyError(f"bucketBy column {column!r} not in output")
    os.makedirs(path, exist_ok=True)
    vals = table.column(column).to_pandas().to_numpy()
    ids = B.bucket_ids(vals, num_buckets)
    for b in range(num_buckets):
        rows = np.nonzero(ids == b)[0]
        if not len(rows):
            continue
        f = B.bucket_file(path, b, file_format)
        sub = table.take(rows)
        if file_format == "parquet":
            pq.write_table(sub, f)
        elif file_format == "orc":
            import pyarrow.orc as orc
            orc.write_table(sub, f)
        else:
            raise ValueError(
                f"bucketed write unsupported for {file_format}")
        stats.num_files += 1
        stats.num_bytes += os.path.getsize(f)
    B.write_spec(path, column, num_buckets)
    stats.num_partitions = num_buckets
    return stats


def _write_orc(table, path: str, partition_by, stats: WriteStats) -> None:
    import uuid
    import pyarrow.orc as orc

    os.makedirs(path, exist_ok=True)
    tag = uuid.uuid4().hex[:8]
    if not partition_by:
        f = os.path.join(path, f"part-{tag}-0.orc")
        orc.write_table(table, f)
        stats.num_files = 1
        stats.num_bytes = os.path.getsize(f)
        return
    # hive-style split: distinct partition tuples -> subdirectories
    import pyarrow.compute as pc
    keys = table.select(partition_by).to_pylist()
    seen = {}
    for i, k in enumerate(keys):
        seen.setdefault(tuple(k.values()), []).append(i)
    drop = [c for c in table.column_names if c not in partition_by]
    for values, rows in seen.items():
        sub = os.path.join(path, *[
            f"{c}={v}" for c, v in zip(partition_by, values)])
        os.makedirs(sub, exist_ok=True)
        f = os.path.join(sub, f"part-{tag}-0.orc")
        orc.write_table(table.take(rows).select(drop), f)
        stats.num_files += 1
        stats.num_bytes += os.path.getsize(f)
    stats.num_partitions = len(seen)


class DataFrameWriter:
    """df.write.mode(...).partitionBy(...).parquet(path) surface."""

    def __init__(self, df):
        self.df = df
        self._mode = "error"
        self._partition_by: Optional[List[str]] = None
        self._bucket_by: Optional[tuple] = None

    def mode(self, m: str) -> "DataFrameWriter":
        assert m in ("error", "errorifexists", "overwrite", "append",
                     "ignore")
        self._mode = "error" if m == "errorifexists" else m
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def bucketBy(self, num_buckets: int, col: str) -> "DataFrameWriter":
        self._bucket_by = (int(num_buckets), col)
        return self

    def _write(self, path: str, file_format: str) -> WriteStats:
        from spark_rapids_tpu.api.session import TpuSession
        from spark_rapids_tpu.config import rapids_conf as rc
        # call-time conf resolution (retry budget, join knobs) follows
        # the session executing this write
        TpuSession._active = self.df.session
        exec_plan = self.df.session.plan(self.df.plan)
        return write_batches(
            exec_plan.execute(), path, file_format,
            mode=self._mode,
            partition_by=self._partition_by,
            bucket_by=self._bucket_by,
            max_rows_per_file=self.df.session.conf.get(
                rc.WRITER_MAX_ROWS_PER_FILE))

    def parquet(self, path: str) -> WriteStats:
        return self._write(path, "parquet")

    def orc(self, path: str) -> WriteStats:
        return self._write(path, "orc")

    def csv(self, path: str) -> WriteStats:
        return self._write(path, "csv")
