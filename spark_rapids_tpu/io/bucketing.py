"""Bucketed table layout: write-side bucket assignment + read-side pruning.

Counterpart of the reference's bucketed-scan support
(GpuFileSourceScanExec bucket handling; Spark's HashPartitioning bucket
spec).  Standalone engines have no metastore, so the spec travels as a
``_bucket_spec.json`` sidecar in the table directory:

    {"column": "k", "num_buckets": 8, "version": 1}

Write: rows hash-route to ``part-bucket-NNNNN.<fmt>`` files.  Read: an
equality filter on the bucket column prunes the scan to one file — the
host-side analog of Spark's bucket pruning.  The hash is a fixed fmix32
(murmur3 finalizer) so write and read sides can never drift.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np

SPEC_FILE = "_bucket_spec.json"


def _fmix32(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint32, copy=True)
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x85EBCA6B)
    x ^= x >> np.uint32(13)
    x *= np.uint32(0xC2B2AE35)
    x ^= x >> np.uint32(16)
    return x


def bucket_ids(values: np.ndarray, num_buckets: int) -> np.ndarray:
    """Vectorized bucket assignment for an int/float/bool/string host
    array.  Nulls (None/NaN) go to bucket 0.

    Numerics hash under a canonical float64 representation so the
    bucket of a value never depends on the numpy dtype it happens to
    arrive in (int64 5, float64 5.0, and a nullable-int column gone
    float at write time all land in the same bucket)."""
    if values.dtype.kind in ("O", "U", "S"):
        # pack utf-8 bytes into a rows x words uint32 matrix and fold
        # word-columns through fmix: the loop is over WORD POSITIONS of
        # the longest string, each step vectorized across all rows
        enc = [b"" if v is None else str(v).encode("utf-8")
               for v in values]
        lens = np.array([len(b) for b in enc], dtype=np.uint32)
        width = max(int(lens.max(initial=0)), 1)
        words = -(-width // 4)
        mat = np.zeros((len(enc), words * 4), dtype=np.uint8)
        for i, b in enumerate(enc):
            mat[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        u32 = mat.reshape(len(enc), words, 4).astype(np.uint32)
        folded = (u32[..., 0] | (u32[..., 1] << np.uint32(8)) |
                  (u32[..., 2] << np.uint32(16)) |
                  (u32[..., 3] << np.uint32(24)))
        h = lens.copy()
        for w in range(words):
            h = _fmix32(h ^ folded[:, w])
        return (h % np.uint32(num_buckets)).astype(np.int64)
    v = values.astype(np.float64, copy=True)
    # canonicalize -0.0 and NaN like the device partitioner
    v[np.isnan(v)] = 0.0
    v = v + 0.0
    bits = v.view(np.uint64)
    mixed = _fmix32((bits & np.uint64(0xFFFFFFFF)).astype(np.uint32)
                    ^ (bits >> np.uint64(32)).astype(np.uint32))
    return (mixed % np.uint32(num_buckets)).astype(np.int64)


def bucket_id_of(value, num_buckets: int) -> int:
    """Scalar wrapper used by read-side pruning."""
    return int(bucket_ids(np.array([value]), num_buckets)[0])


def write_spec(dir_path: str, column: str, num_buckets: int) -> None:
    with open(os.path.join(dir_path, SPEC_FILE), "w") as f:
        json.dump({"column": column, "num_buckets": num_buckets,
                   "version": 1}, f)


def read_spec(path: str) -> Optional[dict]:
    """Bucket spec of a table directory, or None."""
    if not os.path.isdir(path):
        return None
    spec_path = os.path.join(path, SPEC_FILE)
    if not os.path.exists(spec_path):
        return None
    with open(spec_path) as f:
        spec = json.load(f)
    if spec.get("version") != 1 or "column" not in spec \
            or "num_buckets" not in spec:
        return None
    return spec


def bucket_file(dir_path: str, bucket: int, file_format: str) -> str:
    return os.path.join(dir_path,
                        f"part-bucket-{bucket:05d}.{file_format}")


def prune_paths(paths: List[str], spec: dict, file_format: str,
                literal_value) -> Tuple[List[str], int]:
    """Paths for the single bucket that can contain literal_value.
    Returns (paths, bucket_id); missing files (empty buckets) drop out."""
    b = bucket_id_of(literal_value, spec["num_buckets"])
    f = bucket_file(paths[0], b, file_format)
    return ([f] if os.path.exists(f) else []), b
