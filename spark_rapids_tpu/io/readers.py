"""File scan execs (parquet / orc / csv) with pushdown.

Counterpart of the reference's L6 I/O layer (GpuParquetScan.scala 1,900 LoC,
GpuOrcScan.scala, GpuBatchScanExec.scala, GpuFileSourceScanExec.scala): the
host side parses footers, prunes row groups by predicate, discovers hive
partition values, and assembles host buffers (here: pyarrow, the parquet-mr
analog); the device side receives columnar uploads.  The three multi-file
strategies live in ``multifile.py``.

Predicate pushdown: supported filter subtrees are translated to pyarrow
dataset expressions (``to_arrow_filter``) — this subsumes the reference's
row-group statistics filtering AND applies exact filtering host-side; the
engine's own TpuFilterExec still runs above for semantics parity.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import rapids_conf as rc
from spark_rapids_tpu.exec.base import NUM_INPUT_BATCHES, Schema, TpuExec
from spark_rapids_tpu.ops import predicates as P
from spark_rapids_tpu.ops import stringops as S
from spark_rapids_tpu.ops.expressions import (
    Alias, BoundReference, Expression, Literal, UnresolvedColumn)
from spark_rapids_tpu.plan.logical import FileRelation


def _dataset(paths, file_format):
    import pyarrow.dataset as ds
    fmt = file_format
    if file_format == "csv":
        fmt = ds.CsvFileFormat()
    # a single path may be a directory (hive-partitioned dataset root);
    # pyarrow only accepts directories as a bare string
    src = paths[0] if len(paths) == 1 else paths
    return ds.dataset(src, format=fmt, partitioning="hive")


def infer_file_schema(paths: List[str], file_format: str) -> Schema:
    dataset = _dataset(paths, file_format)
    return [(f.name, dts.from_arrow_type(f.type)) for f in dataset.schema]


def scan_input_meta(paths: List[str]) -> List[tuple]:
    """Sorted ``(path, size_bytes, mtime_ns)`` triples for a scan's
    input file set — the identity of what a FileRelation will actually
    read, without opening a single footer.  Folded into
    stage-checkpoint lineage keys (robustness/checkpoint.py) so
    appending a file — or mutating one: new size, or a SAME-SIZE
    in-place rewrite, which only the mtime catches — invalidates
    exactly the scan-adjacent subtrees, and used by the
    incremental-ingest runner to detect out-of-band input mutation.
    (A touch without a content change forces a spurious recompute;
    degradation is always allowed, wrong bytes never are.)
    Unstattable paths fingerprint as (-1, -1) — a vanished file still
    changes the key."""
    import os

    def stat(p):
        try:
            st = os.stat(p)
            return (p, st.st_size, st.st_mtime_ns)
        except OSError:
            return (p, -1, -1)

    out = []
    for p in paths:
        if os.path.isdir(p):
            # hive-partitioned dataset root: the file set IS the input
            for root, _dirs, names in sorted(os.walk(p)):
                out.extend(stat(os.path.join(root, name))
                           for name in sorted(names))
            continue
        out.append(stat(p))
    return sorted(out)


def input_signature(meta: List[tuple]) -> str:
    """Canonical string form of a ``scan_input_meta`` result — THE one
    encoding of input identity, shared by the stage-lineage keys
    (checkpoint.input_fingerprint) and the incremental runner's
    state-staleness check so their invalidation rules can never
    silently diverge."""
    return ";".join(f"{p}={s}@{m}" for p, s, m in meta)


def to_arrow_filter(expr: Expression):
    """Translate a supported predicate subtree to a pyarrow expression;
    returns None when any part is untranslatable (the caller keeps the full
    engine-side filter either way)."""
    import pyarrow.dataset as ds
    import pyarrow.compute as pc

    def field(e):
        if isinstance(e, BoundReference):
            return ds.field(e.name)
        if isinstance(e, UnresolvedColumn):
            return ds.field(e.col_name)
        return None

    def lit(e):
        if isinstance(e, Literal) and not (
                e.dtype.is_string and e.value is None):
            return e.value
        return None

    def rec(e):
        if isinstance(e, P.And):
            l, r = rec(e.left), rec(e.right)
            return l & r if l is not None and r is not None else None
        if isinstance(e, P.Or):
            l, r = rec(e.left), rec(e.right)
            return (l | r) if l is not None and r is not None else None
        ops = {P.EqualTo: "__eq__", P.LessThan: "__lt__",
               P.LessThanOrEqual: "__le__", P.GreaterThan: "__gt__",
               P.GreaterThanOrEqual: "__ge__"}
        for cls, method in ops.items():
            if isinstance(e, cls):
                f, v = field(e.left), lit(e.right)
                if f is not None and v is not None:
                    return getattr(f, method)(v)
                f, v = field(e.right), lit(e.left)
                if f is not None and v is not None:
                    flipped = {"__lt__": "__gt__", "__le__": "__ge__",
                               "__gt__": "__lt__", "__ge__": "__le__",
                               "__eq__": "__eq__"}[method]
                    return getattr(f, flipped)(v)
                return None
        if isinstance(e, P.IsNull):
            f = field(e.child)
            return f.is_null() if f is not None else None
        if isinstance(e, P.IsNotNull):
            f = field(e.child)
            return f.is_valid() if f is not None else None
        if isinstance(e, P.In):
            f = field(e.children[0])
            vals = [lit(o) for o in e.children[1:]]
            if f is not None and all(v is not None for v in vals):
                return f.isin(vals)
            return None
        return None

    return rec(expr)


META_COLUMN_NAMES = frozenset({
    "__input_file_name", "_metadata.file_path", "_metadata.file_name",
    "_metadata.file_size", "_metadata.file_modification_time"})


class TpuFileScanExec(TpuExec):
    # each pull decodes + uploads a fresh batch; nothing is retained,
    # so downstream stages may donate these buffers
    ephemeral_output = True

    def __init__(self, paths: List[str], file_format: str, schema: Schema,
                 batch_rows: int = 1 << 20,
                 columns: Optional[List[str]] = None,
                 arrow_filter=None, reader_type: str = "AUTO",
                 num_threads: int = 8, max_files_parallel: int = 4,
                 file_meta=()):
        super().__init__()
        self.paths = paths
        self.file_format = file_format
        self._schema = list(schema)
        # per-file metadata columns requested (input_file_name /
        # _metadata struct); these never read from the files themselves
        self.file_meta = set(file_meta)
        # columns actually read; the rest are emitted as null placeholders
        # (pruning preserves the schema so bound ordinals stay valid)
        self.columns = [n for n, _ in schema
                        if (columns is None or n in columns)
                        and n not in META_COLUMN_NAMES]
        self.batch_rows = batch_rows
        self.arrow_filter = arrow_filter
        self.reader_type = reader_type
        self.num_threads = num_threads
        self.max_files_parallel = max_files_parallel
        self._register_metric(NUM_INPUT_BATCHES)

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        extra = ", pushdown" if self.arrow_filter is not None else ""
        return (f"TpuFileScanExec[{self.file_format}, {len(self.paths)} "
                f"files, {self.reader_type}{extra}]")

    def _finish_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Re-add pruned columns as all-null placeholders so the output
        matches the relation schema position-for-position."""
        if len(batch.names) == len(self._schema):
            return batch.select([n for n, _ in self._schema]) \
                if batch.names != [n for n, _ in self._schema] else batch
        import jax.numpy as jnp
        from spark_rapids_tpu.columnar.column import Column
        cols = {}
        cap = batch.capacity
        for name, dt in self._schema:
            if name in batch.columns:
                cols[name] = batch.columns[name]
            elif dt.is_string:
                c = Column.from_strings([None] * batch.nrows, capacity=cap)
                cols[name] = c
            else:
                cols[name] = Column(
                    dt, jnp.zeros(cap, dtype=dt.storage), batch.nrows,
                    validity=jnp.zeros(cap, dtype=jnp.bool_))
        return ColumnarBatch(cols, batch.nrows)

    def _attach_meta(self, batch: ColumnarBatch, path: str
                     ) -> ColumnarBatch:
        import os
        from spark_rapids_tpu.columnar.column import Column
        cols = dict(batch.columns)
        n, cap = batch.nrows, batch.capacity
        if "input_file" in self.file_meta:
            cols["__input_file_name"] = Column.from_strings(
                [path] * n, capacity=cap)
        if "metadata" in self.file_meta:
            import jax.numpy as jnp
            st = os.stat(path)
            cols["_metadata.file_path"] = Column.from_strings(
                [os.path.abspath(path)] * n, capacity=cap)
            cols["_metadata.file_name"] = Column.from_strings(
                [os.path.basename(path)] * n, capacity=cap)
            cols["_metadata.file_size"] = Column(
                dts.INT64, jnp.full(cap, st.st_size, dtype=jnp.int64), n)
            cols["_metadata.file_modification_time"] = Column(
                dts.TIMESTAMP_US,
                jnp.full(cap, int(st.st_mtime * 1e6), dtype=jnp.int64), n)
        return ColumnarBatch(cols, n)

    def _per_file_scan(self) -> Iterator[ColumnarBatch]:
        """Metadata columns need per-file batch attribution: each
        dataset fragment reads and chunks independently (fragment reads
        keep hive partition columns), its constant meta columns ride
        every chunk."""
        dataset = _dataset(self.paths, self.file_format)
        for frag in dataset.get_fragments(filter=self.arrow_filter):
            table = frag.to_table(schema=dataset.schema,
                                  columns=self.columns,
                                  filter=self.arrow_filter)
            for off in range(0, table.num_rows, self.batch_rows):
                chunk = table.slice(off, self.batch_rows)
                if not chunk.num_rows:
                    continue
                self.metrics[NUM_INPUT_BATCHES] += 1
                yield self._finish_batch(self._attach_meta(
                    ColumnarBatch.from_arrow(chunk), frag.path))

    def do_execute(self) -> Iterator[ColumnarBatch]:
        # "io.read" fires once per produced batch, so chaos tests can
        # kill a scan mid-stream; recovery is query-level (the
        # QueryRetryDriver re-drives the whole plan — scans re-read).
        # Each pull runs under an "io.reader" watchdog section: a
        # stalled decode (slow object store, wedged reader pool
        # thread) overruns its deadline and the monitor converts the
        # hang into a retryable TimeoutFault at the next checkpoint
        from spark_rapids_tpu.robustness import watchdog
        from spark_rapids_tpu.robustness.inject import fire
        it = self._scan_batches()
        while True:
            with watchdog.section("io.reader"):
                batch = next(it, None)
                if batch is not None:
                    fire("io.read")
            if batch is None:
                return
            yield batch

    def _scan_batches(self) -> Iterator[ColumnarBatch]:
        if not self.paths:
            # bucket pruning eliminated every file
            return
        if self.file_meta:
            yield from self._per_file_scan()
            return
        if self.file_format == "csv" or len(self.paths) == 1:
            yield from self._simple_scan()
            return
        from spark_rapids_tpu.io.multifile import iter_file_tables
        for table in iter_file_tables(
                self.paths, self.file_format, self.columns,
                self.arrow_filter, self.reader_type, self.batch_rows,
                self.num_threads, self.max_files_parallel):
            self.metrics[NUM_INPUT_BATCHES] += 1
            for off in range(0, table.num_rows, self.batch_rows):
                chunk = table.slice(off, self.batch_rows)
                if chunk.num_rows:
                    yield self._finish_batch(ColumnarBatch.from_arrow(chunk))

    def _simple_scan(self) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        dataset = _dataset(self.paths, self.file_format)
        kwargs = {"columns": self.columns, "batch_size": self.batch_rows}
        if self.arrow_filter is not None:
            kwargs["filter"] = self.arrow_filter
        for record_batch in dataset.to_batches(**kwargs):
            if record_batch.num_rows == 0:
                continue
            self.metrics[NUM_INPUT_BATCHES] += 1
            yield self._finish_batch(ColumnarBatch.from_arrow(
                pa.Table.from_batches([record_batch])))


def _bucket_pruned_paths(node: FileRelation) -> List[str]:
    """Bucket pruning: an equality filter on the bucket column narrows
    the scan to that bucket's file (GpuFileSourceScanExec bucket-pruning
    analog, spec from the _bucket_spec.json sidecar)."""
    from spark_rapids_tpu.io import bucketing as B
    spec = node.bucket_spec
    if not spec:
        return node.paths
    col = spec["column"]

    def name_of(e):
        if isinstance(e, BoundReference):
            return e.name
        if isinstance(e, UnresolvedColumn):
            return e.col_name
        return None

    for f in node.pushed_filters:
        if not isinstance(f, P.EqualTo):
            continue
        for a, b in ((f.left, f.right), (f.right, f.left)):
            if name_of(a) == col and isinstance(b, Literal) \
                    and b.value is not None:
                pruned, _ = B.prune_paths(node.paths, spec,
                                          node.file_format, b.value)
                return pruned
    return node.paths


def make_file_scan_exec(node: FileRelation, conf) -> TpuFileScanExec:
    arrow_filter = None
    for f in node.pushed_filters:
        af = to_arrow_filter(f)
        if af is not None:
            arrow_filter = af if arrow_filter is None else \
                (arrow_filter & af)
    fmt = node.file_format
    return TpuFileScanExec(
        _bucket_pruned_paths(node), node.file_format, node.schema,
        columns=sorted(node.required_columns)
        if getattr(node, "required_columns", None) else None,
        arrow_filter=arrow_filter,
        file_meta=node.file_meta,
        batch_rows=conf["spark.rapids.sql.reader.batchSizeRows"],
        reader_type=conf[
            f"spark.rapids.sql.format.{fmt}.reader.type"],
        num_threads=conf[
            f"spark.rapids.sql.format.{fmt}.multiThreadedRead."
            "numThreads"],
        max_files_parallel=conf[
            f"spark.rapids.sql.format.{fmt}.multiThreadedRead."
            "maxNumFilesParallel"])
