"""File scan execs (parquet / csv / orc).

Round-1 shape of the reference's L6 I/O layer (GpuParquetScan.scala,
GpuOrcScan.scala, GpuBatchScanExec.scala): host-side parse via pyarrow —
the parquet-mr/footers analog — then device upload of columnar batches.
Column pruning happens at the pyarrow level; the multi-file COALESCING /
MULTITHREADED strategies and predicate pushdown land with the full io task.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.plan.logical import FileRelation


def infer_file_schema(paths: List[str], file_format: str) -> Schema:
    import pyarrow.dataset as ds
    dataset = ds.dataset(paths, format=file_format)
    return [(f.name, dts.from_arrow_type(f.type)) for f in dataset.schema]


class TpuFileScanExec(TpuExec):
    def __init__(self, paths: List[str], file_format: str, schema: Schema,
                 batch_rows: int = 1 << 20,
                 columns: Optional[List[str]] = None):
        super().__init__()
        self.paths = paths
        self.file_format = file_format
        self._schema = [s for s in schema
                        if columns is None or s[0] in columns]
        self.batch_rows = batch_rows

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        return (f"TpuFileScanExec[{self.file_format}, {len(self.paths)} "
                f"files]")

    def do_execute(self) -> Iterator[ColumnarBatch]:
        import pyarrow.dataset as ds
        dataset = ds.dataset(self.paths, format=self.file_format)
        names = [n for n, _ in self._schema]
        for record_batch in dataset.to_batches(columns=names,
                                               batch_size=self.batch_rows):
            if record_batch.num_rows == 0:
                continue
            import pyarrow as pa
            yield ColumnarBatch.from_arrow(
                pa.Table.from_batches([record_batch]))


def make_file_scan_exec(node: FileRelation, conf) -> TpuFileScanExec:
    return TpuFileScanExec(node.paths, node.file_format, node.schema)
