"""Batch coalescing: CoalesceGoal + the concatenating iterator.

Counterpart of GpuCoalesceBatches.scala (CoalesceGoal:142 TargetSize /
RequireSingleBatch, AbstractGpuCoalesceIterator:195): accumulate small
batches until a size goal is met, concatenating on device.  Pending batches
are registered spillable so a long accumulation can't pin HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory.spill import (
    AGGREGATE_INTERMEDIATE_PRIORITY, SpillableBatchCatalog, default_catalog)
from spark_rapids_tpu.ops.concat import concat_batches


class CoalesceGoal:
    pass


@dataclasses.dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    bytes: int = 1 << 31


class RequireSingleBatch(CoalesceGoal):
    pass


def coalesce_iterator(batches: Iterator[ColumnarBatch], goal: CoalesceGoal,
                      catalog: Optional[SpillableBatchCatalog] = None
                      ) -> Iterator[ColumnarBatch]:
    catalog = catalog or default_catalog()
    pending = []
    pending_bytes = 0
    target = goal.bytes if isinstance(goal, TargetSize) else None

    def flush():
        nonlocal pending, pending_bytes
        if not pending:
            return None
        got = [h.materialize() for h in pending]
        for h in pending:
            h.close()
        pending = []
        pending_bytes = 0
        return concat_batches(got) if len(got) > 1 else got[0]

    try:
        for batch in batches:
            # only skip KNOWN-empty batches: forcing a deferred
            # (device-resident) count here would cost the per-batch
            # round trip this path exists to avoid — concat_batches
            # handles lazy counts natively
            if batch.row_count.is_concrete and batch.nrows == 0:
                continue
            # a shuffle-received batch still pins its packed exchange
            # payload (ColumnarBatch.transient_wire_bytes) — the goal
            # accounting must see the true HBM footprint or a long
            # accumulation right after an exchange undercounts by ~2x
            size = batch.device_size_bytes() + \
                int(getattr(batch, "transient_wire_bytes", 0) or 0)
            if target is not None and pending and \
                    pending_bytes + size > target:
                out = flush()
                if out is not None:
                    yield out
            pending.append(catalog.register(
                batch, AGGREGATE_INTERMEDIATE_PRIORITY))
            pending_bytes += size
        out = flush()
        if out is not None:
            yield out
    finally:
        # early generator close (LIMIT upstream, consumer exception):
        # unregister still-pending spillables so the catalog never
        # carries dead registrations for the rest of the session
        for h in pending:
            h.close()
        pending = []
