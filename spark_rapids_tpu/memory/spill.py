"""Tiered spill framework: HBM -> host RAM -> disk.

Counterpart of the reference's RapidsBufferCatalog / RapidsBufferStore chain
(RapidsBufferCatalog.scala:40, RapidsBufferStore.scala:41, Device/Host/Disk
stores) and SpillableColumnarBatch (SpillableColumnarBatch.scala:29), with
one structural difference dictated by the platform: XLA owns HBM and there
is no RMM-style allocation-failure callback, so spilling is *watermark-
driven* — the catalog tracks bytes held by spillable batches and proactively
moves the lowest-priority ones to host (numpy) and then disk (npz files)
when the budget is exceeded.  The analog of the reference's
``DeviceMemoryEventHandler.onAllocFailure`` retry loop is
``ensure_budget()``, which callers invoke before large allocations.

Spill priorities mirror SpillPriorities.scala: shuffle outputs coldest,
actively-iterated batches hottest.
"""

from __future__ import annotations

import heapq
import itertools
import os
import tempfile
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column

# storage tiers (RapidsBuffer.scala:53 StorageTier)
DEVICE = "DEVICE"
HOST = "HOST"
DISK = "DISK"

# spill priorities (SpillPriorities.scala:26-61)
OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY = -1000
AGGREGATE_INTERMEDIATE_PRIORITY = 0
ACTIVE_ON_DECK_PRIORITY = 1000
# stage checkpoints register at or below this priority
# (robustness/checkpoint.py CHECKPOINT_PRIORITY); the cross-query
# eviction floor applies to handles in this class
CHECKPOINT_TIER_MAX = -1500
# session-persistent incremental-ingest state (robustness/incremental.py)
# is the coldest class of all: standing state outlives any one query, so
# under HBM pressure it leaves the device before even per-query
# checkpoints — restores pay a host round trip, live queries never wait
INCREMENTAL_STATE_PRIORITY = -2000


class IntegrityMetrics:
    """Process-wide spill-integrity counters (checksum verification
    failures per tier), surfaced by tools/profiling."""

    def __init__(self):
        self._lock = threading.Lock()
        self.corruption_counts: Dict[str, int] = {}

    def bump(self, tier: str) -> None:
        with self._lock:
            self.corruption_counts[tier] = \
                self.corruption_counts.get(tier, 0) + 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.corruption_counts)

    def reset(self) -> None:
        with self._lock:
            self.corruption_counts.clear()


integrity_metrics = IntegrityMetrics()


def _payload_checksum(payload: dict, nrows: int) -> int:
    """crc32 over the host payload in canonical form: buffer keys in
    sorted order, every buffer's raw bytes, plus the row count — so
    any single flipped bit anywhere fails verification.  Canonical
    means identical across representations of the same batch: non-
    array entries and zero-length buffers are skipped (the disk frame
    codec stores empty buffers as absent, and ``__nrows`` rides the
    handle, not the restored dict)."""
    crc = zlib.crc32(str(int(nrows)).encode())
    for key in sorted(payload):
        v = payload[key]
        if not isinstance(v, np.ndarray) or v.size == 0:
            continue
        crc = zlib.crc32(key.encode(), crc)
        a = np.ascontiguousarray(v)
        crc = zlib.crc32(a.view(np.uint8).reshape(-1), crc)
    return crc & 0xFFFFFFFF


def _emit_corruption(tier: str, buf_id: int, detail: str) -> None:
    """Count + event-log a checksum failure (SpillCorruption events
    feed the profiling health check with per-query attribution)."""
    integrity_metrics.bump(tier)
    from spark_rapids_tpu.utils.events import emit_on_session
    emit_on_session("SpillCorruption", tier=tier, bufId=buf_id,
                    detail=detail)


class SpillableHandle:
    """One registered batch, resident at exactly one tier."""

    _ids = itertools.count()

    def __init__(self, catalog: "SpillableBatchCatalog",
                 batch: ColumnarBatch, priority: int,
                 owner: Optional[int] = None):
        self.id = next(SpillableHandle._ids)
        self.catalog = catalog
        self.priority = priority
        # owning query (the QueryContext owner ident that registered
        # this batch; None outside any query scope).  Drives per-owner
        # budgets and cross-query eviction-floor isolation.
        self.owner = owner
        self.tier = DEVICE
        self.size_bytes = batch.device_size_bytes()
        # transient shuffle-wire reservation (ColumnarBatch
        # .transient_wire_bytes): a just-received exchange batch still
        # pins its packed lane payloads in HBM, so backpressure must
        # see the larger footprint while the batch sits at DEVICE.  The
        # payload is never spilled — it dies with the exchange program
        # — so leaving DEVICE releases the reservation for good.
        self.wire_bytes = int(
            getattr(batch, "transient_wire_bytes", 0) or 0)
        self.last_access = 0
        self._device: Optional[ColumnarBatch] = batch
        self._host: Optional[dict] = None
        # HOST tier, compressed form: when the catalog's host codec is
        # on, the payload lives as ONE frame-codec blob (the same
        # self-describing frame format the DISK tier writes) instead of
        # raw numpy buffers — checkpoints and incremental state demote
        # through this catalog, so they inherit the codec for free
        self._host_frame: Optional[bytes] = None
        self._host_stored = 0
        self._disk_path: Optional[str] = None
        # crc32 of the host payload, stamped when the batch leaves
        # DEVICE and verified on every HOST->DEVICE / DISK->HOST
        # restore (None until first spill, or with integrity off)
        self._integrity_crc: Optional[int] = None
        self._schema = batch.schema
        self._capacity = batch.capacity
        # deferred (device-resident) counts stay deferred while the
        # batch sits at the DEVICE tier; spilling materializes (the
        # host payload needs the concrete count anyway)
        self._row_count = batch.row_count
        self.closed = False

    @property
    def nrows(self) -> int:
        return int(self._row_count)

    @property
    def row_count(self):
        return self._row_count

    @property
    def nrows_bound(self) -> int:
        """Sync-free upper bound on nrows (capacity when deferred)."""
        if self._row_count.is_concrete:
            return int(self._row_count)
        return self._capacity

    # -------------------------------------------------------------- movement --
    def _to_host_payload(self) -> dict:
        b = self._device
        payload = {"__nrows": self.nrows}
        for name, col in b.columns.items():
            # host_* readers keep still-host columns bit-exact and skip
            # the device fetch entirely
            payload[f"{name}.data"] = col.host_values()
            v = col.host_validity()
            if v is not None:
                payload[f"{name}.validity"] = v
            o = col.host_offsets()
            if o is not None:
                payload[f"{name}.offsets"] = o
        return payload

    def _rebuild(self, get) -> ColumnarBatch:
        cols = {}
        for name, dt in self._schema:
            data = get(f"{name}.data")
            if data is None:
                # the frame codec stores zero-length buffers as absent
                # (lens=0); a legitimately empty buffer (e.g. the chars of
                # an all-empty string column) must round-trip as empty, not
                # as None -> asarray(None) crash
                data = np.zeros(
                    0, dtype=np.uint8 if dt.is_string else dt.storage)
            # hand the host buffers straight to Column: it materializes
            # the device copy lazily on first device use
            cols[name] = Column(
                dt, np.ascontiguousarray(data), self.nrows,
                validity=get(f"{name}.validity"),
                offsets=get(f"{name}.offsets"))
        return ColumnarBatch(cols, self.nrows)

    def _frame_columns(self, payload: dict):
        """(dtype_code, data, validity, offsets) per schema column —
        the native frame codec's input layout."""
        from spark_rapids_tpu import native
        return [(native.dtype_code(dt),
                 payload.get(f"{name}.data"),
                 payload.get(f"{name}.validity"),
                 payload.get(f"{name}.offsets"))
                for name, dt in self._schema]

    def _payload_from_frame(self, blob: bytes) -> dict:
        """Decode a self-describing frame blob back into the canonical
        payload dict (raises on a frame that no longer decodes — the
        caller converts that into CorruptionFault)."""
        from spark_rapids_tpu import native
        _, cols = native.deserialize_batch(blob)
        payload = {}
        for (name, dt), (_, d, v, o) in zip(self._schema, cols):
            if d is not None:
                payload[f"{name}.data"] = d if dt.is_string else \
                    d.view(dt.storage)
            if v is not None:
                payload[f"{name}.validity"] = v.view(np.bool_)
            if o is not None:
                payload[f"{name}.offsets"] = o.view(np.int32)
        return payload

    @property
    def stored_bytes(self) -> int:
        """Bytes this handle actually occupies at its current tier —
        the encoded frame size at HOST (codec on) / DISK, the device
        size otherwise.  Budget consumers that meter STANDING state
        (checkpoint.maxBytes, incremental.maxStateBytes) read this so
        compression buys proportionally more retained state."""
        if self.tier == HOST and self._host_frame is not None:
            return self._host_stored
        if self.tier == DISK and self._host_stored:
            return self._host_stored
        return self.size_bytes

    def spill_to_host(self) -> int:
        """Demote to HOST; returns the DEVICE bytes released (the batch
        plus any transient wire reservation — the wire headroom never
        follows the batch to the host tier).  With the catalog's host
        codec on, the payload is kept as ONE compressed frame blob; the
        integrity crc is stamped over the DECODED canonical bytes
        BEFORE encoding, so verification semantics are unchanged."""
        assert self.tier == DEVICE
        payload = self._to_host_payload()
        if self.catalog.integrity_check:
            # stamped exactly once, when the bytes leave the device:
            # every later restore (host or disk) verifies against this
            self._integrity_crc = _payload_checksum(payload, self.nrows)
        if self.catalog.host_codec:
            from spark_rapids_tpu import native
            blob = native.serialize_batch(
                self.nrows, self._frame_columns(payload),
                compress=self.catalog.host_codec)
            self._host_frame = blob
            self._host_stored = len(blob)
            self.catalog.note_host_encoding(self.size_bytes, len(blob))
        else:
            self._host = payload
        self._device = None
        self.tier = HOST
        released = self.size_bytes + self.wire_bytes
        self.wire_bytes = 0
        return released

    def spill_to_disk(self) -> int:
        assert self.tier == HOST
        from spark_rapids_tpu import native
        from spark_rapids_tpu.robustness.faults import SpillIOError
        from spark_rapids_tpu.robustness.inject import fire
        # "spill.disk" fires before any state moves: on failure the
        # batch is still intact at the HOST tier, nothing is lost, and
        # the query driver can retry the whole query
        fire("spill.disk")
        path = os.path.join(self.catalog.spill_dir, f"buf-{self.id}.tcf")
        if self._host_frame is not None:
            # already a self-describing frame (compressed host tier):
            # the disk write is a straight page-out, no re-encode
            blob = self._host_frame
        else:
            blob = native.serialize_batch(
                self.nrows, self._frame_columns(self._host),
                compress=self.catalog.frame_codec)
            self._host_stored = len(blob)
        # torn-write-proof: stage to a temp file, fsync, then rename
        # into place.  A crash anywhere before the rename leaves no
        # file at ``path``, so a partial frame is never restorable.
        tmp = path + ".tmp"
        try:
            os.makedirs(self.catalog.spill_dir, exist_ok=True)
            native.write_spill_file(tmp, blob)
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, path)
        except OSError as e:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
            # disk full / unreachable: re-type for the fault taxonomy
            # (retryable — the host copy is untouched)
            raise SpillIOError(
                f"disk spill of buf-{self.id} failed: {e}") from e
        self._disk_path = path
        self._host = None
        self._host_frame = None
        self.tier = DISK
        return self.size_bytes

    def _verify_payload(self, payload: dict, tier: str) -> None:
        """Checksum gate on every restore: a mismatch DROPS the batch
        (close unlinks any disk file and deregisters) and raises a
        degradable CorruptionFault — the ladder re-runs from source;
        wrong bytes are never returned."""
        if not self.catalog.integrity_check or \
                self._integrity_crc is None:
            return
        got = _payload_checksum(payload, self.nrows)
        if got == self._integrity_crc:
            return
        detail = (f"buf-{self.id}: crc {got:#010x} != stored "
                  f"{self._integrity_crc:#010x}")
        self.close()
        _emit_corruption(tier, self.id, detail)
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        raise CorruptionFault(tier, detail)

    def materialize(self) -> ColumnarBatch:
        """Get the batch back on device (unspilling if needed)."""
        if self.closed:
            raise ValueError("spillable batch already closed")
        self.last_access = self.catalog.next_access_stamp()
        if self.tier == DEVICE:
            return self._device
        from spark_rapids_tpu.utils import tracing
        if tracing._armed:
            with tracing.span(f"spill.restore.{self.tier.lower()}"):
                return self._materialize_cold()
        return self._materialize_cold()

    def _materialize_cold(self) -> ColumnarBatch:
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        from spark_rapids_tpu.robustness.inject import fire_mutate
        if self.tier == HOST:
            if self._host_frame is not None:
                # compressed host tier: the chaos hook mutates the
                # frame bytes (as on disk); a frame that no longer
                # decodes is corruption — drop, never guess at bytes
                blob = fire_mutate("spill.corrupt.host",
                                   self._host_frame)
                try:
                    payload = self._payload_from_frame(blob)
                except Exception as e:
                    detail = (f"buf-{self.id}: host frame decode "
                              f"failed: {e}")
                    self.close()
                    _emit_corruption(HOST, self.id, detail)
                    raise CorruptionFault(HOST, detail) from e
            else:
                payload = self._corrupt_point(self._host,
                                              "spill.corrupt.host")
            self._verify_payload(payload, HOST)
            batch = self._rebuild(lambda k: payload.get(k))
        else:
            from spark_rapids_tpu import native
            from spark_rapids_tpu.robustness.faults import SpillIOError
            try:
                blob = native.read_spill_file(self._disk_path)
            except OSError as e:
                raise SpillIOError(
                    f"disk unspill of buf-{self.id} failed: {e}") from e
            blob = fire_mutate("spill.corrupt.disk", blob)
            try:
                payload = self._payload_from_frame(blob)
            except OSError:
                raise
            except Exception as e:
                # a frame that no longer decodes IS corruption (a
                # flipped bit in the compressed stream): drop the
                # batch, never guess at bytes
                detail = f"buf-{self.id}: frame decode failed: {e}"
                self.close()
                _emit_corruption(DISK, self.id, detail)
                raise CorruptionFault(DISK, detail) from e
            self._verify_payload(payload, DISK)
            batch = self._rebuild(lambda k: payload.get(k))
        self.catalog.unspill(self, batch)
        return batch

    @staticmethod
    def _corrupt_point(payload: dict, point: str) -> dict:
        """Chaos hook: offer ONE payload buffer (the first data buffer
        in canonical order) to an armed corrupt rule.  The mutated copy
        replaces the buffer in a shallow-copied dict — the restore sees
        rot, the stored payload object itself is untouched."""
        from spark_rapids_tpu.robustness.inject import fire_mutate
        key = next((k for k in sorted(payload)
                    if isinstance(payload[k], np.ndarray)
                    and payload[k].size > 0), None)
        if key is None:
            return payload
        mutated = fire_mutate(point, payload[key])
        if mutated is payload[key]:
            return payload
        payload = dict(payload)
        payload[key] = mutated
        return payload

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._device = None
        self._host = None
        self._host_frame = None
        try:
            if self._disk_path and os.path.exists(self._disk_path):
                os.unlink(self._disk_path)
        except OSError:
            # the catalog's session-close sweep collects stragglers a
            # racing unlink left behind
            pass
        finally:
            # deregistration must survive an unlink failure, else the
            # dead handle pins catalog counters for the session's life
            self._disk_path = None
            self.catalog.remove(self)


class SpillableBatchCatalog:
    """Singleton-ish registry with watermark-driven tier demotion.

    ``device_budget``: bytes of HBM this engine lets spillable batches pin
    before demoting the coldest to host; ``host_budget``: same for host RAM
    before demoting to disk (reference `memory.host.spillStorageSize`).
    """

    def __init__(self, device_budget: int = 1 << 34,
                 host_budget: int = 1 << 30,
                 spill_dir: Optional[str] = None,
                 frame_codec: int = 2,
                 disk_write_threads: int = 2,
                 integrity_check: bool = True,
                 checkpoint_floor: int = 0,
                 host_codec: int = 0):
        self.device_budget = device_budget
        self.host_budget = host_budget
        # cross-query isolation floor: device pressure originating
        # from one owner may not demote ANOTHER owner's checkpoint-
        # priority handles below this many device-resident bytes
        # (spark.rapids.tpu.serving.checkpointEvictionFloorBytes)
        self.checkpoint_floor = int(checkpoint_floor)
        # per-owner DEVICE-tier byte budgets (QueryContext installs
        # one for the duration of its query when
        # serving.queryMemoryBudgetBytes is set)
        self._owner_budgets: Dict[int, int] = {}
        # incremental per-owner DEVICE-tier byte counters, maintained
        # alongside device_bytes at every tier transition so the
        # per-register budget check is O(1), not a catalog scan
        self._owner_device: Dict[int, int] = {}
        # spark.rapids.memory.spill.integrityCheck.enabled: checksum
        # every payload leaving DEVICE, verify on every restore
        self.integrity_check = bool(integrity_check)
        # only a directory this catalog created gets rmdir'd at close
        self._owns_spill_dir = spill_dir is None
        # host->disk demotions overlap in a small writer pool: the
        # native pager releases the GIL for serialize+write
        # (spark.rapids.memory.spill.diskWriteThreads)
        self.disk_write_threads = max(int(disk_write_threads), 1)
        # per-session frame codec level for spilled/cached frames
        # (0 raw / 1 zrle / 2 zrle+lzb); sessions set this from
        # spark.rapids.shuffle.compression.codec
        self.frame_codec = frame_codec
        # HOST-tier codec level (spark.rapids.tpu.encoding.storage.
        # hostCodec): 0 keeps raw numpy payloads; >0 stores host-tier
        # payloads as compressed frame blobs (checkpoints and
        # incremental state inherit this — the one shared codec layer)
        self.host_codec = int(host_codec)
        # raw vs encoded host-frame byte totals (bench
        # state_bytes_raw/compressed and the profiling storage line).
        # Own lock: note_host_encoding is called from spill_to_host,
        # which may run UNDER the catalog lock (demote/_spill_tier) —
        # re-taking the non-reentrant catalog lock would deadlock
        self._enc_lock = threading.Lock()
        self.host_raw_bytes_total = 0
        self.host_encoded_bytes_total = 0
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="tpu-spill-")
        # warm the native library now: its first load may shell out to g++
        # (up to ~2min); doing it lazily inside spill_to_disk would stall
        # every thread behind the catalog lock
        from spark_rapids_tpu import native
        native.available()
        self._lock = threading.Lock()
        self._handles: Dict[int, SpillableHandle] = {}
        # every handle id THIS catalog ever issued: close()'s orphan
        # sweep is scoped to these, so two catalogs sharing a spill
        # dir can never unlink each other's live frames
        self._issued_ids: set = set()
        self.device_bytes = 0
        self.host_bytes = 0
        self.disk_bytes = 0
        self.spilled_to_host_total = 0
        self.spilled_to_disk_total = 0
        self._access_counter = itertools.count(1)

    def next_access_stamp(self) -> int:
        return next(self._access_counter)

    def note_host_encoding(self, raw: int, encoded: int) -> None:
        """Cumulative raw->encoded attribution for host-tier frames
        (called by the handle on each compressed demotion, possibly
        under the catalog lock — see _enc_lock)."""
        with self._enc_lock:
            self.host_raw_bytes_total += int(raw)
            self.host_encoded_bytes_total += int(encoded)

    # ------------------------------------------------------------- interface --
    def register(self, batch: ColumnarBatch,
                 priority: int = AGGREGATE_INTERMEDIATE_PRIORITY,
                 owner: Optional[int] = None) -> SpillableHandle:
        if owner is None:
            # auto-tag with the registering query's scope (covers the
            # pipeline worker, checkpoint saves, coalesce — any site
            # running inside, or adopted into, a QueryContext)
            from spark_rapids_tpu.serving import context as _qc
            ctx = _qc.current()
            owner = ctx.owner_ident if ctx is not None else None
        from spark_rapids_tpu.utils import tracing
        if tracing._armed:
            with tracing.span("spill.register"):
                return self._register_impl(batch, priority, owner)
        return self._register_impl(batch, priority, owner)

    def _register_impl(self, batch: ColumnarBatch, priority: int,
                       owner: Optional[int]) -> SpillableHandle:
        h = SpillableHandle(self, batch, priority, owner=owner)
        with self._lock:
            self._handles[h.id] = h
            self._issued_ids.add(h.id)
            self.device_bytes += h.size_bytes + h.wire_bytes
            self._owner_device_adjust(h.owner,
                                      h.size_bytes + h.wire_bytes)
        # the wire reservation is consumed by registration: a later
        # re-registration of the same batch (coalesce after pipeline)
        # must not re-reserve the exchange payload headroom
        if h.wire_bytes:
            batch.transient_wire_bytes = 0
        self.ensure_budget(for_owner=owner)
        self._enforce_owner_budget(h)
        return h

    # ------------------------------------------------------- per-owner budgets --
    def set_owner_budget(self, owner: int, budget: int) -> None:
        with self._lock:
            self._owner_budgets[owner] = int(budget)

    def clear_owner_budget(self, owner: int) -> None:
        with self._lock:
            self._owner_budgets.pop(owner, None)

    def owner_device_bytes(self, owner: int) -> int:
        with self._lock:
            return self._owner_device_bytes(owner)

    def _owner_device_bytes(self, owner: int) -> int:
        return self._owner_device.get(owner, 0)

    def _owner_device_adjust(self, owner: Optional[int],
                             delta: int) -> None:
        """Mirror every DEVICE-tier byte movement into the per-owner
        counter (called wherever ``device_bytes`` changes)."""
        if owner is None:
            return
        new = self._owner_device.get(owner, 0) + delta
        if new:
            self._owner_device[owner] = new
        else:
            self._owner_device.pop(owner, None)

    def _demote_to_host_locked(self, h: SpillableHandle) -> int:
        """One DEVICE->HOST transition with all its accounting (caller
        holds the lock).  Returns the device bytes freed — the batch
        plus any transient wire reservation; only the batch payload
        itself lands on the host tier."""
        from spark_rapids_tpu.utils import tracing
        with tracing.span("spill.demote.host"):
            freed = h.spill_to_host()
        self.device_bytes -= freed
        self._owner_device_adjust(h.owner, -freed)
        self.host_bytes += h.size_bytes
        self.spilled_to_host_total += h.size_bytes
        return freed

    def _enforce_owner_budget(self, h: SpillableHandle) -> None:
        """The per-query memory-budget ladder, run after each of the
        owner's registrations: over budget, the owner's OWN coldest
        device handles demote to host (degrade — other queries'
        batches are untouched); when self-spilling everything else
        still leaves the owner over (the new batch alone busts the
        budget), the owning query is rejected with a typed
        BudgetExhaustedFault.  Other queries never pay."""
        owner = h.owner
        if owner is None:
            return
        with self._lock:
            budget = self._owner_budgets.get(owner)
            if budget is None or self._owner_device_bytes(owner) <= budget:
                return
            victims = sorted(
                (x for x in self._handles.values()
                 if x.owner == owner and x.tier == DEVICE
                 and x.id != h.id),
                key=lambda x: (x.priority, x.last_access, x.id))
            used = self._owner_device_bytes(owner)
            for v in victims:
                if used <= budget:
                    break
                used -= self._demote_to_host_locked(v)
            spilled = bool(victims)
            over = used > budget
        if spilled:
            # the self-spill may push the HOST tier over ITS watermark
            self.ensure_budget(for_owner=owner)
        from spark_rapids_tpu.serving import context as _qc
        ctx = _qc.current()
        if ctx is None:
            return
        if spilled:
            ctx.note_memory_pressure(used, spilled=True)
        if over:
            # the rejection propagates out of register(): the caller
            # never receives the handle, so it must not stay in the
            # catalog — a leaked registration would pin its bytes for
            # the session's life and bill spurious pressure to the
            # NEXT query on a recycled thread ident
            self.remove(h)
            ctx.note_memory_pressure(used, spilled=False)  # raises

    def unspill(self, h: SpillableHandle, batch: ColumnarBatch) -> None:
        """Promote back to DEVICE after materialize (shouldUnspill=true
        behavior, RapidsBufferCatalog.scala)."""
        with self._lock:
            if h.tier == HOST:
                self.host_bytes -= h.size_bytes
            elif h.tier == DISK:
                self.disk_bytes -= h.size_bytes
                if h._disk_path and os.path.exists(h._disk_path):
                    os.unlink(h._disk_path)
                    h._disk_path = None
            h.tier = DEVICE
            h._device = batch
            h._host = None
            h._host_frame = None
            self.device_bytes += h.size_bytes
            self._owner_device_adjust(h.owner, h.size_bytes)
        self.ensure_budget(for_owner=h.owner)

    def remove(self, h: SpillableHandle) -> None:
        with self._lock:
            if h.id not in self._handles:
                return
            del self._handles[h.id]
            if h.tier == DEVICE:
                self.device_bytes -= h.size_bytes + h.wire_bytes
                self._owner_device_adjust(
                    h.owner, -(h.size_bytes + h.wire_bytes))
            elif h.tier == HOST:
                self.host_bytes -= h.size_bytes
            else:
                self.disk_bytes -= h.size_bytes

    def demote(self, h: SpillableHandle, target: str) -> None:
        """Push one handle down to ``target`` tier immediately,
        independent of the watermark loop (the checkpoint tier policy:
        payloads whose conf excludes DEVICE residency leave HBM at
        registration instead of waiting for pressure).  No-op for a
        closed/foreign handle or a tier at/below the current one."""
        if target not in (HOST, DISK):
            return
        with self._lock:
            if h.closed or h.id not in self._handles:
                return
            if h.tier == DEVICE:
                self._demote_to_host_locked(h)
            if h.tier == HOST and target == DISK:
                freed = h.spill_to_disk()
                self.host_bytes -= freed
                self.disk_bytes += freed
                self.spilled_to_disk_total += freed

    def ensure_budget(self, extra_needed: int = 0,
                      for_owner: Optional[int] = None) -> None:
        """Demote coldest handles until budgets hold (the synchronousSpill
        loop, RapidsBufferStore.scala:146).  ``for_owner`` attributes
        the pressure to the query that caused it: that owner's own
        handles demote first, and other owners' checkpoint-priority
        payloads are protected by the eviction floor."""
        with self._lock:
            self._spill_tier(DEVICE, self.device_budget - extra_needed,
                             for_owner)
            self._spill_tier(HOST, self.host_budget)

    def _floor_protected(self, h: SpillableHandle,
                         for_owner: Optional[int],
                         device_left: Dict[int, int]) -> bool:
        """Cross-query checkpoint floor: pressure from ``for_owner``
        may not demote ANOTHER owner's checkpoint-priority handle once
        that owner's device-resident checkpoint bytes would drop below
        the floor.  An owner's own handles are never protected from
        its own pressure."""
        if not self.checkpoint_floor or h.owner is None or \
                h.owner == for_owner or h.priority > CHECKPOINT_TIER_MAX:
            return False
        left = device_left.get(h.owner)
        if left is None:
            left = sum(x.size_bytes for x in self._handles.values()
                       if x.owner == h.owner and x.tier == DEVICE
                       and x.priority <= CHECKPOINT_TIER_MAX)
            device_left[h.owner] = left
        if left - h.size_bytes < self.checkpoint_floor:
            return True
        device_left[h.owner] = left - h.size_bytes
        return False

    def _spill_tier(self, tier: str, budget: int,
                    for_owner: Optional[int] = None) -> None:
        used = self.device_bytes if tier == DEVICE else self.host_bytes
        if used <= budget:
            return
        # coldest first: lowest priority, then — under attributed
        # pressure — the CAUSING owner's handles before a co-tenant's
        # within the same priority class, then least-recently
        # accessed.  Priority stays dominant: a neighbor's cold
        # shuffle output must still demote before the causing query's
        # own pinned on-deck batch, else every registration under
        # pressure would thrash its own working set device<->host
        def key(h: SpillableHandle):
            foreign = 1 if (for_owner is not None and
                            h.owner != for_owner) else 0
            return (h.priority, foreign, h.last_access, h.id)

        candidates = sorted(
            (h for h in self._handles.values() if h.tier == tier),
            key=key)
        if tier == DEVICE:
            device_left: Dict[int, int] = {}
            deferred = []
            for h in candidates:
                if used <= budget:
                    break
                if self._floor_protected(h, for_owner, device_left):
                    deferred.append(h)
                    continue
                used -= self._demote_to_host_locked(h)
            # the floor is isolation, not a leak: if the budget cannot
            # be met any other way, protected handles demote after all
            for h in deferred:
                if used <= budget:
                    break
                used -= self._demote_to_host_locked(h)
            if self.host_bytes > self.host_budget:
                self._spill_tier(HOST, self.host_budget)
            return
        # host -> disk: pick the victims first, then overlap the
        # serialize+write calls in the writer pool (handles are
        # disjoint; catalog counters update on this thread)
        to_spill = []
        for h in candidates:
            if used <= budget:
                break
            to_spill.append(h)
            used -= h.size_bytes
        if not to_spill:
            return
        def account(freed):
            self.host_bytes -= freed
            self.disk_bytes += freed
            self.spilled_to_disk_total += freed

        if self.disk_write_threads > 1 and len(to_spill) > 1:
            # account every COMPLETED demotion even when one writer
            # fails mid-batch, else host/disk counters drift for the
            # rest of the session.  The wait is watchdog-cooperative:
            # a wedged writer (stalled NFS, an unbounded delay rule on
            # "spill.disk") trips the section deadline and the fault
            # delivers HERE — a bare fut.result() under the catalog
            # lock would deadlock the whole process unrecoverably.
            import concurrent.futures as cf
            from spark_rapids_tpu.robustness import watchdog
            pool = cf.ThreadPoolExecutor(
                max_workers=self.disk_write_threads)
            first_err = None
            try:
                pending = [pool.submit(h.spill_to_disk)
                           for h in to_spill]
                with watchdog.section("spill.disk") as sect:
                    while pending:
                        watchdog.checkpoint()
                        done = [f for f in pending if f.done()]
                        if not done:
                            cf.wait(pending, timeout=0.05,
                                    return_when=cf.FIRST_COMPLETED)
                            continue
                        if sect is not None:
                            sect.beat()  # progress, not a hang
                        for fut in done:
                            pending.remove(fut)
                            try:
                                account(fut.result())
                            except BaseException as e:  # noqa: BLE001
                                first_err = first_err or e
            finally:
                # never wait=True: joining a wedged writer re-creates
                # the hang the cooperative wait just escaped
                pool.shutdown(wait=False, cancel_futures=True)
            if first_err is not None:
                raise first_err
        else:
            for h in to_spill:
                account(h.spill_to_disk())

    def close(self) -> None:
        """Session-teardown sweep: close every live handle (unlinking
        their disk files), then collect any orphaned spill artifacts —
        ``buf-*.tcf`` left by a crashed restore, ``*.tmp`` staging
        files from a torn write — and remove the temp dir if this
        catalog created it.  Idempotent; the catalog stays usable
        afterwards (spill_to_disk re-creates the directory)."""
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            h.close()

        def _mine(name: str) -> bool:
            # only artifacts THIS catalog issued (buf-<id>.tcf[.tmp]):
            # a shared spill_dir may hold another live catalog's frames
            if not name.startswith("buf-") or not (
                    name.endswith(".tcf") or name.endswith(".tcf.tmp")):
                return False
            try:
                return int(name[4:].split(".", 1)[0]) in self._issued_ids
            except ValueError:
                return False

        try:
            for name in os.listdir(self.spill_dir):
                if _mine(name):
                    try:
                        os.unlink(os.path.join(self.spill_dir, name))
                    except OSError:
                        pass
            if self._owns_spill_dir:
                os.rmdir(self.spill_dir)
        except OSError:
            pass

    def stats(self) -> Dict[str, int]:
        return {
            "device_bytes": self.device_bytes,
            "host_bytes": self.host_bytes,
            "disk_bytes": self.disk_bytes,
            "spilled_to_host_total": self.spilled_to_host_total,
            "spilled_to_disk_total": self.spilled_to_disk_total,
            "host_raw_bytes_total": self.host_raw_bytes_total,
            "host_encoded_bytes_total": self.host_encoded_bytes_total,
            "num_handles": len(self._handles),
        }


_default_catalog: Optional[SpillableBatchCatalog] = None


def default_catalog() -> SpillableBatchCatalog:
    global _default_catalog
    if _default_catalog is None:
        _default_catalog = SpillableBatchCatalog()
    return _default_catalog


def set_default_catalog(cat: Optional[SpillableBatchCatalog]) -> None:
    global _default_catalog
    _default_catalog = cat


class TpuSemaphore:
    """Admission control: bounds tasks concurrently issuing TPU work
    (GpuSemaphore.scala:28, `spark.rapids.sql.concurrentGpuTasks`)."""

    def __init__(self, permits: int = 1):
        self._sem = threading.BoundedSemaphore(permits)
        self._held = threading.local()
        self.wait_time_ns = 0

    def acquire_if_necessary(self) -> None:
        if getattr(self._held, "count", 0) == 0:
            import time
            t0 = time.perf_counter_ns()
            self._sem.acquire()
            self.wait_time_ns += time.perf_counter_ns() - t0
        self._held.count = getattr(self._held, "count", 0) + 1

    def release_if_held(self) -> None:
        count = getattr(self._held, "count", 0)
        if count > 0:
            self._held.count = count - 1
            if self._held.count == 0:
                self._sem.release()

    def release_all_held(self) -> None:
        """Drop this thread's whole admission count (end-of-task hook:
        the pipeline worker calls this before exiting, else a permit
        acquired by a UDF exec's re-admission would die with the thread
        and deadlock the next query's worker)."""
        if getattr(self._held, "count", 0) > 0:
            self._held.count = 0
            self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_held()
        return False
