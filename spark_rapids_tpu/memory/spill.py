"""Tiered spill framework: HBM -> host RAM -> disk.

Counterpart of the reference's RapidsBufferCatalog / RapidsBufferStore chain
(RapidsBufferCatalog.scala:40, RapidsBufferStore.scala:41, Device/Host/Disk
stores) and SpillableColumnarBatch (SpillableColumnarBatch.scala:29), with
one structural difference dictated by the platform: XLA owns HBM and there
is no RMM-style allocation-failure callback, so spilling is *watermark-
driven* — the catalog tracks bytes held by spillable batches and proactively
moves the lowest-priority ones to host (numpy) and then disk (npz files)
when the budget is exceeded.  The analog of the reference's
``DeviceMemoryEventHandler.onAllocFailure`` retry loop is
``ensure_budget()``, which callers invoke before large allocations.

Spill priorities mirror SpillPriorities.scala: shuffle outputs coldest,
actively-iterated batches hottest.
"""

from __future__ import annotations

import heapq
import itertools
import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column

# storage tiers (RapidsBuffer.scala:53 StorageTier)
DEVICE = "DEVICE"
HOST = "HOST"
DISK = "DISK"

# spill priorities (SpillPriorities.scala:26-61)
OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY = -1000
AGGREGATE_INTERMEDIATE_PRIORITY = 0
ACTIVE_ON_DECK_PRIORITY = 1000


class SpillableHandle:
    """One registered batch, resident at exactly one tier."""

    _ids = itertools.count()

    def __init__(self, catalog: "SpillableBatchCatalog",
                 batch: ColumnarBatch, priority: int):
        self.id = next(SpillableHandle._ids)
        self.catalog = catalog
        self.priority = priority
        self.tier = DEVICE
        self.size_bytes = batch.device_size_bytes()
        self.last_access = 0
        self._device: Optional[ColumnarBatch] = batch
        self._host: Optional[dict] = None
        self._disk_path: Optional[str] = None
        self._schema = batch.schema
        self._capacity = batch.capacity
        # deferred (device-resident) counts stay deferred while the
        # batch sits at the DEVICE tier; spilling materializes (the
        # host payload needs the concrete count anyway)
        self._row_count = batch.row_count
        self.closed = False

    @property
    def nrows(self) -> int:
        return int(self._row_count)

    @property
    def row_count(self):
        return self._row_count

    @property
    def nrows_bound(self) -> int:
        """Sync-free upper bound on nrows (capacity when deferred)."""
        if self._row_count.is_concrete:
            return int(self._row_count)
        return self._capacity

    # -------------------------------------------------------------- movement --
    def _to_host_payload(self) -> dict:
        b = self._device
        payload = {"__nrows": self.nrows}
        for name, col in b.columns.items():
            # host_* readers keep still-host columns bit-exact and skip
            # the device fetch entirely
            payload[f"{name}.data"] = col.host_values()
            v = col.host_validity()
            if v is not None:
                payload[f"{name}.validity"] = v
            o = col.host_offsets()
            if o is not None:
                payload[f"{name}.offsets"] = o
        return payload

    def _rebuild(self, get) -> ColumnarBatch:
        cols = {}
        for name, dt in self._schema:
            data = get(f"{name}.data")
            if data is None:
                # the frame codec stores zero-length buffers as absent
                # (lens=0); a legitimately empty buffer (e.g. the chars of
                # an all-empty string column) must round-trip as empty, not
                # as None -> asarray(None) crash
                data = np.zeros(
                    0, dtype=np.uint8 if dt.is_string else dt.storage)
            # hand the host buffers straight to Column: it materializes
            # the device copy lazily on first device use
            cols[name] = Column(
                dt, np.ascontiguousarray(data), self.nrows,
                validity=get(f"{name}.validity"),
                offsets=get(f"{name}.offsets"))
        return ColumnarBatch(cols, self.nrows)

    def spill_to_host(self) -> int:
        assert self.tier == DEVICE
        self._host = self._to_host_payload()
        self._device = None
        self.tier = HOST
        return self.size_bytes

    def spill_to_disk(self) -> int:
        assert self.tier == HOST
        from spark_rapids_tpu import native
        from spark_rapids_tpu.robustness.faults import SpillIOError
        from spark_rapids_tpu.robustness.inject import fire
        # "spill.disk" fires before any state moves: on failure the
        # batch is still intact at the HOST tier, nothing is lost, and
        # the query driver can retry the whole query
        fire("spill.disk")
        path = os.path.join(self.catalog.spill_dir, f"buf-{self.id}.tcf")
        cols = []
        for name, dt in self._schema:
            cols.append((native.dtype_code(dt),
                         self._host.get(f"{name}.data"),
                         self._host.get(f"{name}.validity"),
                         self._host.get(f"{name}.offsets")))
        blob = native.serialize_batch(self.nrows, cols,
                                      compress=self.catalog.frame_codec)
        try:
            native.write_spill_file(path, blob)
        except OSError as e:
            # disk full / unreachable: re-type for the fault taxonomy
            # (retryable — the host copy is untouched)
            raise SpillIOError(
                f"disk spill of buf-{self.id} failed: {e}") from e
        self._disk_path = path
        self._host = None
        self.tier = DISK
        return self.size_bytes

    def materialize(self) -> ColumnarBatch:
        """Get the batch back on device (unspilling if needed)."""
        if self.closed:
            raise ValueError("spillable batch already closed")
        self.last_access = self.catalog.next_access_stamp()
        if self.tier == DEVICE:
            return self._device
        if self.tier == HOST:
            payload = self._host
            batch = self._rebuild(lambda k: payload.get(k))
        else:
            from spark_rapids_tpu import native
            from spark_rapids_tpu.robustness.faults import SpillIOError
            try:
                blob = native.read_spill_file(self._disk_path)
            except OSError as e:
                raise SpillIOError(
                    f"disk unspill of buf-{self.id} failed: {e}") from e
            _, cols = native.deserialize_batch(blob)
            payload = {}
            for (name, dt), (_, d, v, o) in zip(self._schema, cols):
                if d is not None:
                    payload[f"{name}.data"] = d if dt.is_string else \
                        d.view(dt.storage)
                if v is not None:
                    payload[f"{name}.validity"] = v.view(np.bool_)
                if o is not None:
                    payload[f"{name}.offsets"] = o.view(np.int32)
            batch = self._rebuild(lambda k: payload.get(k))
        self.catalog.unspill(self, batch)
        return batch

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._device = None
        self._host = None
        if self._disk_path and os.path.exists(self._disk_path):
            os.unlink(self._disk_path)
        self.catalog.remove(self)


class SpillableBatchCatalog:
    """Singleton-ish registry with watermark-driven tier demotion.

    ``device_budget``: bytes of HBM this engine lets spillable batches pin
    before demoting the coldest to host; ``host_budget``: same for host RAM
    before demoting to disk (reference `memory.host.spillStorageSize`).
    """

    def __init__(self, device_budget: int = 1 << 34,
                 host_budget: int = 1 << 30,
                 spill_dir: Optional[str] = None,
                 frame_codec: int = 2,
                 disk_write_threads: int = 2):
        self.device_budget = device_budget
        self.host_budget = host_budget
        # host->disk demotions overlap in a small writer pool: the
        # native pager releases the GIL for serialize+write
        # (spark.rapids.memory.spill.diskWriteThreads)
        self.disk_write_threads = max(int(disk_write_threads), 1)
        # per-session frame codec level for spilled/cached frames
        # (0 raw / 1 zrle / 2 zrle+lzb); sessions set this from
        # spark.rapids.shuffle.compression.codec
        self.frame_codec = frame_codec
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="tpu-spill-")
        # warm the native library now: its first load may shell out to g++
        # (up to ~2min); doing it lazily inside spill_to_disk would stall
        # every thread behind the catalog lock
        from spark_rapids_tpu import native
        native.available()
        self._lock = threading.Lock()
        self._handles: Dict[int, SpillableHandle] = {}
        self.device_bytes = 0
        self.host_bytes = 0
        self.disk_bytes = 0
        self.spilled_to_host_total = 0
        self.spilled_to_disk_total = 0
        self._access_counter = itertools.count(1)

    def next_access_stamp(self) -> int:
        return next(self._access_counter)

    # ------------------------------------------------------------- interface --
    def register(self, batch: ColumnarBatch,
                 priority: int = AGGREGATE_INTERMEDIATE_PRIORITY
                 ) -> SpillableHandle:
        h = SpillableHandle(self, batch, priority)
        with self._lock:
            self._handles[h.id] = h
            self.device_bytes += h.size_bytes
        self.ensure_budget()
        return h

    def unspill(self, h: SpillableHandle, batch: ColumnarBatch) -> None:
        """Promote back to DEVICE after materialize (shouldUnspill=true
        behavior, RapidsBufferCatalog.scala)."""
        with self._lock:
            if h.tier == HOST:
                self.host_bytes -= h.size_bytes
            elif h.tier == DISK:
                self.disk_bytes -= h.size_bytes
                if h._disk_path and os.path.exists(h._disk_path):
                    os.unlink(h._disk_path)
                    h._disk_path = None
            h.tier = DEVICE
            h._device = batch
            h._host = None
            self.device_bytes += h.size_bytes
        self.ensure_budget()

    def remove(self, h: SpillableHandle) -> None:
        with self._lock:
            if h.id not in self._handles:
                return
            del self._handles[h.id]
            if h.tier == DEVICE:
                self.device_bytes -= h.size_bytes
            elif h.tier == HOST:
                self.host_bytes -= h.size_bytes
            else:
                self.disk_bytes -= h.size_bytes

    def ensure_budget(self, extra_needed: int = 0) -> None:
        """Demote coldest handles until budgets hold (the synchronousSpill
        loop, RapidsBufferStore.scala:146)."""
        with self._lock:
            self._spill_tier(DEVICE, self.device_budget - extra_needed)
            self._spill_tier(HOST, self.host_budget)

    def _spill_tier(self, tier: str, budget: int) -> None:
        used = self.device_bytes if tier == DEVICE else self.host_bytes
        if used <= budget:
            return
        # coldest first: lowest priority, then least-recently accessed
        candidates = sorted(
            (h for h in self._handles.values() if h.tier == tier),
            key=lambda h: (h.priority, h.last_access, h.id))
        if tier == DEVICE:
            for h in candidates:
                if used <= budget:
                    break
                freed = h.spill_to_host()
                self.device_bytes -= freed
                self.host_bytes += freed
                self.spilled_to_host_total += freed
                used -= freed
            if self.host_bytes > self.host_budget:
                self._spill_tier(HOST, self.host_budget)
            return
        # host -> disk: pick the victims first, then overlap the
        # serialize+write calls in the writer pool (handles are
        # disjoint; catalog counters update on this thread)
        to_spill = []
        for h in candidates:
            if used <= budget:
                break
            to_spill.append(h)
            used -= h.size_bytes
        if not to_spill:
            return
        def account(freed):
            self.host_bytes -= freed
            self.disk_bytes += freed
            self.spilled_to_disk_total += freed

        if self.disk_write_threads > 1 and len(to_spill) > 1:
            # account every COMPLETED demotion even when one writer
            # fails mid-batch, else host/disk counters drift for the
            # rest of the session
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(
                    max_workers=self.disk_write_threads) as pool:
                futures = [pool.submit(h.spill_to_disk)
                           for h in to_spill]
                first_err = None
                for fut in futures:
                    try:
                        account(fut.result())
                    except BaseException as e:  # noqa: BLE001
                        first_err = first_err or e
                if first_err is not None:
                    raise first_err
        else:
            for h in to_spill:
                account(h.spill_to_disk())

    def stats(self) -> Dict[str, int]:
        return {
            "device_bytes": self.device_bytes,
            "host_bytes": self.host_bytes,
            "disk_bytes": self.disk_bytes,
            "spilled_to_host_total": self.spilled_to_host_total,
            "spilled_to_disk_total": self.spilled_to_disk_total,
            "num_handles": len(self._handles),
        }


_default_catalog: Optional[SpillableBatchCatalog] = None


def default_catalog() -> SpillableBatchCatalog:
    global _default_catalog
    if _default_catalog is None:
        _default_catalog = SpillableBatchCatalog()
    return _default_catalog


def set_default_catalog(cat: Optional[SpillableBatchCatalog]) -> None:
    global _default_catalog
    _default_catalog = cat


class TpuSemaphore:
    """Admission control: bounds tasks concurrently issuing TPU work
    (GpuSemaphore.scala:28, `spark.rapids.sql.concurrentGpuTasks`)."""

    def __init__(self, permits: int = 1):
        self._sem = threading.BoundedSemaphore(permits)
        self._held = threading.local()
        self.wait_time_ns = 0

    def acquire_if_necessary(self) -> None:
        if getattr(self._held, "count", 0) == 0:
            import time
            t0 = time.perf_counter_ns()
            self._sem.acquire()
            self.wait_time_ns += time.perf_counter_ns() - t0
        self._held.count = getattr(self._held, "count", 0) + 1

    def release_if_held(self) -> None:
        count = getattr(self._held, "count", 0)
        if count > 0:
            self._held.count = count - 1
            if self._held.count == 0:
                self._sem.release()

    def release_all_held(self) -> None:
        """Drop this thread's whole admission count (end-of-task hook:
        the pipeline worker calls this before exiting, else a permit
        acquired by a UDF exec's re-admission would die with the thread
        and deadlock the next query's worker)."""
        if getattr(self._held, "count", 0) > 0:
            self._held.count = 0
            self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_held()
        return False
