"""Split-and-retry OOM framework.

TPU analog of the reference's device-OOM recovery discipline
(``DeviceMemoryEventHandler.scala:43`` ``onAllocFailure`` — spill the device
store and reattempt the allocation — plus the split-and-retry iterator
pattern its operators layer on top: when an attempt still OOMs after
spilling, halve the work and process the halves independently).

XLA owns HBM and there is no allocation callback to hook, so recovery is
exception-driven instead: a device computation that exhausts HBM surfaces as
``XlaRuntimeError: RESOURCE_EXHAUSTED``.  The framework catches exactly
that, demotes every registered spillable batch off the device, and retries;
a second failure at the same size splits the input batch in half
(recursively, down to a floor) so each attempt needs less scratch HBM.

Failure *detection* is also centralised here: ``is_oom`` classifies
exceptions, and every recovery step is recorded on the ``RetryMetrics``
singleton so the profiling tool can report retry/split counts per query.

Test hook: ``inject_oom(n)`` forces the next ``n`` guarded attempts to
raise a synthetic OOM, mirroring how the reference's tests force RMM retry
paths without real exhaustion.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Iterable, Iterator, List, Optional, TypeVar

T = TypeVar("T")
R = TypeVar("R")

# markers XLA / jax use for device-memory exhaustion
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM ", "Attempting to reserve")


class InjectedOomError(MemoryError):
    """Synthetic OOM raised by the test-injection hook."""


class SplitAndRetryOOM(MemoryError):
    """Raised when an attempt still OOMs at the minimum split size —
    the work cannot be made to fit no matter how small the batch."""


def is_oom(exc: BaseException) -> bool:
    """True for *device* memory exhaustion only.  A plain host
    ``MemoryError`` is deliberately NOT recoverable: the recovery path
    (spill to host, Arrow split round-trip) allocates host memory and
    would amplify the very pressure that raised it."""
    if isinstance(exc, InjectedOomError):
        return True
    if isinstance(exc, MemoryError):
        return False
    text = str(exc)
    return any(m in text for m in _OOM_MARKERS)


# ---------------------------------------------------------------- injection --
# the OOM checkpoint is one named point in the unified injection
# registry (robustness/inject.py); inject_oom stays as the deprecated
# shim the existing retry tests (and users of the old hook) call
from spark_rapids_tpu.robustness import inject as _inject

_inject.register_point("memory.oom", InjectedOomError)


def inject_oom(num_ooms: int = 1, skip: int = 0) -> None:
    """Deprecated shim over ``robustness.inject``: force the next
    ``num_ooms`` guarded attempts (after skipping ``skip``) on this
    thread to raise ``InjectedOomError``.  Equivalent to
    ``inject("memory.oom", count=num_ooms, skip=skip)``."""
    # last-call-wins per thread, like the old threading.local injector:
    # re-arming here must never disarm another thread's rule
    _inject.clear("memory.oom", this_thread_only=True)
    _inject.inject("memory.oom", count=num_ooms, skip=skip,
                   exc=InjectedOomError)


def clear_injected_oom() -> None:
    _inject.clear("memory.oom", this_thread_only=True)


def _checkpoint() -> None:
    _inject.fire("memory.oom")


# ------------------------------------------------------------------ metrics --
class RetryMetrics:
    """Recovery counters, surfaced by tools/profiling.py.

    Process-wide totals plus a thread-local mirror: a query executes its
    operator pipeline on the calling thread, so per-query deltas read the
    thread-local view — concurrent queries on other threads (or other
    sessions) don't contaminate each other's QueryEnd attribution."""

    def __init__(self):
        self.lock = threading.Lock()
        self.retry_count = 0
        self.split_count = 0
        self.spilled_on_retry = 0
        self._per_thread = {}  # effective ident -> counter dict
        self._owner = {}       # worker ident -> owning (driving) ident

    def _effective_ident(self) -> int:
        ident = threading.get_ident()
        return self._owner.get(ident, ident)

    def _bump(self, retries=0, splits=0, spilled=0) -> None:
        with self.lock:
            self.retry_count += retries
            self.split_count += splits
            self.spilled_on_retry += spilled
            loc = self._per_thread.setdefault(
                self._effective_ident(),
                {"retryCount": 0, "splitAndRetryCount": 0,
                 "spilledOnRetryBytes": 0})
            loc["retryCount"] += retries
            loc["splitAndRetryCount"] += splits
            loc["spilledOnRetryBytes"] += spilled

    def snapshot(self) -> dict:
        with self.lock:
            return {"retryCount": self.retry_count,
                    "splitAndRetryCount": self.split_count,
                    "spilledOnRetryBytes": self.spilled_on_retry}

    def snapshot_local(self) -> dict:
        """This thread's counters — the per-query attribution view.  A
        pipeline worker (exec/pipeline.py) adopts its driving thread,
        so retries inside the pipelined iterator still land here."""
        with self.lock:
            loc = self._per_thread.get(self._effective_ident())
            return dict(loc) if loc else \
                {"retryCount": 0, "splitAndRetryCount": 0,
                 "spilledOnRetryBytes": 0}

    def adopt(self, owner_ident: int) -> None:
        with self.lock:
            self._owner[threading.get_ident()] = owner_ident

    def release(self) -> None:
        with self.lock:
            self._owner.pop(threading.get_ident(), None)

    def disown(self, ident: int) -> None:
        """Sever ``ident``'s adoption from the outside (a driver
        abandoning a wedged worker thread)."""
        with self.lock:
            self._owner.pop(ident, None)

    def purge_owner(self, owner_ident: int) -> None:
        """Drop every adoption mapping TO ``owner_ident`` — the
        query-exit counterpart of disown(): OS ident reuse must not
        let a finished worker's stale adoption attribute a new
        query's retries to this dead query
        (serving/context.QueryContext.__exit__)."""
        with self.lock:
            _inject.purge_adoptions(self._owner, owner_ident)

    def reset(self) -> None:
        with self.lock:
            self.retry_count = 0
            self.split_count = 0
            self.spilled_on_retry = 0
            self._per_thread.clear()


retry_metrics = RetryMetrics()


# ----------------------------------------------------------------- recovery --
# serializes the budget save/zero/restore dance: without it two threads
# recovering concurrently can capture the other's zeroed budget as
# "saved" and leave the shared catalog pinned at budget 0 forever
_recovery_lock = threading.Lock()


def _spill_device_store(catalog=None) -> int:
    """Demote every registered spillable batch off the device (the
    synchronousSpill(targetSize=0) step of onAllocFailure)."""
    if catalog is None:
        from spark_rapids_tpu.memory.spill import default_catalog
        catalog = default_catalog()
    with _recovery_lock:
        before = catalog.spilled_to_host_total
        saved = catalog.device_budget
        try:
            catalog.device_budget = 0
            catalog.ensure_budget()
        finally:
            catalog.device_budget = saved
        return catalog.spilled_to_host_total - before


def _handle_oom(catalog=None) -> None:
    """Must run AFTER the except block that caught the OOM has exited:
    while the handler is live, the exception's traceback pins the failed
    attempt's frame (and its device-array locals), so a gc pass inside
    the handler could not reclaim the very buffers we need back."""
    # drop dead device buffers eagerly so XLA can actually reuse the HBM
    import gc
    gc.collect()
    freed = _spill_device_store(catalog)
    retry_metrics._bump(retries=1, spilled=freed)


# ----------------------------------------------------------------- wrappers --
# fallback when no session is active; with a session the budget reads
# from ITS conf (spark.rapids.memory.oomRetry.maxRetries) at call time,
# so one session's setting never leaks into another's
_default_max_retries = 2


def set_default_max_retries(n: int) -> None:
    global _default_max_retries
    _default_max_retries = int(n)


def _resolve_max_retries() -> int:
    try:
        from spark_rapids_tpu.api.session import TpuSession
        from spark_rapids_tpu.config import rapids_conf as rc
    except ImportError:  # torn-down interpreter only
        return _default_max_retries
    s = TpuSession._active
    if s is not None:
        # conf errors (bad oomRetry.maxRetries value) must fail loudly,
        # not silently fall back to the default budget
        return s.conf.get(rc.OOM_RETRY_MAX)
    return _default_max_retries


def with_retry_no_split(fn: Callable[[], R], *, catalog=None,
                        max_retries: Optional[int] = None) -> R:
    """Run ``fn``; on device OOM spill the device store and rerun, up to
    ``max_retries`` recoveries.  For attempts whose input cannot be
    subdivided (e.g. emitting one already-sized output batch)."""
    if max_retries is None:
        max_retries = _resolve_max_retries()
    attempt = 0
    while True:
        try:
            _checkpoint()
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            if not is_oom(e) or attempt >= max_retries:
                raise
            attempt += 1
        # recovery runs here, after the except block has exited and the
        # exception (whose traceback pins the failed attempt's frame and
        # device locals) is cleared — see _handle_oom
        _handle_oom(catalog)


def split_batch_in_half(batch) -> List:
    """Default splitter: one ColumnarBatch -> two of half the rows.

    Goes through Arrow (host) deliberately — this is the rare recovery
    path, and a host round-trip both frees the device copy and
    re-materialises compact halves (the contiguous-split analog)."""
    n = batch.nrows
    if n <= 1:
        raise SplitAndRetryOOM(
            f"cannot split batch of {n} row(s) any further")
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    table = batch.to_arrow()
    mid = n // 2
    return [ColumnarBatch.from_arrow(table.slice(0, mid)),
            ColumnarBatch.from_arrow(table.slice(mid, n - mid))]


def with_retry(inputs: Iterable[T], fn: Callable[[T], R], *,
               split: Callable[[T], List[T]] = split_batch_in_half,
               catalog=None) -> Iterator[R]:
    """Map ``fn`` over ``inputs`` with OOM recovery.

    Per input: first OOM spills the device store and retries at full
    size; an OOM on the retry splits the input and pushes the halves
    back on the work queue (each half gets the same spill-then-split
    treatment, recursively).  Yields one result per final attempt, so
    callers must tolerate ``fn``'s unit of work shrinking.  ``inputs``
    is consumed lazily — one upstream batch is live at a time."""
    upstream = iter(inputs)
    queue: deque = deque()
    while True:
        if queue:
            item = queue.popleft()
        else:
            try:
                item = next(upstream)
            except StopIteration:
                return
        spilled_once = False
        while True:
            must_split = False
            try:
                _checkpoint()
                yield fn(item)
                break
            except Exception as e:  # noqa: BLE001 - classified below
                # SplitAndRetryOOM (raised by split at the 1-row floor)
                # re-raises here: is_oom is False for host MemoryErrors
                if not is_oom(e):
                    raise
                must_split = spilled_once
            # recovery runs after the except block so the cleared
            # exception no longer pins the failed attempt's device
            # locals — see _handle_oom
            if not must_split:
                spilled_once = True
                _handle_oom(catalog)
                continue
            halves = split(item)
            retry_metrics._bump(splits=1)
            for h in reversed(halves):
                queue.appendleft(h)
            break
