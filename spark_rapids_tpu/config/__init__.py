from spark_rapids_tpu.config.rapids_conf import RapidsConf, ConfEntry

__all__ = ["RapidsConf", "ConfEntry"]
