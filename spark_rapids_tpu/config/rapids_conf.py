"""Typed, self-documenting configuration registry.

Counterpart of ``sql-plugin/.../RapidsConf.scala`` (1,745 LoC, 119 entries):
typed entries with defaults, docs and validators, a global registry, and a
``generate_docs()`` that renders the configs reference markdown the same way
``RapidsConf.main`` writes ``docs/configs.md``.

Key names keep the reference's ``spark.rapids.*`` prefix so that users of the
reference find the same knobs; GPU-specific words become TPU ones
(``concurrentGpuTasks`` -> ``concurrentTpuTasks``).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional


class ConfEntry:
    """One typed config entry (RapidsConf.scala:116 `ConfEntry`)."""

    def __init__(self, key: str, default: Any, doc: str, conv: Callable,
                 validator: Optional[Callable[[Any], Optional[str]]] = None,
                 internal: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.conv = conv
        self.validator = validator
        self.internal = internal

    def env_key(self) -> str:
        """The entry's environment-variable form — the ONE derivation
        shared by value resolution (``get``) and the explicitly-set
        test (``RapidsConf.is_set``), so the cost model's
        override-vs-decide discipline can never diverge from what
        ``get`` actually reads."""
        return self.key.upper().replace(".", "_")

    def get(self, settings: Dict[str, str]) -> Any:
        raw = settings.get(self.key)
        if raw is None:
            raw = os.environ.get(self.env_key())
        if raw is None:
            return self.default
        value = self.conv(raw) if isinstance(raw, str) else raw
        if self.validator is not None:
            err = self.validator(value)
            if err:
                raise ValueError(f"{self.key}={value!r}: {err}")
        return value


def _to_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _to_int(s: str) -> int:
    return int(s)


def _to_float(s: str) -> float:
    return float(s)


_REGISTRY: Dict[str, ConfEntry] = {}


def _register(entry: ConfEntry) -> ConfEntry:
    assert entry.key not in _REGISTRY, f"duplicate conf {entry.key}"
    _REGISTRY[entry.key] = entry
    return entry


def conf(key, default, doc, conv=str, validator=None, internal=False):
    return _register(ConfEntry(key, default, doc, conv, validator, internal))


def _positive(v):
    return None if v > 0 else "must be positive"


def _fraction(v):
    return None if 0.0 <= v <= 1.0 else "must be in [0, 1]"


# --------------------------------------------------------------------- entries --
SQL_ENABLED = conf(
    "spark.rapids.sql.enabled", True,
    "Enable or disable TPU acceleration of SQL operators entirely. "
    "(reference RapidsConf.scala:514)", _to_bool)

EXPLAIN = conf(
    "spark.rapids.sql.explain", "NONE",
    "Explain why parts of a query did or did not run on TPU: NONE, "
    "NOT_ON_TPU, ALL. (reference `sql.explain` RapidsConf.scala:1142)", str,
    lambda v: None if v in ("NONE", "NOT_ON_TPU", "ALL") else
    "must be NONE, NOT_ON_TPU or ALL")

EVENT_LOG_DIR = conf(
    "spark.rapids.tpu.eventLog.dir", "",
    "Directory for the session's JSON-lines query event log (plans, per-op "
    "metrics, spill stats). Empty disables logging. Consumed by the "
    "qualification/profiling tools (reference analog: Spark event logs + "
    "GpuMetric -> SQLMetrics).", str)

EVENT_LOG_FLUSH_MS = conf(
    "spark.rapids.tpu.eventLog.flushMs", 0,
    "Batched event-log flushing: lines are written immediately but "
    "fsync-class flush()es are coalesced to at most one per this many "
    "milliseconds, so hot-path emitters (the watchdog monitor, spill "
    "integrity) stop paying a flush per line. 0 (default) keeps "
    "flush-per-line (today's behavior). QueryEnd/QueryFatal/SessionEnd "
    "always flush explicitly, so crash post-mortems still see the "
    "tail.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

TRACE_ENABLED = conf(
    "spark.rapids.tpu.trace.enabled", False,
    "Arm the span-tracing runtime (utils/tracing.py): thread-aware, "
    "query-attributed wall-clock spans over operator batch loops, "
    "fused-stage dispatch, jit trace/AOT-cache loads, host syncs, "
    "exchange launch/resolve, spill tier transitions, checkpoint "
    "write/resume, incremental tick phases, admission and UDF-pool "
    "waits. Spans drain at QueryEnd into the QueryEnd 'spans' rollup "
    "(eventlog QueryInfo.spans -> profiling \"Where the time went\"), "
    "the per-site observation store, and — with trace.dir set — a "
    "Perfetto-loadable Chrome trace file per query. Default off; when "
    "off every span site costs a single branch and results are "
    "bit-identical either way. Setting trace.dir also arms tracing. "
    "Process-global (the jitCache.dir discipline): the last-"
    "constructed session's setting wins.", _to_bool)

TRACE_DIR = conf(
    "spark.rapids.tpu.trace.dir", "",
    "Directory for per-query Chrome-trace-event JSON exports "
    "(tools/traceview.py; open at ui.perfetto.dev). One file per "
    "query envelope, written at QueryEnd — including failed and fatal "
    "envelopes, so post-mortems get a timeline. Empty disables export "
    "(the spans rollup and observation store still work when "
    "trace.enabled is set). Setting this implies trace.enabled.", str)

TRACE_MAX_EVENTS = conf(
    "spark.rapids.tpu.trace.maxEvents", 100_000,
    "Bound on span records per query: per-thread buffers stop "
    "recording past this many events and the exported trace carries "
    "an explicit trace-truncated marker with the dropped count — a "
    "bounded trace never silently reads as complete.", _to_int,
    _positive)

PROFILE_TRACE = conf(
    "spark.rapids.tpu.profile.trace", False,
    "Wrap each operator's execution in a jax.profiler TraceAnnotation so "
    "per-op ranges appear in XPlane/perfetto captures (the NVTX-range "
    "analog, NvtxWithMetrics.scala).", _to_bool)

BATCH_SIZE_BYTES = conf(
    "spark.rapids.sql.batchSizeBytes", 1 << 31,
    "Target size in bytes for columnar batches; hard-capped at 2 GiB "
    "mirroring the reference's per-column row-count limit "
    "(RapidsConf.scala:436-444).", _to_int,
    lambda v: None if 0 < v <= (1 << 31) else "must be in (0, 2GiB]")

BATCH_ROW_CAPACITY = conf(
    "spark.rapids.sql.tpu.maxBatchRows", 1 << 22,
    "Maximum rows per device batch (shape-bucket ceiling). TPU-specific: "
    "bounds the set of XLA-compiled shapes.", _to_int, _positive)

SORT_OOC_THRESHOLD = conf(
    "spark.rapids.sql.sort.outOfCoreThresholdBytes", 256 << 20,
    "Total input bytes above which multi-batch sorts use the windowed "
    "out-of-core merge (sorted spillable runs, bounded merge windows) "
    "instead of one concatenated device sort (reference "
    "GpuSortExec.scala:225 GpuOutOfCoreSortIterator).", _to_int, _positive)

SORT_OOC_WINDOW_ROWS = conf(
    "spark.rapids.sql.sort.outOfCoreWindowRows", 1 << 16,
    "Rows pulled from each sorted run per merge step of the out-of-core "
    "sort; bounds the merge working set to ~2*runs*window rows.",
    _to_int, _positive)

AGG_MERGE_CHUNK_ROWS = conf(
    "spark.rapids.sql.agg.mergeChunkRows", 1 << 22,
    "Partial-aggregate batches are merged in chunks of at most this many "
    "rows (tree reduction) instead of one concatenation of every partial, "
    "so the merge working set stays bounded (reference sort-based "
    "fallback, aggregate.scala:184-197).", _to_int, _positive)

CONCURRENT_TPU_TASKS = conf(
    "spark.rapids.sql.concurrentTpuTasks", 1,
    "Number of tasks that may issue work to the TPU concurrently "
    "(reference `concurrentGpuTasks` RapidsConf.scala:423).", _to_int,
    _positive)

HAS_NANS = conf(
    "spark.rapids.sql.hasNans", True,
    "Assume floating point values may be NaN; some float aggregations "
    "refuse to run when set (reference RapidsConf.scala:549).", _to_bool)

DECIMAL_ENABLED = conf(
    "spark.rapids.sql.decimalType.enabled", True,
    "Enable decimal (DECIMAL_64) processing: device arithmetic with "
    "Spark result-type rules and overflow->null, sum up to "
    "decimal(8,s) children; wider results and avg fall back to CPU "
    "(reference RapidsConf.scala:564).", _to_bool)

OPTIMIZER_TRANSITION_COST = conf(
    "spark.rapids.sql.optimizer.transitionRowCost", 0.1,
    "Microseconds per row charged for a host<->device transition by the "
    "cost-based optimizer; operator costs come calibrated from "
    "plan/cbo_weights.json (regenerate with "
    "spark-rapids-tpu-cbo-calibrate).", _to_float)

INCOMPAT_ENABLED = conf(
    "spark.rapids.sql.incompatibleOps.enabled", True,
    "Run operators whose semantics differ from CPU Spark in documented "
    "corner cases (ASCII-only case mapping, byte-semantics regex). The "
    "reference defaults this OFF (RapidsMeta.scala:271); this engine "
    "defaults ON because each incompat is individually documented and "
    "per-op keys (spark.rapids.sql.expression.<Name>) can disable any "
    "single one.", _to_bool)

IMPROVED_FLOAT_OPS = conf(
    "spark.rapids.sql.improvedFloatOps.enabled", False,
    "Allow float ops whose results may differ from CPU beyond 1-ulp.",
    _to_bool)

UDF_COMPILER_ENABLED = conf(
    "spark.rapids.sql.udfCompiler.enabled", False,
    "Compile Python UDF bytecode into TPU expression trees "
    "(reference udf-compiler, RapidsConf.scala:519).", _to_bool)

REGEXP_ENABLED = conf(
    "spark.rapids.sql.regexp.enabled", True,
    "Evaluate regular-expression expressions (rlike, regexp_replace, "
    "split_part) on device; when false every regex expression tags off "
    "to the CPU fallback (reference `sql.regexp.enabled`, "
    "RapidsConf.scala).", _to_bool)

VARIABLE_FLOAT_AGG = conf(
    "spark.rapids.sql.variableFloatAgg.enabled", True,
    "Allow sum/avg over floating-point values even though chunked and "
    "distributed evaluation reorders the additions, so results can "
    "differ from CPU Spark in the last ulps (reference "
    "`sql.variableFloatAgg.enabled`; defaults ON here because the "
    "engine is chunk-parallel by construction).", _to_bool)

CAST_STRING_TO_FLOAT = conf(
    "spark.rapids.sql.castStringToFloat.enabled", True,
    "Allow string->float casts on device (reference "
    "`sql.castStringToFloat.enabled`; tiny-ulp differences possible "
    "for values near the subnormal range).", _to_bool)

CAST_FLOAT_TO_STRING = conf(
    "spark.rapids.sql.castFloatToString.enabled", True,
    "Allow float->string casts on device (reference "
    "`sql.castFloatToString.enabled`; formatting of some exponents "
    "differs from Java).", _to_bool)

CAST_FLOAT_TO_DECIMAL = conf(
    "spark.rapids.sql.castFloatToDecimal.enabled", True,
    "Allow float->decimal casts on device (reference "
    "`sql.castFloatToDecimal.enabled`).", _to_bool)

CAST_STRING_TO_TIMESTAMP = conf(
    "spark.rapids.sql.castStringToTimestamp.enabled", True,
    "Allow string->timestamp/date casts on device (reference "
    "`sql.castStringToTimestamp.enabled`; only the fixed-width ISO "
    "subset parses on device).", _to_bool)

SUPPRESS_PLANNING_FAILURE = conf(
    "spark.rapids.sql.suppressPlanningFailure", False,
    "When TPU planning itself raises, retry the whole query on the "
    "CPU fallback chain instead of failing (reference "
    "`sql.suppressPlanningFailure`, RapidsConf.scala).", _to_bool)

MEM_POOL_FRACTION = conf(
    "spark.rapids.memory.tpu.allocFraction", 0.9,
    "Fraction of HBM this engine may retain in its batch pool before "
    "spilling (reference `memory.gpu.allocFraction`).", _to_float, _fraction)

MEM_MIN_ALLOC_FRACTION = conf(
    "spark.rapids.memory.tpu.minAllocFraction", 0.25,
    "Minimum fraction of HBM the batch pool must be able to claim; "
    "session init fails fast when reserve/limit squeeze the pool below "
    "this (reference `memory.gpu.minAllocFraction`, "
    "GpuDeviceManager.scala:170-245).", _to_float, _fraction)

MEM_MAX_ALLOC_FRACTION = conf(
    "spark.rapids.memory.tpu.maxAllocFraction", 1.0,
    "Hard ceiling on the HBM fraction the batch pool may claim, "
    "applied after the reserve is subtracted (reference "
    "`memory.gpu.maxAllocFraction`).", _to_float, _fraction)

MEM_RESERVE = conf(
    "spark.rapids.memory.tpu.reserve", 640 << 20,
    "Bytes of HBM held back from the pool for the XLA runtime and "
    "compiled-program scratch (the CUDA-context reserve analog, "
    "`memory.gpu.reserve`).", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

HOST_SPILL_STORAGE_SIZE = conf(
    "spark.rapids.memory.host.spillStorageSize", 1 << 30,
    "Bytes of host memory used as the first spill tier before disk "
    "(reference RapidsConf.scala:357).", _to_int, _positive)

SPILL_DISK_WRITE_THREADS = conf(
    "spark.rapids.memory.spill.diskWriteThreads", 2,
    "Concurrent writer threads used when demoting host-tier batches "
    "to disk; the native pager releases the GIL so writes overlap "
    "(reference spill-thread sizing, RapidsConf.scala:393).",
    _to_int, _positive)

SPILL_ENABLED = conf(
    "spark.rapids.memory.tpu.spillEnabled", True,
    "Enable HBM->host->disk spilling of spillable batches.", _to_bool)

DEVICE_MEMORY_LIMIT = conf(
    "spark.rapids.memory.tpu.deviceLimitBytes", 0,
    "Device-pool budget in bytes for spillable batches; 0 = derive from HBM "
    "size * allocFraction.", _to_int)

SHUFFLE_PARTITIONS = conf(
    "spark.rapids.sql.shuffle.partitions", 8,
    "Default number of shuffle partitions (spark.sql.shuffle.partitions "
    "analog).", _to_int, _positive)

SHUFFLE_COMPRESSION_CODEC = conf(
    "spark.rapids.shuffle.compression.codec", "lz4",
    "Codec for host-path frame payloads (spill, cache, host-staged "
    "shuffle): none, zrle (zero-RLE only), lz4 (zrle + LZ4-class lzb, "
    "smaller wins per buffer; zstd accepted as an alias) — reference "
    "TableCompressionCodec.scala:107.", str,
    lambda v: None if v in ("none", "zrle", "lz4", "zstd")
    else "unknown codec")

WINDOW_BATCH_ROWS = conf(
    "spark.rapids.sql.window.batchRows", 1 << 20,
    "Target rows per window-operator chunk when the input arrives "
    "sorted (the planner inserts a sort under every partitioned "
    "window). Chunks flush at partition boundaries (the "
    "GpuKeyBatchingIterator analog); a single partition larger than "
    "this streams with running-state carry when every window function "
    "in the operator has a running frame, and otherwise grows the "
    "chunk.", _to_int, _positive)

DISTRIBUTED_ENABLED = conf(
    "spark.rapids.sql.distributed.enabled", True,
    "When the session holds a device mesh, offer every query plan to the "
    "distributed planner (parallel/dist_planner.py) before the single-"
    "process engine; unsupported plans fall back with the reason on "
    "session.last_dist_explain (the planner-inserted exchange analog, "
    "reference GpuShuffleExchangeExec.scala:120).", _to_bool)

DISTRIBUTED_NUM_SHARDS = conf(
    "spark.rapids.sql.distributed.numShards", 0,
    "Build an N-device mesh at session start and run supported queries "
    "distributed (0 = only when a Mesh is passed to TpuSession "
    "directly). N devices must already be visible to jax — real chips, "
    "or virtual CPU devices which require XLA_FLAGS="
    "--xla_force_host_platform_device_count=N to be set BEFORE jax "
    "initializes; session construction raises otherwise.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SHUFFLE_TRANSPORT_ENABLED = conf(
    "spark.rapids.shuffle.transport.enabled", True,
    "Use the ICI all-to-all collective exchange when executing on a device "
    "mesh (the UCX-transport analog, reference RapidsConf.scala:986); "
    "otherwise serialize through the host shuffle store.", _to_bool)

SHUFFLE_PACKED_ENABLED = conf(
    "spark.rapids.tpu.shuffle.packed.enabled", True,
    "Fused packed shuffle wire format: byte-reinterpret all fixed-width "
    "columns of an exchange into width-homogeneous lane payloads (uint32 "
    "lanes for 4/8-byte columns, uint8 lanes for bool/small ints, "
    "validity masks bit-packed eight to a lane) and move each payload "
    "with ONE all_to_all — O(distinct widths) <= 2 collectives per "
    "exchange instead of O(columns + masks). False restores per-column "
    "collectives (the A/B baseline, and the automatic fallback for "
    "exchanges carrying unpackable columns). See docs/performance.md "
    "\"Shuffle wire format\".", _to_bool)

SHUFFLE_SLOT_MODE = conf(
    "spark.rapids.tpu.shuffle.slot.mode", "adaptive",
    "All-to-all slot (padding) sizing per exchange site: 'adaptive' "
    "smooths the power-of-two slot with a per-site EMA of observed max "
    "slices (stable slots keep jit-cache keys stable) and lets warm "
    "sites launch speculatively without the stats hostsync — a slot "
    "overflow re-runs the launch at full capacity and records a "
    "degradable recovery action instead of dropping rows; 'fixed' sizes "
    "every launch from its own histogram only; 'capacity' restores "
    "full-capacity padding (always correct, numShards x the useful "
    "bytes on ICI).", str,
    lambda v: None if v in ("adaptive", "fixed", "capacity") else
    "must be adaptive, fixed or capacity")

SHUFFLE_SLOT_OVERFLOW_GROWTH = conf(
    "spark.rapids.tpu.shuffle.slot.overflowGrowth", 2.0,
    "Multiplier applied to an exchange site's slot EMA after a "
    "speculative-slot overflow, so the next stats-sized launch carries "
    "headroom above the slice that overflowed.", _to_float,
    lambda v: None if v >= 1.0 else "must be >= 1.0")

SHUFFLE_SLOT_RAGGED_ENABLED = conf(
    "spark.rapids.tpu.shuffle.slot.ragged.enabled", False,
    "Skew-adaptive RAGGED slot plans for stats-sized exchanges: when "
    "the per-destination histogram shows a few hot (src, dst) slices, "
    "the base all_to_all is sized from the COLD slices and the hot "
    "surplus rides per-pair collective-permutes that transmit only on "
    "their own link — padded wire bytes stop scaling with the hottest "
    "destination times every slice (parallel/shuffle.py RaggedPlan). "
    "False (default) keeps one uniform slot per exchange (current "
    "behavior). The overflow-retry rung stays the safety net: a slice "
    "exceeding its ragged limit re-runs at full capacity, rows are "
    "never dropped.", _to_bool)

SHUFFLE_SLOT_RAGGED_FACTOR = conf(
    "spark.rapids.tpu.shuffle.slot.ragged.minSavings", 1.5,
    "Minimum wire-rows reduction (uniform / ragged) a ragged plan must "
    "buy before it is used; below this the uniform slot wins (fewer "
    "collectives, stable jit keys).", _to_float,
    lambda v: None if v >= 1.0 else "must be >= 1.0")

EXCHANGE_ASYNC_ENABLED = conf(
    "spark.rapids.tpu.exchange.async.enabled", False,
    "Asynchronous exchange/compute overlap (parallel/exchange_async.py): "
    "exchange-bearing launches are dispatched, not blocked on — the "
    "post-launch verification (speculative slot-overflow flag) defers "
    "into an AsyncExchangeHandle resolved at the next stage boundary, "
    "so downstream fused compute dispatches while the collective is "
    "still in flight.  Bounded by the in-flight window below; a "
    "deferred overflow (or an injected fault at resolve time) degrades "
    "to the synchronous path through the recovery ladder — results are "
    "never wrong, only re-driven.  False (default) keeps every "
    "exchange synchronous (current behavior).", _to_bool)

EXCHANGE_INFLIGHT_WINDOW_BYTES = conf(
    "spark.rapids.tpu.exchange.async.inflightWindowBytes", 1 << 28,
    "Budget on unresolved exchange payload bytes in flight at once "
    "(the async window's backpressure): admitting a handle past the "
    "budget resolves the oldest pending handles first, so a deep plan "
    "cannot pin unbounded HBM in unverified exchange buffers.  "
    "In-flight bytes are also charged to the query's serving memory "
    "budget (serving/context.py).", _to_int, _positive)

EXCHANGE_HOST_STAGING_THRESHOLD = conf(
    "spark.rapids.tpu.exchange.hostStaging.thresholdBytes", 0,
    "When a single exchange's estimated payload exceeds this many "
    "bytes, stage it through host RAM instead of the device collective: "
    "rows round-trip through the spill tier's frame codec (compressed, "
    "pinned-host analog) and come back already co-located, so an "
    "oversized shuffle lands in host memory instead of failing over to "
    "the recovery ladder's split rung.  0 (default) disables staging "
    "(current behavior).", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SHUFFLE_TOPOLOGY_STRATEGY = conf(
    "spark.rapids.tpu.shuffle.topology.strategy", "auto",
    "Collective strategy per mesh axis: 'all_to_all' always uses the "
    "ICI-style padded all-to-all; 'gather' uses gather-then-"
    "redistribute (ONE all-gather per width group, each shard compacts "
    "its own rows locally — fewer, larger transfers, the DCN-friendly "
    "shape); 'auto' (default) picks all_to_all on single-slice (ICI) "
    "axes and gather on axes that span hosts/slices "
    "(parallel/mesh.py axis_link_kind) — i.e. current behavior on a "
    "single-slice mesh.", str,
    lambda v: None if v in ("auto", "all_to_all", "gather") else
    "must be auto, all_to_all or gather")

_READER_TYPES = ("PERFILE", "COALESCING", "MULTITHREADED", "AUTO")


def _reader_type_ok(v):
    return None if v in _READER_TYPES else \
        "must be PERFILE, COALESCING, MULTITHREADED or AUTO"


MULTITHREADED_READ_NUM_THREADS = conf(
    "spark.rapids.sql.format.parquet.multiThreadedRead.numThreads", 8,
    "Thread-pool size for the multithreaded file reader "
    "(reference RapidsConf.scala:734).", _to_int, _positive)

MAX_NUM_FILES_PARALLEL = conf(
    "spark.rapids.sql.format.parquet.multiThreadedRead.maxNumFilesParallel", 4,
    "Max files buffered in flight per task by the multithreaded reader "
    "(reference RapidsConf.scala:740).", _to_int, _positive)

PARQUET_ENABLED = conf(
    "spark.rapids.sql.format.parquet.enabled", True,
    "Use the engine's columnar parquet scan; when false parquet scans "
    "tag off and the whole read runs on the pandas fallback chain "
    "(reference `sql.format.parquet.enabled`, RapidsConf.scala:664).",
    _to_bool)

PARQUET_READ_ENABLED = conf(
    "spark.rapids.sql.format.parquet.read.enabled", True,
    "Read side of the parquet format switch (reference "
    "`sql.format.parquet.read.enabled`).", _to_bool)

ORC_ENABLED = conf(
    "spark.rapids.sql.format.orc.enabled", True,
    "Use the engine's columnar ORC scan (reference "
    "`sql.format.orc.enabled`).", _to_bool)

ORC_READ_ENABLED = conf(
    "spark.rapids.sql.format.orc.read.enabled", True,
    "Read side of the ORC format switch.", _to_bool)

CSV_ENABLED = conf(
    "spark.rapids.sql.format.csv.enabled", True,
    "Use the engine's columnar CSV scan (reference "
    "`sql.format.csv.enabled`).", _to_bool)

CSV_READ_ENABLED = conf(
    "spark.rapids.sql.format.csv.read.enabled", True,
    "Read side of the CSV format switch.", _to_bool)

PARQUET_READER_TYPE = conf(
    "spark.rapids.sql.format.parquet.reader.type", "AUTO",
    "Parquet reader strategy: PERFILE, COALESCING, MULTITHREADED, AUTO "
    "(reference RapidsConf.scala:693-722).", str, _reader_type_ok)

ORC_READER_TYPE = conf(
    "spark.rapids.sql.format.orc.reader.type", "AUTO",
    "ORC reader strategy (reference RapidsConf.scala per-format reader "
    "knobs).", str, _reader_type_ok)

CSV_READER_TYPE = conf(
    "spark.rapids.sql.format.csv.reader.type", "AUTO",
    "CSV reader strategy.", str, _reader_type_ok)

ORC_READ_NUM_THREADS = conf(
    "spark.rapids.sql.format.orc.multiThreadedRead.numThreads", 8,
    "Thread-pool size for the multithreaded ORC reader.",
    _to_int, _positive)

CSV_READ_NUM_THREADS = conf(
    "spark.rapids.sql.format.csv.multiThreadedRead.numThreads", 8,
    "Thread-pool size for the multithreaded CSV reader.",
    _to_int, _positive)

ORC_MAX_NUM_FILES_PARALLEL = conf(
    "spark.rapids.sql.format.orc.multiThreadedRead.maxNumFilesParallel",
    4, "Max ORC files buffered in flight per task.", _to_int, _positive)

CSV_MAX_NUM_FILES_PARALLEL = conf(
    "spark.rapids.sql.format.csv.multiThreadedRead.maxNumFilesParallel",
    4, "Max CSV files buffered in flight per task.", _to_int, _positive)

READER_BATCH_SIZE_ROWS = conf(
    "spark.rapids.sql.reader.batchSizeRows", 1 << 20,
    "Soft cap on rows per batch produced by file scans (reference "
    "`spark.rapids.sql.reader.batchSizeRows`).", _to_int, _positive)

WRITER_MAX_ROWS_PER_FILE = conf(
    "spark.rapids.sql.writer.maxRowsPerFile", 1 << 22,
    "Max rows per output file for dataset writes.", _to_int, _positive)

JOIN_OUTPUT_BATCH_ROWS = conf(
    "spark.rapids.sql.join.outputBatchRows", 1 << 22,
    "Join output chunk size in rows — bounds peak HBM per emitted "
    "batch (the JoinGatherer output-splitting analog, "
    "GpuHashJoin output batching).", _to_int, _positive)

OOM_RETRY_MAX = conf(
    "spark.rapids.memory.oomRetry.maxRetries", 2,
    "Spill-and-retry attempts per device OOM before splitting or "
    "failing (memory/retry.py split-and-retry framework).",
    _to_int, lambda v: None if v >= 0 else "must be >= 0")

QUERY_RECOVERY_ENABLED = conf(
    "spark.rapids.sql.recovery.enabled", True,
    "Enable the query-level recovery/degradation driver: classified "
    "transient faults (device OOM, reader/transport hiccups, "
    "preemption) re-drive the query down a bounded ladder — retry, "
    "spill-and-retry, smaller batches, single-device replan, CPU "
    "fallback — instead of failing it (robustness/driver.py).",
    _to_bool)

QUERY_RECOVERY_MAX_RETRIES = conf(
    "spark.rapids.sql.recovery.maxRetries", 2,
    "Plain same-plan retries (with backoff) before the recovery "
    "ladder escalates to degradation.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

QUERY_RECOVERY_BACKOFF_MS = conf(
    "spark.rapids.sql.recovery.backoffMs", 25,
    "Base backoff between same-plan query retries, doubled per retry, "
    "jittered (deterministically, seeded per driver), and capped at "
    "spark.rapids.sql.recovery.backoffCapMs.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

QUERY_RECOVERY_BACKOFF_CAP_MS = conf(
    "spark.rapids.sql.recovery.backoffCapMs", 2000,
    "Ceiling on the exponential retry backoff (before jitter). Chaos "
    "tests lower it so ladders stay fast; long-haul batch jobs may "
    "raise it to ride out minutes-long maintenance events.", _to_int,
    _positive)

RECOVERY_CHECKPOINT_ENABLED = conf(
    "spark.rapids.sql.recovery.checkpoint.enabled", True,
    "Register the post-shuffle output of every completed distributed "
    "exchange stage (aggregate/join/sort/window) as a stage checkpoint "
    "in a per-query lineage log (robustness/checkpoint.py). On a "
    "retryable fault the recovery ladder's re-attempt resumes from the "
    "last good checkpoint — completed subtrees splice in from the "
    "spill catalog instead of re-reading sources and re-running "
    "collectives; recovery cost becomes proportional to the FAILED "
    "stage, not the whole query. Checkpoints are CRC-verified on "
    "restore; a corrupt or evicted one is dropped and its subtree "
    "re-runs.", _to_bool)

RECOVERY_CHECKPOINT_MAX_BYTES = conf(
    "spark.rapids.sql.recovery.checkpoint.maxBytes", 1 << 30,
    "Ceiling on the bytes one query's stage-checkpoint lineage log may "
    "pin across all spill tiers; oldest checkpoints evict first "
    "(CheckpointEvict events) and their subtrees simply re-run on "
    "resume. Payloads are additionally counted against the spill "
    "catalog's device budget while HBM-resident, so checkpoints "
    "demote under the same watermark pressure as live batches.",
    _to_int, _positive)

RECOVERY_CHECKPOINT_TIERS = conf(
    "spark.rapids.sql.recovery.checkpoint.tiers", "device,host,disk",
    "Spill tiers a stage-checkpoint payload may occupy. "
    "'device,host,disk' (default) registers at DEVICE and lets "
    "watermark pressure demote; 'host,disk' demotes to host "
    "immediately at write (checkpoints never compete for HBM); 'disk' "
    "pushes straight to the atomic disk frames.", str,
    lambda v: None if v in ("device,host,disk", "host,disk", "disk")
    else "must be 'device,host,disk', 'host,disk' or 'disk'")

WATCHDOG_ENABLED = conf(
    "spark.rapids.tpu.watchdog.enabled", True,
    "Enable the hang watchdog (robustness/watchdog.py): monitored "
    "sections around reader decode, shuffle program launch, host "
    "syncs, UDF worker calls and the pipeline worker heartbeat "
    "convert deadline overruns into classified retryable TimeoutFault"
    "s delivered at the next cooperative cancellation checkpoint, so "
    "the recovery ladder absorbs hangs the same way it absorbs "
    "exceptions (the UCX transport heartbeat/timeout analog).",
    _to_bool)

WATCHDOG_DEFAULT_DEADLINE_MS = conf(
    "spark.rapids.tpu.watchdog.defaultDeadlineMs", 300_000,
    "Deadline applied to every monitored section without a per-point "
    "override (spark.rapids.tpu.watchdog.deadline.<point>). 0 "
    "disables monitoring for sections without an override.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

WATCHDOG_QUERY_DEADLINE_MS = conf(
    "spark.rapids.tpu.watchdog.queryDeadlineMs", 0,
    "Wall-time deadline for one query execution attempt; an overrun "
    "is a retryable TimeoutFault, so the recovery ladder re-drives "
    "(and ultimately degrades) rather than hanging forever. 0 "
    "disables the whole-query deadline.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

WATCHDOG_POLL_MS = conf(
    "spark.rapids.tpu.watchdog.pollMs", 25,
    "Target poll interval of the watchdog monitor thread; the "
    "effective cadence also adapts to the shortest active deadline "
    "so short test deadlines detect promptly.", _to_int, _positive)

SPILL_INTEGRITY_ENABLED = conf(
    "spark.rapids.memory.spill.integrityCheck.enabled", True,
    "Verify a crc32 checksum (computed when a batch leaves the "
    "device) on every HOST and DISK tier spill restore; a mismatch "
    "drops the batch and raises a degradable CorruptionFault so the "
    "recovery ladder re-runs from source — wrong bytes are never "
    "returned. Disk spill files are always written atomically "
    "(temp file + fsync + rename) regardless of this flag.",
    _to_bool)

SKEW_JOIN_ENABLED = conf(
    "spark.rapids.sql.join.skew.enabled", True,
    "Enable skew-join mitigation in the distributed exchange "
    "(OptimizeSkewedJoin analog; parallel/distributed.py).", _to_bool)

SKEW_JOIN_FACTOR = conf(
    "spark.rapids.sql.join.skew.factor", 4.0,
    "A shuffle destination receiving more than factor x median rows "
    "is treated as skewed.", float,
    lambda v: None if v > 1.0 else "must be > 1.0")

SKEW_JOIN_MIN_ROWS = conf(
    "spark.rapids.sql.join.skew.minRows", 1 << 12,
    "Minimum destination row count before skew mitigation triggers.",
    _to_int, _positive)

BROADCAST_JOIN_THRESHOLD_ROWS = conf(
    "spark.rapids.sql.join.broadcastThresholdRows", 1 << 16,
    "Build sides at or below this many rows broadcast instead of "
    "shuffling (autoBroadcastJoinThreshold analog, in rows).",
    _to_int, _positive)

PYTHON_NUM_WORKERS = conf(
    "spark.rapids.sql.python.numWorkers", 0,
    "Worker processes for black-box Python UDF evaluation (0 = inline "
    "on the driver thread; the concurrentPythonWorkers analog). "
    "Spawn-started and reused across batches; unpicklable functions "
    "fall back to inline.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

PIPELINE_ENABLED = conf(
    "spark.rapids.tpu.pipeline.enabled", True,
    "Drive query execution through the asynchronous pipeline "
    "(exec/pipeline.py): a worker thread pulls operator batches — "
    "overlapping reader decode, host->device upload and XLA dispatch — "
    "while the driving thread consumes results.  Pure overlap "
    "optimization: batch contents and order are identical to the "
    "sequential pull loop.", _to_bool)

PIPELINE_DEPTH = conf(
    "spark.rapids.tpu.pipeline.depth", 2,
    "Maximum batches in flight between the pipeline worker and the "
    "consuming thread.  In-flight batches stay registered in the spill "
    "catalog, so depth bounds pinned HBM, not just queue length; depth "
    "1 still overlaps one producer step with the consumer.",
    _to_int, _positive)

PIPELINE_DONATION = conf(
    "spark.rapids.tpu.pipeline.donation.enabled", True,
    "Donate input HBM to XLA on fused filter/project stages whose "
    "input batches are pipeline-ephemeral (produced by the upstream "
    "operator and dropped after the stage), letting outputs reuse the "
    "input buffers.  No-op on the CPU backend (XLA:CPU ignores "
    "donation); donated stages skip operator-level OOM retry and "
    "escalate straight to query-level recovery, which re-runs from "
    "source (docs/performance.md#donation).", _to_bool)

FUSION_ENABLED = conf(
    "spark.rapids.tpu.fusion.enabled", True,
    "Whole-stage fusion (exec/fusion.py): the planner collapses maximal "
    "Filter/Project chains — and the chain feeding a (pre-shuffle) "
    "aggregate — into ONE compiled XLA computation per pipeline stage, so "
    "intermediates stay in registers/VMEM and each batch costs one jit "
    "dispatch instead of one per operator (selection travels as a mask "
    "inside the trace, compacted once at the stage boundary). Fusion "
    "never crosses an exchange, a cached plan node, or an operator the "
    "fuser cannot ingest (black-box UDFs, CPU-fallback expressions) — "
    "those chains auto-fall-back to unfused execution. False restores "
    "one-dispatch-per-operator execution (the A/B baseline; results are "
    "bit-identical either way).", _to_bool)

PALLAS_HASH_ENABLED = conf(
    "spark.rapids.tpu.pallas.hash.enabled", False,
    "Hash-table group-by and join phase-A (ops/pallas_kernels.py): a "
    "single-pass open-addressing table over the 64-bit coded key "
    "replaces the sort/segment-sum formulation where the dense coded "
    "table cannot fit (high-cardinality keys) and on the single-key "
    "inner/left/semi/anti join probe.  A pallas kernel owns the "
    "VMEM-resident table on real TPUs; elsewhere a round-based XLA "
    "formulation runs the same contract.  Probe-chain overflow raises a "
    "flag and the launch DISCARDS the hash output and re-runs the "
    "current sort path (rows are never dropped), recorded in the "
    "fusion-metrics breadcrumb family.  False (default) is a full A/B: "
    "results are bit-identical either way.", _to_bool)

PALLAS_HASH_TABLE_SLOTS = conf(
    "spark.rapids.tpu.pallas.hash.tableSlots", 1 << 16,
    "Slot count of the hash group-by table (power of two).  Bounds "
    "distinct groups per launch — more groups than slots (or a probe "
    "chain past the 256-step bound) overflows to the sort path.  Also "
    "the VMEM bound: the table is 3 i32 lanes, 12 bytes/slot, so 2^20 "
    "slots (~12 MB) is the practical ceiling on-chip.", _to_int,
    lambda v: None if v >= 64 and (v & (v - 1)) == 0
    else "must be a power of two >= 64")

FUSION_WIRE_ENABLED = conf(
    "spark.rapids.tpu.fusion.wire.enabled", False,
    "Fuse the wire across the exchange boundary (parallel/"
    "distributed.py): a warm distributed aggregate launches ONE program "
    "per shard that runs scan-mask -> filter -> partial-agg -> lane "
    "packing/validity bit-packing -> all_to_all -> merge/finalize, "
    "instead of the separate local-partials and exchange+merge "
    "dispatches.  Applies only on the speculative (warm-slot) path; "
    "stats-planned, ragged, staged, and keyless launches keep the "
    "two-dispatch shape and record a fused-wire fallback breadcrumb.  "
    "Slot overflow inside a fused launch degrades to the current "
    "two-phase path exactly like speculative overflow does today.  "
    "stage_ids are unchanged fused or not (checkpoint/resume splice "
    "unaffected).  False (default) is a full A/B: results are "
    "bit-identical either way.", _to_bool)

FUSION_MAX_OPS = conf(
    "spark.rapids.tpu.fusion.maxChainOps", 16,
    "Ceiling on the operators one fused stage may collapse. Bounds the "
    "size of the traced computation (compile time grows with the fused "
    "expression forest); chains longer than this split into multiple "
    "fused stages.", _to_int, _positive)

JIT_CACHE_DIR = conf(
    "spark.rapids.tpu.jitCache.dir", "",
    "Directory for the PERSISTENT jit-cache tier (ops/jit_cache.py): "
    "compiled stages are AOT-serialized via jax.export, keyed by "
    "sha256(structural signature, input shapes, backend, jax/jaxlib "
    "versions), and loaded before tracing on a miss — a second process "
    "running the same query compiles nothing. Entries are CRC-verified "
    "and environment-checked on load; truncation, bit rot, or a store "
    "written by a different jax/jaxlib falls back to a fresh compile "
    "(JitCacheInvalid event), never a failed or wrong query. Cold runs "
    "pay one extra Python trace per stage to produce the export — the "
    "price of the zero-trace warm start. Empty disables the tier (the "
    "in-memory cache still applies).", str)

JIT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.jitCache.maxBytes", 1 << 30,
    "Ceiling on the persistent jit-cache directory's total size; "
    "oldest entries evict first (their signatures simply recompile "
    "next cold run).", _to_int, _positive)

PIPELINE_DEFER_SYNCS = conf(
    "spark.rapids.tpu.pipeline.deferSyncs", True,
    "Carry per-batch row/group counts as device-resident scalars "
    "(columnar RowCount) and only materialize them at true host "
    "decision points, collapsing the per-batch int(n) device->host "
    "round trips in the aggregation path.  False restores the eager "
    "per-batch syncs (the sequential baseline tests/test_pipeline.py "
    "measures against).", _to_bool)

SERVING_CONCURRENT_QUERIES = conf(
    "spark.rapids.tpu.serving.concurrentQueries", 4,
    "Maximum queries admitted onto the device concurrently by the "
    "session-level admission controller (serving/admission.py — the "
    "query-granularity face of the reference's GpuSemaphore). Queries "
    "past the limit wait in a fair FIFO queue; 0 disables admission "
    "control entirely (every query runs immediately, the pre-serving "
    "behavior).", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SERVING_HBM_ADMISSION_FRACTION = conf(
    "spark.rapids.tpu.serving.hbmAdmissionFraction", 0.8,
    "Fraction of the spill catalog's device budget that admitted "
    "queries' declared memory weights may claim together — the "
    "byte-weighted half of the admission semaphore. A query whose "
    "weight does not fit waits (FIFO) until admitted queries release; "
    "a single query heavier than the whole budget still admits alone "
    "rather than deadlocking.", _to_float, _fraction)

SERVING_ADMISSION_TIMEOUT_MS = conf(
    "spark.rapids.tpu.serving.admissionTimeoutMs", 0,
    "Longest one query may wait in the admission queue before it is "
    "rejected with a typed AdmissionFault (the queue->reject rung of "
    "the budget ladder). 0 waits indefinitely.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SERVING_MAX_QUEUED_QUERIES = conf(
    "spark.rapids.tpu.serving.maxQueuedQueries", 0,
    "Bound on the admission queue depth; a query arriving at a full "
    "queue is rejected immediately with AdmissionFault('queue-full') "
    "instead of piling onto a session that is already saturated. 0 "
    "leaves the queue unbounded.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SERVING_QUERY_MEMORY_BUDGET = conf(
    "spark.rapids.tpu.serving.queryMemoryBudgetBytes", 0,
    "Per-query ceiling on spill-catalog bytes the query's own batches "
    "may pin at the DEVICE tier. Exhaustion degrades THAT query: its "
    "own coldest handles spill to host first (BudgetExhausted event, "
    "action=spill); a query whose device-resident set still exceeds "
    "the budget after self-spilling is rejected with a typed "
    "BudgetExhaustedFault. 0 disables enforcement (the admission "
    "weight then derives from hbmAdmissionFraction / "
    "concurrentQueries).", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SERVING_SYNC_BUDGET = conf(
    "spark.rapids.tpu.serving.syncBudget", 0,
    "Per-query ceiling on counted device->host synchronizations "
    "(utils/hostsync.py). A query that exceeds it is rejected with a "
    "typed BudgetExhaustedFault at the offending sync — a runaway "
    "sync loop in one query must not serialize the whole session's "
    "tunnel. 0 disables.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SERVING_DEADLINE_BUDGET_MS = conf(
    "spark.rapids.tpu.serving.deadlineBudgetMs", 0,
    "Wall-time deadline applied to EACH execution attempt of a query "
    "admitted through the serving layer (overrides "
    "spark.rapids.tpu.watchdog.queryDeadlineMs when set). An overrun "
    "is a retryable TimeoutFault for that query only; a query that "
    "overruns on every rung can therefore hold its admission slot "
    "for up to ladder-length x this budget before exhausting. 0 "
    "defers to the watchdog conf.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SERVING_CHECKPOINT_FLOOR_BYTES = conf(
    "spark.rapids.tpu.serving.checkpointEvictionFloorBytes", 0,
    "Cross-query isolation floor for stage checkpoints: device-tier "
    "pressure originating from one query demotes that query's own "
    "handles first, and may not demote ANOTHER query's "
    "checkpoint-priority payloads below this many device-resident "
    "bytes (unless the budget cannot be met any other way). 0 "
    "disables the floor (pure priority order).", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

SERVING_INTERLEAVE_ENABLED = conf(
    "spark.rapids.tpu.serving.interleave.enabled", False,
    "Fair batch-for-batch interleaving of admitted queries "
    "(serving/scheduler.py): instead of each admitted query's batch "
    "loop occupying the device FIFO until it finishes, queries take "
    "weighted round-robin timeslices at every batch (and distributed "
    "stage) boundary — a 10ms dashboard query no longer queues behind "
    "a long scan, and every runnable query advances within one round "
    "(starvation-proof by construction). Weights derive from the "
    "serving budgets: lighter byte weights and deadline-budgeted "
    "queries get more batch slices per round. Cooperative only — it "
    "reorders when batches dispatch, never what they compute, so "
    "results are bit-identical with it off.", _to_bool)

SERVING_INTERLEAVE_QUANTUM = conf(
    "spark.rapids.tpu.serving.interleave.quantumBatches", 1,
    "Base number of batch slices one query may advance per "
    "round-robin turn of the fair interleaver. The effective quantum "
    "scales up for queries declaring a byte weight lighter than the "
    "pool default (bounded 8x) and doubles for deadline-budgeted "
    "queries; every registered query always advances at least one "
    "batch per round.", _to_int, _positive)

SERVING_RESULT_CACHE_ENABLED = conf(
    "spark.rapids.tpu.serving.resultCache.enabled", False,
    "Plan-keyed query RESULT cache (serving/reuse.py): before "
    "planning, a query's exact logical-plan text plus the input "
    "fingerprint of everything it reads (file path/size/mtime_ns "
    "triples, in-memory batch identities) is looked up in a "
    "session-scoped host/disk-tier store; a hit answers with ZERO "
    "executions. Any fingerprint drift invalidates the entry (a "
    "mutated input can never serve stale bytes), results are "
    "CRC-verified on every hit (a failed check degrades to "
    "recompute), and plans containing UDFs or pandas stages are "
    "never cached. Most production dashboard traffic is "
    "near-duplicate — this is the 'Accelerating Presto with GPUs' "
    "result-reuse leg.", _to_bool)

SERVING_RESULT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.serving.resultCache.maxBytes", 256 << 20,
    "Ceiling on the bytes the result cache may pin across the "
    "host/disk spill tiers (stored size — the storage codec "
    "stretches it). Least-recently-used entries evict first; a "
    "result larger than the whole budget is simply not stored.",
    _to_int, _positive)

SERVING_SHARED_STAGE_ENABLED = conf(
    "spark.rapids.tpu.serving.sharedStage.enabled", False,
    "CROSS-QUERY stage cache (serving/reuse.py): mesh queries "
    "register every completed exchange stage in a shared, "
    "session-scoped store keyed by the structural stage id WITH the "
    "input fingerprint folded in (the always_resume lineage "
    "machinery, robustness/incremental.py precedent), so two "
    "different queries sharing a subtree — same scan + filter + "
    "partial aggregate — splice each other's checkpoints through "
    "try_distributed(resume=True) on FIRST attempts. Entries carry "
    "owner attribution for per-query budget billing; CRC failure, "
    "eviction and fingerprint drift all degrade to recompute — "
    "never wrong bytes. Payloads demote to host at write so the "
    "shared store never competes with live batches for HBM.",
    _to_bool)

SERVING_SHARED_STAGE_MAX_BYTES = conf(
    "spark.rapids.tpu.serving.sharedStage.maxBytes", 1 << 30,
    "Ceiling on the bytes the shared cross-query stage cache may pin "
    "across the host/disk spill tiers (stored size). Oldest entries "
    "evict first (SharedStageEvict events); an evicted entry just "
    "re-runs its subtree on the next query that wanted it.",
    _to_int, _positive)

TEMPLATE_ENABLED = conf(
    "spark.rapids.tpu.template.enabled", False,
    "Parameterized plan templates (plan/template.py): before "
    "planning, constant literals are hoisted out of the logical plan "
    "into typed parameter slots with VALUE-FREE cache keys, so the "
    "stage-compiler signatures, fused-aggregate programs and "
    "persistent AOT entries all key on the normalized template and "
    "the literal values travel as device-scalar arguments at "
    "dispatch — a dashboard plan re-issued with shifting literals "
    "retraces and recompiles ZERO times after warmup. Hoisting "
    "refuses literals that change plan shape (nulls, strings, "
    "decimals, ANSI-check constants, LIMIT/slot constants, unaliased "
    "projection names) — refused shapes fall back to exact keying "
    "and produce byte-identical results. Default off; with it off "
    "every plan takes the exact-key path bit-identically.", _to_bool)

TEMPLATE_RESULT_CACHE_ENABLED = conf(
    "spark.rapids.tpu.template.resultCache.enabled", False,
    "TEMPLATE tier of the serving result cache (serving/reuse.py): "
    "answered queries also store under (normalized template "
    "fingerprint, parameter vector), so the SAME dashboard re-issued "
    "with the SAME literals hits even when the exact plan text was "
    "never seen in this form (prepared statements, re-hoisted "
    "ad-hoc plans). Same verification discipline as the exact tier "
    "— input fingerprints statted fresh at lookup, CRC re-verified "
    "on every hit, failures degrade to recompute. Requires BOTH "
    "template.enabled and serving.resultCache.enabled; shares the "
    "exact tier's byte budget.", _to_bool)

INCREMENTAL_ENABLED = conf(
    "spark.rapids.tpu.incremental.enabled", True,
    "Enable incremental state for continuous micro-batch ingest "
    "(robustness/incremental.py, session.incremental(df).tick(paths)): "
    "a tick executes against the last COMMITTED state epoch — "
    "aggregation plans re-aggregate only the appended files and merge "
    "with the standing partial-aggregate state, other plans splice "
    "unchanged (input-fingerprinted) stage checkpoints from the "
    "session-persistent lineage store — and commits the new epoch "
    "atomically only when the tick completes. Any fault mid-tick rolls "
    "back to the committed epoch and the tick degrades to a full "
    "recompute; state is never half-updated. False makes every tick a "
    "plain full re-execution with no standing state.", _to_bool)

INCREMENTAL_MAX_STATE_BYTES = conf(
    "spark.rapids.tpu.incremental.maxStateBytes", 1 << 30,
    "Ceiling on the bytes one standing query's incremental state "
    "(partial-aggregate epochs plus persistent stage checkpoints) may "
    "pin across all spill tiers. Oldest stage entries evict first, "
    "then the aggregate state itself (StateEvict events); an evicted "
    "entry degrades the next tick to full recompute — never a wrong "
    "or failed tick. Per-owner spill accounting (serving layer) keeps "
    "one standing query's state from starving co-tenants regardless.",
    _to_int, _positive)

INCREMENTAL_TIERS = conf(
    "spark.rapids.tpu.incremental.tiers", "device,host,disk",
    "Spill tiers incremental state may occupy (same semantics as "
    "spark.rapids.sql.recovery.checkpoint.tiers): 'device,host,disk' "
    "registers at DEVICE and lets watermark pressure demote; "
    "'host,disk' demotes to host immediately at commit so standing "
    "state never competes with live batches for HBM; 'disk' pushes "
    "straight to the atomic disk frames.", str,
    lambda v: None if v in ("device,host,disk", "host,disk", "disk")
    else "must be 'device,host,disk', 'host,disk' or 'disk'")

INCREMENTAL_WATERMARK_DELAY_MS = conf(
    "spark.rapids.tpu.incremental.watermarkDelayMs", -1,
    "Event-time watermark delay for windowed continuous-ingest "
    "queries (group keys built from functions.window): each committed "
    "epoch advances the watermark to max(window end seen) minus this "
    "delay, the tick's answer excludes windows whose end is at or "
    "before the watermark, and their partial-state buckets evict "
    "atomically with the commit — state stays bounded under infinite "
    "ingest and late rows for expired windows are dropped (they can "
    "never change the answer). A rolled-back tick advances nothing: "
    "watermark and state restore to the committed epoch together. "
    "-1 (default) disables eviction — windowed aggregations then keep "
    "every bucket, like any other group key.", _to_int,
    lambda v: None if v >= -1 else "must be >= -1 (-1 = off)")

INCREMENTAL_TOPN_MAX_STATE_ROWS = conf(
    "spark.rapids.tpu.incremental.topn.maxStateRows", 65536,
    "State cap for mergeable top-N continuous-ingest queries "
    "(orderBy(group keys).limit(n) over a decomposable aggregate): "
    "when the sort key set covers the group keys with bare column "
    "references — the condition under which merging per-epoch top-K "
    "partials provably reproduces the one-shot answer bit-for-bit — "
    "the standing state and every delta partial are trimmed to the "
    "limit's n rows, so state is bounded by n instead of by the "
    "number of groups ever seen. Limits larger than this cap keep "
    "the untrimmed full-group state (still correct, just bigger); "
    "sort keys touching aggregated values always refuse the trim.",
    _to_int, _positive)

FLEET_SHARED_INGEST_ENABLED = conf(
    "spark.rapids.tpu.fleet.sharedIngest.enabled", True,
    "Shared-ingest fan-out for standing-query fleets "
    "(serving/fleet.py, session.fleet()): each fleet tick-round stats "
    "and READS the appended fact files exactly once and fans the "
    "ingested batches out to every delta-capable subscriber — N "
    "dashboards over one stream cost one source pull per new file "
    "instead of N. Per-subscriber epochs still commit and roll back "
    "independently (a faulted subscriber re-reads its own history on "
    "the degraded path; co-subscribers are untouched). False makes "
    "every subscriber pull its own delta, the lone-runner behavior.",
    _to_bool)

FLEET_EPOCH_SHARED_STAGE_ENABLED = conf(
    "spark.rapids.tpu.fleet.sharedStage.epoch.enabled", True,
    "Epoch-aware tier of the cross-query shared stage cache "
    "(serving/reuse.py): at every standing-query COMMIT the epoch "
    "store publishes a snapshot of its committed, file-fingerprinted "
    "stage entries (stage id + input fingerprint + committed epoch) "
    "into the session SharedStageCache, so two standing queries "
    "sharing a delta-join subtree splice each other's committed tick "
    "work. Entries register only at commit — never from provisional "
    "state — so a rolled-back tick can never leak a pre-commit entry "
    "to a co-tenant; an entry evicted from its owner after publication "
    "simply misses and the subtree re-runs. Requires "
    "spark.rapids.tpu.serving.sharedStage.enabled and a mesh.",
    _to_bool)

FLEET_SINK_MAX_RECORDS = conf(
    "spark.rapids.tpu.fleet.sink.maxRecords", 16,
    "Committed sink records one standing query retains for idempotent "
    "re-emission (robustness/incremental.py SinkCommit): each record "
    "is one committed epoch's emission (payload CRC + epoch + query "
    "id, plus the result batches) riding the atomic epoch commit — a "
    "replayed tick whose payload matches the latest committed record "
    "re-emits THAT epoch instead of minting a duplicate. Oldest "
    "records age out past this cap (they can no longer be replayed "
    "against, which only matters for consumers lagging more than this "
    "many data-bearing ticks).", _to_int, _positive)

FLEET_COORDINATOR = conf(
    "spark.rapids.tpu.fleet.coordinator", "",
    "Coordinator address (host:port) for multi-controller fleet "
    "bring-up. When set together with fleet.processId and "
    "fleet.numProcesses, session construction calls "
    "jax.distributed.initialize so every host's process contributes "
    "its local devices to one global mesh spanning DCN. Empty "
    "(default) keeps the single-controller mode — one process, one "
    "host, the behavior of every prior release.", str)

FLEET_PROCESS_ID = conf(
    "spark.rapids.tpu.fleet.processId", -1,
    "This host's process index in the multi-controller fleet "
    "(0..numProcesses-1; process 0 also serves as the coordinator). "
    "-1 (default) with an empty fleet.coordinator means "
    "single-controller mode.", _to_int,
    lambda v: None if v >= -1 else "must be >= -1")

FLEET_NUM_PROCESSES = conf(
    "spark.rapids.tpu.fleet.numProcesses", 0,
    "Total process count in the multi-controller fleet. 0 (default) "
    "means single-controller mode; values >= 2 require "
    "fleet.coordinator and fleet.processId.", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

FLEET_HEARTBEAT_MS = conf(
    "spark.rapids.tpu.fleet.heartbeatMs", 500,
    "Heartbeat period for the per-host membership registry "
    "(parallel/mesh.py HostMembership): each host writes a beat "
    "record at most this often, and peers are judged against it. A "
    "peer silent for heartbeatMs * missedBeatsFatal is declared lost "
    "— a HostLoss event is emitted and the next membership check "
    "raises a RETRYABLE HostLossFault, entering the recovery "
    "ladder's shrink rung.", _to_int, _positive)

FLEET_MISSED_BEATS_FATAL = conf(
    "spark.rapids.tpu.fleet.missedBeatsFatal", 3,
    "How many consecutive missed heartbeats declare a peer host lost "
    "(see fleet.heartbeatMs). Higher values tolerate longer GC/compile "
    "pauses at the cost of slower failure detection.", _to_int,
    _positive)

FLEET_MEMBERSHIP_DIR = conf(
    "spark.rapids.tpu.fleet.membershipDir", "",
    "Directory backing the HostMembership registry (one beat file per "
    "host, written atomically). On CPU test meshes and "
    "logical-host fleets this is a local tmp dir; on a real fleet it "
    "is shared storage every host can reach. Empty (default) places "
    "it under the system temp dir keyed by coordinator address, or "
    "disables membership entirely when the session has no fleet.",
    str)

FLEET_CACHE_DIR = conf(
    "spark.rapids.tpu.fleet.cache.dir", "",
    "Shared-storage directory for FLEET-scoped stage/result/template "
    "cache entries (serving/fleetcache.py): session caches publish "
    "CRC-stamped, fingerprint-verified payloads here so a repeated "
    "plan on ANY host answers from a peer's work. Writers are "
    "epoch-fenced — a publish carrying a fence token older than the "
    "registry's current epoch (a partitioned or restarted 'zombie' "
    "host) is rejected and health-checked, never read. Empty "
    "(default) keeps every cache session-scoped.", str)

FLEET_DCN_DEADLINE_SCALE = conf(
    "spark.rapids.tpu.fleet.dcnDeadlineScale", 4.0,
    "Watchdog deadline multiplier for exchange launches whose "
    "collective crosses DCN (the data axis spans processes or "
    "logical hosts): cross-host hops are orders of magnitude slower "
    "than ICI, so the shuffle.exchange deadline scales by this factor "
    "before a TimeoutFault is parked. 1.0 disables the scaling.",
    _to_float, _positive)

FLEET_LOGICAL_HOSTS = conf(
    "spark.rapids.tpu.fleet.logicalHosts", 0,
    "Partition a SINGLE-process mesh's devices into this many "
    "simulated hosts for testing the fleet machinery without real "
    "multi-controller bring-up: axis link classification reads 'dcn' "
    "across simulated host boundaries (DCN collective selection, "
    "deadline scaling, and byte accounting all engage), membership "
    "tracks one logical host per partition, and the shrink rung can "
    "rebuild the mesh over survivors. 0 (default) disables; ignored "
    "in real multi-controller mode (process boundaries define "
    "hosts).", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

GRAY_FAILURE_ENABLED = conf(
    "spark.rapids.tpu.fleet.grayFailure.enabled", False,
    "Master switch for the gray-failure subsystem "
    "(robustness/grayfailure.py): per-host health scoring from "
    "heartbeat jitter and exchange/host-staging wall observations, "
    "hedged re-dispatch of a SUSPECT host's host-side shard work, "
    "proactive quarantine of a persistently-degraded host through the "
    "soft-shrink path (and its rejoin once recovered), and "
    "self-calibrated watchdog deadlines derived from observed p99 "
    "walls. A fail-slow host — thermal throttle, degraded DCN link — "
    "never trips the heartbeat-loss judgment, so without this the "
    "whole fleet stalls at its pace. False (default) keeps every "
    "decision path bit-identical to the pre-gray-failure engine.",
    _to_bool)

FLEET_SUSPECT_FACTOR = conf(
    "spark.rapids.tpu.fleet.suspectFactor", 3.0,
    "A host whose median observed wall (per evidence point: heartbeat "
    "interval, dist.host_sync, exchange.host_staging) is persistently "
    "this many times the fleet median over the rolling suspect window "
    "becomes SUSPECT — a typed HostSuspect event, never a hard fault "
    "on its own. SUSPECT gates hedged execution and starts the "
    "quarantine clock.", _to_float,
    lambda v: None if v > 1.0 else "must be > 1.0")

FLEET_SUSPECT_WINDOW = conf(
    "spark.rapids.tpu.fleet.suspectWindow", 32,
    "Rolling window (observations per host per evidence point) the "
    "gray-failure health score is computed over. Smaller windows "
    "detect faster but flap on one slow GC pause; larger windows "
    "smooth transients at the cost of detection latency.", _to_int,
    _positive)

FLEET_SUSPECT_MIN_SAMPLES = conf(
    "spark.rapids.tpu.fleet.suspectMinSamples", 3,
    "Minimum observations a host must have at an evidence point "
    "before that point contributes to its health score — bring-up "
    "and cold caches must not read as sickness.", _to_int, _positive)

FLEET_QUARANTINE_AFTER_MS = conf(
    "spark.rapids.tpu.fleet.quarantineAfterMs", 60_000,
    "A host continuously SUSPECT for this long is proactively "
    "quarantined: drained out of the mesh through the soft-shrink "
    "path (fence-epoch bump, survivors-only mesh) at the next safe "
    "query boundary, before anything wedges. Unlike a heartbeat "
    "loss, the host keeps beating and its recovery is tracked for "
    "rejoin. 0 disables proactive quarantine (detection and hedging "
    "still run).", _to_int,
    lambda v: None if v >= 0 else "must be >= 0")

FLEET_REJOIN_AFTER_MS = conf(
    "spark.rapids.tpu.fleet.rejoinAfterMs", 30_000,
    "A quarantined host whose health score stays below the suspect "
    "threshold for this long rejoins the mesh at the next safe query "
    "boundary: devices restored, fleet caches re-fenced (the fence "
    "epoch advances again), no in-flight query touched.", _to_int,
    _positive)

FLEET_HEDGE_PERCENTILE = conf(
    "spark.rapids.tpu.fleet.hedgePercentile", 0.95,
    "Adaptive hedge deadline: a SUSPECT host's host-side shard work "
    "(host staging, member replay) that runs past this percentile of "
    "the fleet's recent healthy walls (times fleet.hedgeMarginFactor) "
    "is re-dispatched on a healthy survivor; first result wins, the "
    "loser is discarded with hedgesFired/hedgesWon/"
    "duplicatesSuppressed pinned.", _to_float,
    lambda v: None if 0.5 <= v <= 1.0 else "must be in [0.5, 1.0]")

FLEET_HEDGE_MARGIN = conf(
    "spark.rapids.tpu.fleet.hedgeMarginFactor", 2.0,
    "Multiplier applied to the hedge percentile wall before a hedge "
    "fires — hedging costs duplicate work, so the deadline leaves "
    "honest headroom above the observed healthy tail.", _to_float,
    lambda v: None if v >= 1.0 else "must be >= 1.0")

FLEET_HEDGE_FLOOR_MS = conf(
    "spark.rapids.tpu.fleet.hedgeFloorMs", 25,
    "Floor on the adaptive hedge deadline: never hedge work that has "
    "run for less than this, whatever the observed walls say — "
    "sub-floor work is cheaper to wait out than to duplicate.",
    _to_int, _positive)

WATCHDOG_CALIBRATION_FLOOR_MS = conf(
    "spark.rapids.tpu.watchdog.calibration.floorMs", 1000,
    "Floor for self-calibrated watchdog deadlines (gray-failure mode "
    "only): a calibrated per-point deadline never drops below this, "
    "whatever the observed p99 says — operator-controlled headroom "
    "against a burst of fast observations tightening a deadline onto "
    "normal jitter.", _to_int, _positive)

WATCHDOG_CALIBRATION_CEILING_MS = conf(
    "spark.rapids.tpu.watchdog.calibration.ceilingMs", 600_000,
    "Ceiling for self-calibrated watchdog deadlines (gray-failure "
    "mode only): the calibrated value never exceeds this, so a run "
    "of pathologically slow observations cannot disable hang "
    "detection by inflating the deadline without bound.", _to_int,
    _positive)

WATCHDOG_CALIBRATION_MARGIN = conf(
    "spark.rapids.tpu.watchdog.calibration.marginFactor", 4.0,
    "Multiplier applied to the observed per-point p99 wall to form "
    "the self-calibrated deadline — the deadline is a hang detector, "
    "not a latency SLO, so it sits well above the healthy tail.",
    _to_float, lambda v: None if v >= 1.0 else "must be >= 1.0")

WATCHDOG_CALIBRATION_MIN_SAMPLES = conf(
    "spark.rapids.tpu.watchdog.calibration.minSamples", 8,
    "Observations a point needs before its watchdog deadline "
    "self-calibrates; below this the static conf deadline "
    "(deadline.<point> / defaultDeadlineMs, DCN-scaled) applies "
    "unchanged.", _to_int, _positive)

ENCODING_EXECUTION_ENABLED = conf(
    "spark.rapids.tpu.encoding.execution.enabled", False,
    "Encoded execution: string GROUP BY keys that are bare column "
    "references dictionary-encode ONCE per batch (stable codes across "
    "batches) and the whole filter+project+partial-aggregate stage "
    "evaluates on i32 codes inside the fused kernels "
    "(exec/aggregate.py), with the strings materialized only at the "
    "stage boundary that needs them (the final key decode). This is "
    "what lets string-heavy group-bys (TPC-H q1 shape) ride the "
    "whole-stage fusion path. Any shape the encoder cannot prove "
    "equality-faithful (computed string keys, a key column consumed "
    "by another expression, string-valued min/max buffers) falls back "
    "to the decoded host-dictionary path — never wrong bytes. False "
    "(default) keeps the decoded path everywhere (bit-identical A/B).",
    _to_bool)

ENCODING_EXECUTION_MAX_DICT = conf(
    "spark.rapids.tpu.encoding.execution.maxDictSize", (1 << 31) - 1,
    "Ceiling on distinct values one encoded-execution dictionary may "
    "hold. Exceeding it mid-query raises a RETRYABLE "
    "EncodingOverflowFault after latching encoded execution OFF for "
    "the session, so the recovery ladder's re-planned attempt runs "
    "the decoded path — exact results, never wrong bytes. The hard "
    "bound is i32 code space; lower values bound host dictionary "
    "memory.", _to_int, _positive)

ENCODING_WIRE_ENABLED = conf(
    "spark.rapids.tpu.encoding.wire.enabled", False,
    "Compressed device wire for dictionary-coded columns: exchange "
    "payload columns that carry int64 dictionary codes (string group "
    "keys, encoded min/max partials, string join keys) narrow to ONE "
    "i32 lane on the packed wire (half the bytes per code column) and "
    "widen back after the collective, and each exchange site "
    "broadcasts only its dictionary DELTA (frame-codec compressed, "
    "crc-verified) instead of materialized rows. A corrupt delta "
    "broadcast degrades that launch to the wide (unnarrowed) wire "
    "with a typed EncodedWireInvalid event — exact results either "
    "way. Savings are attributed as encodedBytesSaved in the QueryEnd "
    "shuffle dict. False (default) ships codes at their storage width "
    "(bit-identical A/B).", _to_bool)

ENCODING_STORAGE_HOST_CODEC = conf(
    "spark.rapids.tpu.encoding.storage.hostCodec", "none",
    "Frame codec for HOST-tier spill payloads (and therefore "
    "checkpoint and incremental-state frames, which demote through "
    "the same catalog): none keeps raw numpy buffers (current "
    "behavior); zrle / lz4 / zstd compress the payload through the "
    "shared native frame codec the DISK tier already uses — the "
    "integrity crc32 is still stamped and verified over the DECODED "
    "canonical bytes, so PR3 corruption semantics are unchanged and a "
    "frame that no longer decodes is dropped as corruption. "
    "Compressed host frames also mean checkpoint.maxBytes and "
    "incremental.maxStateBytes meter STORED bytes, buying several "
    "times more standing state per byte.", str,
    lambda v: None if v in ("none", "zrle", "lz4", "zstd")
    else "unknown codec")

COSTMODEL_ENABLED = conf(
    "spark.rapids.tpu.costModel.enabled", False,
    "Self-tuning cost-based planner (plan/costmodel.py): ONE "
    "evidence-fed cost model decides every tuning knob the engine "
    "otherwise takes from hand-set confs — exchange strategy (uniform "
    "vs ragged vs gather vs host-staged), the host-staging threshold, "
    "fusion chain boundaries, coded-vs-decoded execution, shuffle slot "
    "priors, and the coalesce goal — reading per-site evidence from "
    "the PR11 ObservationStore (rows/bytes/skew/compile_ms per "
    "structural site id, persisted beside the AOT cache dir so WARM "
    "STARTS GET WARM PLANS) and falling back to built-in tables when "
    "a site has no history.  Explicitly-set conf keys stay as "
    "OVERRIDES — the model only decides knobs the user left unset.  "
    "Every decision is recorded in a per-query ledger (QueryEnd "
    "'planner' dict -> eventlog -> profiling \"Planner decisions\") "
    "and observed costs fold back into the store so the model "
    "converges.  False (default) changes nothing: plans, events and "
    "results are bit-identical to the model never existing.", _to_bool)

COSTMODEL_DIR = conf(
    "spark.rapids.tpu.costModel.dir", "",
    "Directory holding the cost model's persisted per-site evidence "
    "(the observations.jsonl the span-tracing ObservationStore "
    "writes).  Empty (default) falls back to "
    "spark.rapids.tpu.jitCache.dir, then spark.rapids.tpu.trace.dir; "
    "with no directory at all the model runs on in-memory evidence "
    "only (decisions still work, they just start cold every "
    "process).  A corrupt or truncated store degrades the model to "
    "its built-in defaults with a CostModelInvalid event — never a "
    "failed or wrong query (the costmodel.load injection point).", str)

COSTMODEL_REPLAN_ENABLED = conf(
    "spark.rapids.tpu.costModel.replan.enabled", True,
    "Mid-query adaptive re-planning (requires costModel.enabled and "
    "the recovery ladder): when an exchange launch's measured "
    "statistics contradict the model's plan-time decision past the "
    "hysteresis band (measured skew says ragged, the plan chose "
    "uniform), the launch raises a RETRYABLE ReplanRequested after "
    "folding the fresh evidence into the store — the ladder's retry "
    "rung keeps the mesh layout, completed stages splice from the "
    "checkpoint lineage, and only the contradicted subtree re-plans "
    "with the measured-optimal strategy.  At most ONE replan per "
    "query; False records the contradiction in the decision ledger "
    "without re-driving.", _to_bool)

COSTMODEL_REPLAN_HYSTERESIS = conf(
    "spark.rapids.tpu.costModel.replan.hysteresis", 2.0,
    "How decisively the measured statistics must beat the plan-time "
    "decision before a mid-query replan fires: the contradicting "
    "alternative's predicted win (e.g. uniform wire rows / ragged "
    "wire rows) must be at least this factor.  Higher values replan "
    "less (the band a borderline workload oscillates in without "
    "re-driving).", _to_float,
    lambda v: None if v >= 1.0 else "must be >= 1.0")

CBO_ENABLED = conf(
    "spark.rapids.sql.optimizer.enabled", False,
    "Enable the cost-based optimizer: device regions whose estimated "
    "speedup cannot pay for the host<->device transition costs are "
    "reverted to CPU (reference CostBasedOptimizer.scala:35, default "
    "off).", _to_bool)


TEST_ENABLED = conf(
    "spark.rapids.sql.test.enabled", False,
    "Strict test mode: fail if an op silently falls back to CPU "
    "(reference RapidsConf.scala:928).", _to_bool, internal=True)

TEST_ALLOWED_NON_TPU = conf(
    "spark.rapids.sql.test.allowedNonTpu", "",
    "Comma-separated op names tolerated on CPU in strict test mode "
    "(reference `test.allowedNonGpu`).", str, internal=True)

METRICS_LEVEL = conf(
    "spark.rapids.sql.metrics.level", "MODERATE",
    "Operator metric verbosity: ESSENTIAL, MODERATE, DEBUG "
    "(reference GpuExec.scala MetricsLevel).", str,
    lambda v: None if v in ("ESSENTIAL", "MODERATE", "DEBUG") else
    "must be ESSENTIAL, MODERATE or DEBUG")


# dynamic per-op enable keys (confKey wiring, GpuOverrides.scala:204-296):
# spark.rapids.sql.expression.<Name> / spark.rapids.sql.exec.<Name>
_DYNAMIC_PREFIXES = ("spark.rapids.sql.expression.",
                     "spark.rapids.sql.exec.")
# per-op cost-model overrides (any logical-plan op name): the CBO loads
# calibrated defaults from plan/cbo_weights.json and these keys override
_COST_PREFIXES = ("spark.rapids.sql.optimizer.tpuOpCost.",
                  "spark.rapids.sql.optimizer.cpuOpCost.")
# per-point watchdog deadline overrides (any monitored section name,
# e.g. io.reader / shuffle.exchange / pipeline.worker); values in ms,
# 0 disables that point
_WATCHDOG_DEADLINE_PREFIX = "spark.rapids.tpu.watchdog.deadline."


def _known_key(key: str) -> bool:
    if key in _REGISTRY:
        return True
    if key.startswith(_WATCHDOG_DEADLINE_PREFIX):
        return True
    for p in _COST_PREFIXES:
        if key.startswith(p):
            return True
    for p in _DYNAMIC_PREFIXES:
        if key.startswith(p):
            suffix = key[len(p):]
            try:  # lazy: the planner imports this module
                from spark_rapids_tpu.plan.overrides import valid_op_names
                return suffix in valid_op_names()
            except ImportError:
                return True
    return False


class RapidsConf:
    """Immutable snapshot view over a settings dict (RapidsConf.scala:1281).

    Unknown ``spark.rapids.*`` keys are rejected at construction — a typo
    in a tuning knob must fail loudly, not silently no-op.  Non-rapids
    keys (e.g. ``spark.sql.*`` passthroughs) are kept untouched."""

    def __init__(self, settings: Optional[Dict[str, str]] = None):
        self.settings = dict(settings or {})
        for k in self.settings:
            if k.startswith("spark.rapids.") and not _known_key(k):
                raise ValueError(
                    f"unknown configuration key {k!r}; see "
                    "RapidsConf.registry() for available keys")

    def op_cost(self, side: str, name: str):
        """Per-op cost override (us/row):
        spark.rapids.sql.optimizer.<side>OpCost.<Op>; None = use the
        calibrated default from plan/cbo_weights.json."""
        raw = self.settings.get(
            f"spark.rapids.sql.optimizer.{side}OpCost.{name}")
        return None if raw is None else float(raw)

    def watchdog_deadline_ms(self, point: str) -> int:
        """Per-point watchdog deadline:
        spark.rapids.tpu.watchdog.deadline.<point>, falling back to
        the defaultDeadlineMs entry.  0 disables the point."""
        raw = self.settings.get(_WATCHDOG_DEADLINE_PREFIX + point)
        if raw is None:
            return self.get(WATCHDOG_DEFAULT_DEADLINE_MS)
        return int(raw)

    def op_enabled(self, kind: str, name: str) -> bool:
        """Per-op enable key: spark.rapids.sql.<kind>.<Name>, default
        True (the reference derives one such key per replacement rule)."""
        raw = self.settings.get(f"spark.rapids.sql.{kind}.{name}")
        if raw is None:
            return True
        return raw if isinstance(raw, bool) else _to_bool(str(raw))

    def get(self, entry: ConfEntry) -> Any:
        return entry.get(self.settings)

    def is_set(self, entry: ConfEntry) -> bool:
        """True when the user EXPLICITLY configured this entry (the
        settings dict or its env-var form).  The cost model treats
        explicit confs as overrides and only decides unset knobs."""
        if entry.key in self.settings:
            return True
        return os.environ.get(entry.env_key()) is not None

    def __getitem__(self, key: str) -> Any:
        return _REGISTRY[key].get(self.settings)

    def set(self, key: str, value) -> "RapidsConf":
        s = dict(self.settings)
        s[key] = value
        return RapidsConf(s)

    # convenience accessors used on hot paths
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return self.get(EXPLAIN)

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def max_batch_rows(self) -> int:
        return self.get(BATCH_ROW_CAPACITY)

    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    @staticmethod
    def registry() -> Dict[str, ConfEntry]:
        return dict(_REGISTRY)

    @staticmethod
    def generate_docs() -> str:
        """Render docs/configs.md (reference RapidsConf.main)."""
        lines = ["# spark-rapids-tpu Configuration", "",
                 "Name | Description | Default", "---|---|---"]
        for key in sorted(_REGISTRY):
            e = _REGISTRY[key]
            if e.internal:
                continue
            lines.append(f"{e.key} | {e.doc} | {e.default}")
        return "\n".join(lines) + "\n"
