"""Standing-query fleets: shared-ingest fan-out over one fact stream.

PR13 gave one standing query epoch semantics (``session.incremental``);
this module composes N of them over the SAME append-only stream so a
dashboard fleet costs far less than N lone runners (ROADMAP item —
the "Accelerating Presto with GPUs" multi-tenant near-duplicate
workload, with Theseus's keep-shared-data-movement-minimal
discipline):

- **one source pull per round** — ``FleetRunner.tick(new_paths)``
  stats and reads the delta files ONCE (stat-before-read, the epoch
  store's mutation-safety rule) and lends the materialized batches to
  every subscriber as a :class:`~spark_rapids_tpu.robustness.
  incremental.SharedIngest`; each subscriber's partial plan swaps its
  fact scan for an InMemoryRelation over the shared batches — N
  queries, 1 pull per new file.  A subscriber whose read shape the
  loan cannot reproduce (metadata columns, pushdown pruning, its own
  catch-up backlog after a faulted round) falls back to its own pull:
  correct, just unshared.
- **independent epochs** — every subscriber keeps its OWN
  IncrementalStateStore; each tick inside a round commits or rolls
  back alone, so one subscriber's chaos fault degrades that
  subscriber to a (correct) recompute and never poisons a
  co-subscriber's tick.  A subscriber whose degraded recompute ALSO
  fails stays on its committed epoch and catches up on a later round.
- **epoch-aware cross-subscriber splice** — at commit each store
  publishes its file-fingerprinted stage entries to the session
  SharedStageCache's epoch tier (serving/reuse.py); subscribers
  sharing a delta-join subtree (the same dimension aggregate, say)
  splice each other's COMMITTED tick work instead of re-running it.
- **exactly-once emission** — every subscriber tick yields a
  :class:`~spark_rapids_tpu.robustness.incremental.SinkCommit`
  (payload CRC + committed epoch + store id) that rode its atomic
  epoch commit; replays re-emit the same epoch idempotently.

Subscriber ticks run sequentially inside a round, each under its own
``deadline_override`` — every execution admits through the fair
interleaver with deadline-weighted quanta (serving/scheduler.py), so
a latency-pinned subscriber keeps its service level while sharing
the mesh with the rest of the fleet and with ad-hoc queries.

Observable: one ``FleetRound`` event per round (subscriber count,
delta files, source pulls, cross-subscriber splices, failures) →
eventlog → profiling "Continuous ingest" rollup.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from spark_rapids_tpu.robustness.incremental import (MicroBatchRunner,
                                                     SharedIngest,
                                                     SinkCommit,
                                                     tick_execution_scope)
from spark_rapids_tpu.serving.context import deadline_override


class FleetHandle:
    """One subscriber's view of the fleet: ``handle.tick(new_paths)``
    drives a WHOLE fleet round (every co-subscriber ticks too — the
    stream moved for all of them) and returns this subscriber's
    :class:`SinkCommit` — or re-raises this subscriber's own fault
    after the co-subscribers finished their ticks."""

    def __init__(self, fleet: "FleetRunner", name: str,
                 runner: MicroBatchRunner, deadline_ms: int):
        self.fleet = fleet
        self.name = name
        self.runner = runner
        self.deadline_ms = int(deadline_ms or 0)
        # paths offered to a round whose tick FAILED: re-offered next
        # round (the runner dedupes anything it did commit), so a
        # faulted subscriber's missed files are queued, never lost
        self._backlog: List[str] = []

    def tick(self, new_paths=()) -> Optional[SinkCommit]:
        self.fleet.tick(new_paths)
        err = self.fleet.last_round_errors.get(self.name)
        if err is not None:
            raise err
        return self.runner.last_sink_commit

    @property
    def last_tick_info(self) -> Dict[str, object]:
        return self.runner.last_tick_info

    def close(self) -> None:
        self.fleet.unsubscribe(self.name)


class FleetRunner:
    """N standing queries over one append-only fact stream, ticked in
    shared-ingest rounds (module docstring).  ``session.fleet()``."""

    def __init__(self, session):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session = session
        self.shared_ingest = bool(
            session.conf.get(rc.FLEET_SHARED_INGEST_ENABLED))
        self._handles: Dict[str, FleetHandle] = {}
        self._seq = 0
        self._offered: set = set()   # every path any round has pulled
        self._round = 0
        self._lock = threading.Lock()
        self.last_round_info: Dict[str, object] = {}
        self.last_round_errors: Dict[str, BaseException] = {}

    # ---------------------------------------------------------- membership --
    def subscribe(self, df, name: Optional[str] = None, fact=None,
                  watermark_delay_ms=None, deadline_ms: int = 0,
                  on_commit=None) -> FleetHandle:
        """Register one standing query.  ``watermark_delay_ms``
        overrides the session conf for THIS subscriber (independent
        eviction schedules over one shared ingest); ``deadline_ms``
        rides every execution of its ticks as the fair interleaver's
        deadline budget; ``on_commit(SinkCommit)`` fires after each of
        its commits (tick scope, NOT tick execution — queries it
        issues cache normally)."""
        with self._lock:
            self._seq += 1
            if name is None:
                name = f"q{self._seq}"
            if name in self._handles:
                raise ValueError(f"subscriber {name!r} already exists")
            runner = MicroBatchRunner(
                self.session, df, fact=fact,
                watermark_delay_ms=watermark_delay_ms)
            runner.on_commit = on_commit
            handle = FleetHandle(self, name, runner, deadline_ms)
            self._handles[name] = handle
            return handle

    def unsubscribe(self, name: str) -> None:
        with self._lock:
            h = self._handles.pop(name, None)
        if h is not None:
            h.runner.close()

    @property
    def subscribers(self) -> List[str]:
        return list(self._handles)

    # --------------------------------------------------------------- rounds --
    def _pull_once(self, paths) -> Optional[SharedIngest]:
        """The round's ONE source pull: stat the delta (the meta every
        subscriber's fingerprint will be stamped from — BEFORE the
        read, so a file mutating mid-round is caught by the next
        staleness check, never hidden), then materialize it through
        the full engine path under the tick-execution marker (no
        result-cache pollution: the loan's identity lives in the
        subscribers' epoch fingerprints, not a plan-keyed cache)."""
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.io.readers import scan_input_meta
        from spark_rapids_tpu.plan import logical as L
        tmpl = next((h.runner._scan
                     for h in self._handles.values()
                     if h.runner._scan is not None), None)
        if tmpl is None:
            return None
        try:
            rel = L.FileRelation(list(paths), tmpl.file_format,
                                 tmpl._schema, dict(tmpl.options))
            schema_names = [(n, d.name) for n, d in rel.schema]
            meta = scan_input_meta(list(paths))
            with tick_execution_scope():
                batches = DataFrame(self.session,
                                    rel)._execute_batches()
        except Exception:
            # the shared pull is an optimization: any failure here
            # (schema not yet resolvable, reader fault) downgrades the
            # round to per-subscriber pulls, which carry their own
            # fault handling
            return None
        return SharedIngest(paths, meta, batches, schema_names)

    def tick(self, new_paths=()) -> Dict[str, Optional[SinkCommit]]:
        """One fleet round: pull the delta once, then tick every
        subscriber with the loan.  Subscriber faults are ISOLATED —
        recorded in ``last_round_errors`` (and re-raised by that
        subscriber's own ``handle.tick``) while every co-subscriber's
        tick proceeds; the faulted subscriber's store stays on its
        committed epoch and its missed files stay queued for the next
        round (its catch-up delta simply exceeds the loan and reads
        its own history)."""
        offered = [new_paths] if isinstance(new_paths, str) \
            else list(new_paths)
        with self._lock:
            handles = list(self._handles.values())
            self._round += 1
            rnd = self._round
            # the fleet's view of "new": never pulled by any round.
            # Round 1 folds in the subscribers' common initial file
            # set (their first tick ingests scan history + delta, and
            # the loan must span exactly that to be usable).
            delta: List[str] = []
            seen = set(self._offered)
            if rnd == 1:
                inits = {tuple(sorted(h.runner._initial))
                         for h in handles if h.runner._scan is not None}
                if len(inits) == 1:
                    for p in sorted(inits.pop()):
                        if p not in seen:
                            seen.add(p)
                            delta.append(p)
            for p in offered:
                if p not in seen:
                    seen.add(p)
                    delta.append(p)
            self._offered = seen

            ingest = None
            if self.shared_ingest and delta and handles:
                ingest = self._pull_once(delta)

            shared = getattr(self.session, "shared_stages", None)
            r0 = shared.local["resumes"] \
                if shared is not None and shared.enabled else 0
            results: Dict[str, Optional[SinkCommit]] = {}
            errors: Dict[str, BaseException] = {}
            for h in handles:
                # catch-up: files a FAILED earlier tick never
                # committed ride ahead of this round's delta (the
                # loan no longer spans the offer, so the runner
                # falls back to its own pull — correct, unshared)
                offer = [p for p in h._backlog if p not in offered] \
                    + offered
                try:
                    with deadline_override(h.deadline_ms):
                        h.runner.tick(offer, _ingest=ingest)
                    results[h.name] = h.runner.last_sink_commit
                    h._backlog = []
                except Exception as exc:  # noqa: BLE001 - isolation:
                    # the runner already rolled back to its committed
                    # epoch; the fault is THIS subscriber's alone
                    errors[h.name] = exc
                    results[h.name] = None
                    h._backlog = [p for p in offer
                                  if p not in offered] + list(delta)
            splices = (shared.local["resumes"] - r0) \
                if shared is not None and shared.enabled else 0
            # standing queries tick for hours: every round is also a
            # gray-failure detection boundary — fold the accumulated
            # wall/heartbeat evidence into per-host states so a host
            # going fail-slow mid-stream surfaces as HostSuspect here,
            # not only at the next ad-hoc query
            tracker = getattr(self.session, "gray_health", None)
            suspects = 0
            if tracker is not None:
                try:
                    states = tracker.poll()
                    suspects = sum(1 for s in states.values()
                                   if s != "healthy")
                except Exception:
                    pass  # detection must never fault a round
            self.last_round_errors = errors
            self.last_round_info = {
                "round": rnd,
                "subscribers": len(handles),
                "deltaFiles": len(delta),
                "sourcePulls": len(delta) if ingest is not None
                else len(delta) * len(handles),
                "sharedIngest": ingest is not None,
                "splices": int(splices),
                "failures": len(errors),
            }
            from spark_rapids_tpu.utils.events import emit_on_session
            emit_on_session(
                "FleetRound", session=self.session,
                round=rnd, subscribers=len(handles),
                deltaFiles=len(delta),
                sourcePulls=int(self.last_round_info["sourcePulls"]),
                splices=int(splices), failures=len(errors),
                **({"suspectHosts": suspects} if suspects else {}))
            return results

    def close(self) -> None:
        with self._lock:
            handles, self._handles = list(self._handles.values()), {}
        for h in handles:
            h.runner.close()
