"""Byte-weighted, fair admission semaphore for concurrent queries.

The reference throttles device pressure with ``GpuSemaphore``: every
task acquires before touching the GPU, weighted so concurrent tasks
cannot oversubscribe memory (GpuSemaphore.scala:28,
``spark.rapids.sql.concurrentGpuTasks``).  This module is the
query-granularity analog for a serving session: hundreds of small
interactive queries share one mesh, and admission — not scheduling —
is what keeps one query's footprint from becoming another's OOM.

:class:`AdmissionController` grants :class:`AdmissionTicket`\\ s under
two simultaneous constraints:

- **count**: at most ``concurrentQueries`` admitted at once;
- **bytes**: admitted queries' declared memory weights must fit in
  ``hbm_bytes`` (``deviceBudget * hbmAdmissionFraction``); a query
  heavier than the whole budget still admits *alone* (progress over
  perfection — the spill tiers absorb the overshoot).

Waiting is **strict FIFO** (ticket order), which makes starvation
impossible by construction: a heavy query at the head blocks later
light ones rather than being overtaken forever.  Two typed rejection
paths exist so saturation degrades the *arriving* query instead of
wedging the session: a bounded queue (``maxQueuedQueries``) rejects at
arrival, and a wait deadline (``admissionTimeoutMs``) rejects a queued
query — both as :class:`~..robustness.faults.AdmissionFault`, which
the recovery ladder classifies FATAL-for-this-query and hands back.

Every grant/rejection emits an ``Admission`` / ``AdmissionReject``
event, and cumulative counters (``snapshot()``) feed bench.py's
``--concurrency`` mode and the profiling concurrency report.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, Optional

from spark_rapids_tpu.robustness.faults import AdmissionFault


class AdmissionTicket:
    """One admitted (or queued) query's place in the controller."""

    _seqs = itertools.count(1)

    __slots__ = ("seq", "weight_bytes", "admitted")

    def __init__(self, weight_bytes: int):
        self.seq = next(AdmissionTicket._seqs)
        self.weight_bytes = int(weight_bytes)
        self.admitted = False


class AdmissionController:
    def __init__(self, max_queries: int, hbm_bytes: int,
                 default_weight: int = 0, timeout_ms: int = 0,
                 max_queue: int = 0):
        self.max_queries = int(max_queries)
        self.hbm_bytes = int(hbm_bytes)
        # weight a query declares when it has no explicit budget:
        # an equal share of the admission bytes
        self.default_weight = int(default_weight) or max(
            self.hbm_bytes // max(self.max_queries, 1), 1)
        self.timeout_ms = int(timeout_ms)
        self.max_queue = int(max_queue)
        self._cond = threading.Condition()
        self._queue: deque = deque()   # waiting tickets, FIFO
        self._active: Dict[int, AdmissionTicket] = {}  # seq -> ticket
        self.admitted_bytes = 0
        # cumulative observability (bench --concurrency / profiling)
        self.total_admitted = 0
        self.total_rejected = 0
        self.total_wait_ns = 0
        self.peak_concurrent = 0
        self.peak_queue_depth = 0

    # ------------------------------------------------------------ internals --
    def _fits(self, ticket: AdmissionTicket) -> bool:
        if len(self._active) >= self.max_queries:
            return False
        if not self._active:
            return True  # never deadlock a query heavier than the pool
        return self.admitted_bytes + ticket.weight_bytes <= self.hbm_bytes

    def _emit(self, session, event: str, **fields) -> None:
        from spark_rapids_tpu.utils.events import emit_on_session
        try:
            emit_on_session(event, session=session, **fields)
        except Exception:
            pass  # admission decisions must never die on a log write

    # ------------------------------------------------------------- interface --
    def acquire(self, weight_bytes: Optional[int] = None,
                session=None) -> AdmissionTicket:
        """Block (FIFO) until admitted; returns the ticket to pass to
        :meth:`release`.  Raises AdmissionFault on a full queue or a
        wait past ``timeout_ms``."""
        w = int(weight_bytes) if weight_bytes else self.default_weight
        ticket = AdmissionTicket(w)
        t0 = time.perf_counter_ns()
        deadline = None if self.timeout_ms <= 0 else \
            time.monotonic() + self.timeout_ms / 1e3
        # rejections are decided under the lock but emitted/raised
        # outside it — an eventlog write on a slow disk must never
        # stall every other tenant's acquire/release behind _cond
        reject = None  # (event fields, AdmissionFault)
        with self._cond:
            if self.max_queue and len(self._queue) >= self.max_queue:
                self.total_rejected += 1
                reject = (
                    dict(reason="queue-full", queued=len(self._queue)),
                    AdmissionFault(
                        "queue-full",
                        f"{len(self._queue)} queries already queued "
                        f"(maxQueuedQueries={self.max_queue})"))
            else:
                self._queue.append(ticket)
                self.peak_queue_depth = max(self.peak_queue_depth,
                                            len(self._queue))
                while not (self._queue[0] is ticket and
                           self._fits(ticket)):
                    timeout = None
                    if deadline is not None:
                        timeout = deadline - time.monotonic()
                        if timeout <= 0:
                            self._queue.remove(ticket)
                            self._cond.notify_all()
                            self.total_rejected += 1
                            wait_ms = \
                                (time.perf_counter_ns() - t0) / 1e6
                            reject = (
                                dict(reason="timeout",
                                     waitMs=round(wait_ms, 3)),
                                AdmissionFault(
                                    "timeout",
                                    f"waited {wait_ms:.0f}ms > "
                                    f"admissionTimeoutMs="
                                    f"{self.timeout_ms}"))
                            break
                    self._cond.wait(timeout)
                if reject is None:
                    self._queue.popleft()
                    ticket.admitted = True
                    self._active[ticket.seq] = ticket
                    self.admitted_bytes += ticket.weight_bytes
                    self.total_admitted += 1
                    self.peak_concurrent = max(self.peak_concurrent,
                                               len(self._active))
                    wait_ns = time.perf_counter_ns() - t0
                    self.total_wait_ns += wait_ns
                    active = len(self._active)
                    queued = len(self._queue)
                    # the head may now also fit (count freed by a
                    # racer, or several light queries behind a
                    # just-admitted one)
                    self._cond.notify_all()
        if reject is not None:
            fields, fault = reject
            self._emit(session, "AdmissionReject", **fields)
            raise fault
        self._emit(session, "Admission", waitMs=round(wait_ns / 1e6, 3),
                   weightBytes=ticket.weight_bytes, active=active,
                   queued=queued)
        return ticket

    def release(self, ticket: AdmissionTicket) -> None:
        with self._cond:
            if self._active.pop(ticket.seq, None) is None:
                return  # double release / never admitted
            self.admitted_bytes -= ticket.weight_bytes
            self._cond.notify_all()

    def snapshot(self) -> Dict[str, float]:
        with self._cond:
            return {
                "active": len(self._active),
                "queued": len(self._queue),
                "admittedBytes": self.admitted_bytes,
                "totalAdmitted": self.total_admitted,
                "totalRejected": self.total_rejected,
                "totalWaitMs": round(self.total_wait_ns / 1e6, 3),
                "peakConcurrent": self.peak_concurrent,
                "peakQueueDepth": self.peak_queue_depth,
            }
