"""FleetStore: fleet-scoped cache entries on shared storage, with
epoch-fenced writers.

The session caches (serving/reuse.py: result/template tiers, the
shared stage cache) are already host-tier, CRC-verified and
owner-attributed — this module is the storage layer that promotes them
to FLEET scope: a directory every host can reach
(``spark.rapids.tpu.fleet.cache.dir``) holding one atomic blob per
entry, so a repeated plan on ANY host answers from a peer's work.

**Fencing** is the correctness core.  A host that was partitioned away
(or judged lost and shrunk out of the mesh) may still be running — a
*zombie* — and may try to publish an entry it computed before it was
cut off.  Every writer therefore carries a fence token: the registry
epoch it read at session start (or at its last fence refresh).  The
shrink rung bumps the epoch atomically with the mesh swap, so a zombie
publish arrives with ``token < epoch`` and is REJECTED under the
publish lock — counted, health-checked (``FleetCacheFence`` events),
and never written where a reader could see it.  This generalizes the
ObservationStore's lock-file-merge discipline (utils/locking.py is the
shared lock) from "merge, last writer wins field-wise" to "publish
only while your lease on the layout is current".

Readers never need the lock: entries land by atomic rename and every
blob re-verifies its CRC at lookup, so a torn or rotted file is a miss
(never wrong bytes) — the same verification discipline every other
tier in the engine follows.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import zlib
from typing import Any, Optional, Tuple

from spark_rapids_tpu.utils.locking import InterProcessLock

_FENCE_FILE = "fence.json"
_BLOB_MAGIC = b"SRTFC1\n"


def _entry_path(dirpath: str, key: str) -> str:
    return os.path.join(
        dirpath, f"e-{hashlib.sha256(key.encode()).hexdigest()}.bin")


class FleetStore:
    """One fleet's shared cache directory.  All methods are
    best-effort: storage trouble degrades to a miss / skipped publish,
    never to an error on the query path."""

    def __init__(self, dirpath: str, session=None):
        self.dir = dirpath
        self._session = session
        self._lock = threading.Lock()
        self._fence_lock = InterProcessLock(
            os.path.join(dirpath, _FENCE_FILE + ".lock"))
        # counters surfaced via stats() -> bench/tests; cross_hits are
        # hits on entries another PROCESS published (the fleet payoff)
        self.counters = {"hits": 0, "cross_hits": 0, "misses": 0,
                         "stores": 0, "fenced": 0}
        os.makedirs(dirpath, exist_ok=True)

    def _emit(self, **fields) -> None:
        try:
            from spark_rapids_tpu.utils.events import emit_on_session
            emit_on_session("FleetCacheFence", self._session, **fields)
        except Exception:
            pass

    # ------------------------------------------------------------- fence --
    def fence_epoch(self) -> int:
        """Current fence epoch (0 for a fresh directory)."""
        try:
            with open(os.path.join(self.dir, _FENCE_FILE),
                      encoding="utf-8") as f:
                return int(json.load(f).get("epoch", 0))
        except (OSError, ValueError):
            return 0

    def bump_fence(self, reason: str = "") -> int:
        """Advance the fence epoch (shrink rung, membership change).
        Every writer still holding the old token is fenced from here
        on.  Returns the new epoch — the caller's fresh token."""
        path = os.path.join(self.dir, _FENCE_FILE)
        got = self._fence_lock.acquire(timeout_s=5.0)
        try:
            epoch = self.fence_epoch() + 1
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"epoch": epoch}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError:
                return self.fence_epoch()
        finally:
            if got:
                self._fence_lock.release()
        self._emit(action="bump", fenceEpoch=epoch, reason=reason)
        return epoch

    # ----------------------------------------------------------- publish --
    def publish(self, key: str, obj: Any, token: int) -> bool:
        """Write ``obj`` under ``key`` — IF the writer's fence
        ``token`` is still current.  The token check runs under the
        fence lock, so a concurrent bump either lands before (publish
        rejected) or after (entry was valid when the bump fenced it) —
        a zombie can never slip an entry past an epoch it didn't
        live through."""
        got = self._fence_lock.acquire(timeout_s=2.0)
        if not got:
            return False  # contended storage: skip, it's only a cache
        try:
            fence = self.fence_epoch()
            if token < fence:
                with self._lock:
                    self.counters["fenced"] += 1
                self._emit(action="reject", key=key[:64],
                           writerEpoch=int(token), fenceEpoch=fence,
                           reason="stale fence token")
                return False
            try:
                blob = pickle.dumps(
                    {"key": key, "epoch": int(token),
                     "owner": os.getpid(), "payload": obj},
                    protocol=pickle.HIGHEST_PROTOCOL)
            except Exception:
                return False  # unpicklable payload: not publishable
            path = _entry_path(self.dir, key)
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(_BLOB_MAGIC)
                    f.write(zlib.crc32(blob).to_bytes(4, "big"))
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return False
        finally:
            if got:
                self._fence_lock.release()
        with self._lock:
            self.counters["stores"] += 1
        return True

    # ------------------------------------------------------------ lookup --
    def lookup(self, key: str) -> Optional[Tuple[Any, int]]:
        """Fetch ``key``'s payload -> (payload, owner_pid), or None.
        Lock-free: entries land by atomic rename, and the CRC gate
        turns any torn/rotted blob into a miss — never wrong bytes."""
        path = _entry_path(self.dir, key)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            with self._lock:
                self.counters["misses"] += 1
            return None
        try:
            if not raw.startswith(_BLOB_MAGIC):
                raise ValueError("bad magic")
            off = len(_BLOB_MAGIC)
            crc = int.from_bytes(raw[off:off + 4], "big")
            blob = raw[off + 4:]
            if zlib.crc32(blob) != crc:
                raise ValueError("crc mismatch")
            rec = pickle.loads(blob)
            if rec.get("key") != key:
                raise ValueError("key collision")
        except Exception:
            # verification failure: drop the blob so it cannot keep
            # missing, and report a miss
            try:
                os.unlink(path)
            except OSError:
                pass
            with self._lock:
                self.counters["misses"] += 1
            return None
        owner = int(rec.get("owner", 0))
        with self._lock:
            self.counters["hits"] += 1
            if owner != os.getpid():
                self.counters["cross_hits"] += 1
        return rec.get("payload"), owner

    # ------------------------------------------------------------- stats --
    def stats(self) -> dict:
        with self._lock:
            return dict(self.counters)
