"""Session-level serving layer: admission control and query isolation.

Multi-tenant serving (ROADMAP open item 3) means hundreds of
concurrent small queries sharing one mesh, and the robustness contract
tightens from "this query recovers" to "this query's failure cannot
become another query's wrong answer or crash".  Two pieces:

- ``admission`` — the byte-weighted, fair FIFO admission semaphore
  (the reference GpuSemaphore at query granularity): bounds concurrent
  queries and their summed memory weights, with typed
  ``AdmissionFault`` rejection on queue overflow / wait timeout.
- ``context`` — ``QueryContext``: scopes every formerly-global piece
  of robustness state (query-id event attribution, checkpoint lineage,
  injection scoping, watchdog tokens, host-sync/retry attribution,
  spill ownership and budgets) to one query, and purges stale
  thread-ident adoptions at exit so OS ident reuse can never splice
  two queries' state.

See docs/robustness.md "Admission control & query isolation".
"""

from spark_rapids_tpu.serving.admission import (  # noqa: F401
    AdmissionController, AdmissionTicket)
from spark_rapids_tpu.serving.context import QueryContext  # noqa: F401
