"""Cross-query computation reuse: result cache + shared stage cache.

Most production dashboard traffic is near-duplicate ("Accelerating
Presto with GPUs", PAPERS.md), and Theseus (PAPERS.md) frames
recomputation as the most expensive data movement of all.  All the
safety machinery this module needs already exists — PR5's structural
stage ids, PR7's input fingerprints + ``always_resume`` splice, PR3's
CRC-stamped spill tiers — it was just scoped per-session/per-query.
This module promotes it to *shared*:

**ResultCache** — a plan-keyed, budgeted, host/disk-tier store of whole
query results, consulted by ``DataFrame._execute_batches`` before
planning.  The EXACT tier keys on the plan's structural signature
(``plan/template.py:plan_signature`` — node structure plus every
expression cache_key, literal VALUES included; the rendered tree text
alone hid aliased-literal digits behind output names, and the
digit-normalized key the compare tools use would alias ``limit(5)``
with ``limit(10)``).  The TEMPLATE tier keys on (normalized template
fingerprint, parameter vector) — one entry per literal binding of a
hoisted plan template, same verification discipline.  Either way a
**hit additionally requires the plan's input fingerprint to match** (``checkpoint.input_fingerprint``:
file path/size/mtime_ns triples + in-memory batch identities, statted
fresh at lookup) — so a hit answers with zero executions and a mutated
input can never serve stale bytes.  Every hit re-verifies the store's
own canonical CRC (the checkpoint-restore discipline; the
``resultcache.load`` injection point feeds the gate real rot in
chaos); any failure invalidates the entry and the query recomputes.
Plans containing UDFs or pandas stages are never cached (arbitrary
Python is not provably deterministic).

**SharedStageCache** — the ``always_resume`` checkpoint store promoted
to a shared, multi-tenant, session-scoped store: every mesh query
registers its completed exchange stages and consults the store on
FIRST attempts, so two different queries sharing a subtree (same scan
+ filter + partial aggregate, proven by structural stage id + input
fingerprint) splice each other's checkpoints through the existing
``try_distributed(resume=True)`` path.  Entries carry owner
attribution (the registering query's id and QueryContext ident, so
per-owner spill billing sees them); the recovery driver's
layout-rung ``clear()`` is a no-op here — a rung demotes ONE query off
the mesh, while committed entries stay keyed to (subtree, mesh layout,
inputs), all of which survive and serve the next tenant.  CRC failure,
eviction and fingerprint drift all degrade to recompute — never wrong
bytes, never a failed query.

Both stores live in the session's spill catalog (host-demoted at
write, so standing reuse state never competes with live batches for
HBM) under ``spark.rapids.tpu.serving.{resultCache,sharedStage}.*``.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
from typing import Dict, List, Optional

import numpy as np

from spark_rapids_tpu.robustness.checkpoint import (CheckpointManager,
                                                    input_fingerprint)
from spark_rapids_tpu.robustness.inject import (fire, fire_mutate,
                                                register_point)

# chaos surface: raise/delay rules wedge/abort a cache load (the query
# degrades to a recompute MISS — never a failure), corrupt rules flip
# result-payload bits so the CRC gate has real rot to catch.  The
# template tier has its own point so the spray can rot template hits
# specifically without touching exact-tier traffic.
register_point("resultcache.load")
register_point("templatecache.load")

# spill priorities: reuse state is insurance, colder than per-query
# checkpoints (-1500) but warmer than standing incremental state
# (-2000) — a live query's lineage always wins HBM over shared caches
SHARED_STAGE_PRIORITY = -1750
RESULT_CACHE_PRIORITY = -1800


def _coop_acquire(lock) -> None:
    """Watchdog-cooperative lock acquire: a tenant blocked behind a
    wedged peer (chaos delay on a store point) still receives its
    deadline cancellation instead of waiting forever."""
    from spark_rapids_tpu.robustness import watchdog
    while not lock.acquire(timeout=0.05):
        watchdog.checkpoint()


class _Locked:
    """``with _Locked(lock):`` using the cooperative acquire."""

    __slots__ = ("lock",)

    def __init__(self, lock):
        self.lock = lock

    def __enter__(self):
        _coop_acquire(self.lock)
        return self

    def __exit__(self, *exc):
        self.lock.release()
        return False


def _rebuild_batch(schema, payload: dict, nrows: int):
    """Host-side ColumnarBatch from a canonical payload dict (the
    spill module's key layout) — the cached copy never aliases the
    live result's (possibly device-resident) buffers."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    cols = {}
    for name, dt in schema:
        data = payload.get(f"{name}.data")
        if data is None:
            data = np.zeros(
                0, dtype=np.uint8 if dt.is_string else dt.storage)
        cols[name] = Column(dt, np.ascontiguousarray(data), nrows,
                            validity=payload.get(f"{name}.validity"),
                            offsets=payload.get(f"{name}.offsets"))
    return ColumnarBatch(cols, nrows)


def _inmemory_batches(plan) -> list:
    """Every live batch object an InMemoryRelation leaf references —
    the objects whose ``id()``s the input fingerprint encodes."""
    from spark_rapids_tpu.plan import logical as L
    out = []

    def walk(node):
        if isinstance(node, L.InMemoryRelation):
            out.extend(node.batches)
        for c in node.children:
            walk(c)

    walk(plan)
    return out


class _CachedResult:
    """One plan's stored result: per-batch spill handles + the
    metadata to verify and rebuild them.

    ``pins`` holds WEAK references to the in-memory input batches the
    stored fingerprint's ``id()``s describe: ``id()`` identity is only
    sound while the object lives, so a dead referent (CPython may
    recycle the address onto different data) invalidates the entry at
    the next lookup instead of risking a stale-aliased hit.  Weak, not
    strong — the cache must never pin a client's (possibly
    device-resident) input batches alive."""

    __slots__ = ("key", "fingerprint", "schema", "parts", "seq",
                 "owner_qid", "hits", "pins")

    def __init__(self, key, fingerprint, schema, parts, seq, owner_qid,
                 pins=()):
        self.key = key
        self.fingerprint = fingerprint
        self.schema = list(schema)
        # [(handle, crc, nrows)] in batch order
        self.parts = parts
        self.seq = seq
        self.owner_qid = owner_qid
        self.hits = 0
        import weakref
        self.pins = [weakref.ref(b) for b in pins]

    def pins_alive(self) -> bool:
        return all(r() is not None for r in self.pins)

    @property
    def stored_bytes(self) -> int:
        return sum(h.stored_bytes for h, _, _ in self.parts
                   if not h.closed)

    def close(self) -> None:
        for h, _, _ in self.parts:
            try:
                h.close()
            except Exception:
                pass


class PendingResult:
    """The token ``offer()`` hands back: carries the key and the
    PRE-execution input fingerprint (stat-before-read — a file mutated
    mid-execution leaves the entry stamped with pre-mutation identity,
    so the next lookup's fresh stat walk misses instead of serving
    stale bytes)."""

    __slots__ = ("key", "fingerprint", "hit", "batches", "cacheable",
                 "pins", "tier")

    def __init__(self):
        self.key: Optional[str] = None
        self.fingerprint: Optional[str] = None
        self.hit = False
        self.batches = None
        self.cacheable = False
        self.pins: list = []  # live in-memory input batch objects
        self.tier = "exact"   # "exact" | "template"


class ResultCache:
    """Session-scoped, budgeted result store (see module docstring)."""

    def __init__(self, session):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session = session
        conf = session.conf
        self.enabled = bool(conf.get(rc.SERVING_RESULT_CACHE_ENABLED))
        self.max_bytes = int(
            conf.get(rc.SERVING_RESULT_CACHE_MAX_BYTES))
        self.catalog = getattr(session, "memory_catalog", None)
        self._entries: Dict[str, _CachedResult] = {}
        self._seq = itertools.count(1)
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.invalidations = 0
        self.evictions = 0
        # template tier (ISSUE 17): entries share the map/budget/locks
        # with the exact tier ("T:"-prefixed keys), counted separately
        self.template_hits = 0
        self.template_misses = 0
        self.template_stores = 0
        # fleet tier (ISSUE 18): a FleetStore on shared storage,
        # consulted when the LOCAL map misses and published to after a
        # local store.  Only pin-free plans participate: the weak-pin
        # discipline keys in-memory inputs by ``id()``, which is
        # process-local — a cross-process id match proves nothing, so
        # plans with in-memory leaves never cross the process boundary.
        self.fleet = getattr(session, "fleet_cache", None)
        self.fleet_hits = 0
        self.fleet_stores = 0

    # ------------------------------------------------------------- helpers --
    @staticmethod
    def plan_key(plan) -> str:
        """EXACT plan identity: the structural signature — node
        structure plus every expression cache_key, literal VALUES
        included (two plans differing only in literal digits can never
        alias, even where describe() shows output names only).  The
        data the plan reads is keyed separately by the input
        fingerprint."""
        from spark_rapids_tpu.plan.template import plan_signature
        return hashlib.sha256(
            repr(plan_signature(plan)).encode()).hexdigest()

    @staticmethod
    def template_key(fingerprint: str, param_vector) -> str:
        """TEMPLATE tier identity: (normalized template fingerprint,
        canonical parameter vector)."""
        return "T:" + hashlib.sha256(
            (fingerprint + "|" + repr(param_vector)).encode()).hexdigest()

    @staticmethod
    def cacheable(plan) -> bool:
        """Only provably-deterministic plans cache: anything routing
        through arbitrary Python (UDF expressions, *InPandas stages)
        is refused — a stale answer is worse than no cache."""
        text = plan.tree_string()
        return not ("UDF" in text or "InPandas" in text or
                    "ArrowEval" in text)

    def _emit(self, event: str, **fields) -> None:
        from spark_rapids_tpu.utils.events import emit_on_session
        try:
            emit_on_session(event, session=self.session, **fields)
        except Exception:
            pass  # cache bookkeeping must never fail a query

    def _note_sharing(self, **fields) -> None:
        from spark_rapids_tpu.serving import context as qc
        ctx = qc.current()
        if ctx is not None:
            ctx.sharing.update(fields)

    # -------------------------------------------------------------- lookup --
    def offer(self, plan, count_miss: bool = True) -> PendingResult:
        """Consult the cache BEFORE planning.  ``pend.hit`` with the
        stored batches on a verified hit; otherwise the caller
        executes normally and hands the result to :meth:`store` with
        the same token.  ``count_miss=False`` for the post-admission
        RE-consult of a query that already missed once — the second
        lookup must not double-count the same miss."""
        pend = PendingResult()
        if not self.enabled or self.catalog is None:
            return pend
        try:
            pend.cacheable = self.cacheable(plan)
            if not pend.cacheable:
                return pend
            pend.key = self.plan_key(plan)
            # stat BEFORE the read (the PR7 discipline)
            pend.fingerprint = input_fingerprint(plan)
            pend.pins = _inmemory_batches(plan)
        except Exception:
            pend.cacheable = False
            return pend
        try:
            batches = self._load(pend, count_miss)
        except Exception:
            batches = None  # any load failure is a miss, never a
            #                 failed query (the recompute is exact)
        if batches is not None:
            pend.hit = True
            pend.batches = batches
        return pend

    def offer_template(self, info, count_miss: bool = True
                       ) -> PendingResult:
        """Template-tier lookup: key on (template fingerprint,
        CURRENT parameter vector) of a hoisted
        :class:`~spark_rapids_tpu.plan.template.TemplateInfo`.  The
        fingerprint-verification discipline is the exact tier's —
        input fingerprints statted fresh, weak pins on in-memory
        inputs, CRC re-verified on every hit."""
        pend = PendingResult()
        pend.tier = "template"
        if not self.enabled or self.catalog is None:
            return pend
        try:
            pend.cacheable = self.cacheable(info.plan)
            if not pend.cacheable:
                return pend
            pend.key = self.template_key(info.fingerprint,
                                         info.param_vector())
            pend.fingerprint = input_fingerprint(info.plan)
            pend.pins = _inmemory_batches(info.plan)
        except Exception:
            pend.cacheable = False
            return pend
        try:
            batches = self._load(pend, count_miss)
        except Exception:
            batches = None
        if batches is not None:
            pend.hit = True
            pend.batches = batches
        return pend

    def _count_miss_locked(self, tier: str) -> None:
        self.misses += 1
        if tier == "template":
            self.template_misses += 1

    def _miss(self, note: str, count: bool = True, tier: str = "exact"):
        if count:
            with _Locked(self._lock):
                self._count_miss_locked(tier)
            if tier == "template":
                self._note_sharing(templateCache=note)
            else:
                self._note_sharing(resultCache=note)
        return None

    def _invalidate(self, entry: "_CachedResult", reason: str,
                    count_miss: bool = True, tier: str = "exact"):
        """Invalidate-if-still-live (a concurrent lookup or eviction
        may have removed the entry already) and count the miss."""
        with _Locked(self._lock):
            if self._entries.get(entry.key) is entry:
                self._invalidate_locked(entry, reason)
            if count_miss:
                self._count_miss_locked(tier)
        if tier == "template":
            self._note_sharing(templateCache="invalidated")
        else:
            self._note_sharing(resultCache="invalidated")
        return None

    def _load(self, pend: PendingResult, count_miss: bool = True):
        from spark_rapids_tpu.memory.spill import _payload_checksum
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        from spark_rapids_tpu.robustness.incremental import \
            _batch_payload
        tier = pend.tier
        point = "templatecache.load" if tier == "template" \
            else "resultcache.load"
        with _Locked(self._lock):
            entry = self._entries.get(pend.key)
            if entry is None:
                fleet_try = (self.fleet is not None and not pend.pins)
                if not fleet_try:
                    if count_miss:
                        self._count_miss_locked(tier)
                        self._note_sharing(**{
                            "templateCache" if tier == "template"
                            else "resultCache": "miss"})
                    return None
            elif entry.fingerprint != pend.fingerprint:
                # an input file moved (appended, rewritten — even
                # same-size, the mtime catches it): the stored result
                # no longer describes the data
                self._invalidate_locked(entry,
                                        "input-fingerprint-moved")
                if count_miss:
                    self._count_miss_locked(tier)
                self._note_sharing(**{
                    "templateCache" if tier == "template"
                    else "resultCache": "invalidated"})
                return None
            elif not entry.pins_alive():
                # an in-memory input batch the fingerprint's id()s
                # describe was collected: the id may now alias a
                # DIFFERENT object's data, so the match is unprovable
                self._invalidate_locked(entry, "input-batch-collected")
                if count_miss:
                    self._count_miss_locked(tier)
                self._note_sharing(**{
                    "templateCache" if tier == "template"
                    else "resultCache": "invalidated"})
                return None
            else:
                parts = list(entry.parts)
                schema = list(entry.schema)
        if entry is None:
            # local miss, no process-local pins: a peer process may
            # have published this plan's result to the fleet store
            return self._fleet_load(pend, point, count_miss)
        # heavy verification OUTSIDE the lock: materializing and
        # checksumming multi-MB host/disk payloads must not serialize
        # co-tenants' lookups into a queue (this is the concurrency
        # path).  A concurrent eviction closing a handle mid-read
        # surfaces as OSError/ValueError and lands in the invalid arm.
        try:
            # chaos: raise/delay rules degrade the load to a MISS
            # (the query recomputes — exact, just slower); corrupt
            # rules below rot the payload for the CRC gate
            fire(point)
            batches = []
            for h, crc, nrows in parts:
                batch = h.materialize()
                payload = _batch_payload(batch)
                key = next((k for k in sorted(payload)
                            if payload[k].size > 0), None)
                if key is not None:
                    mutated = fire_mutate(point, payload[key])
                    if mutated is not payload[key]:
                        payload = dict(payload)
                        payload[key] = mutated
                got = _payload_checksum(payload, nrows)
                if got != crc:
                    return self._invalidate(
                        entry,
                        f"crc {got:#010x} != stored {crc:#010x}",
                        count_miss, tier)
                batches.append(_rebuild_batch(schema, payload, nrows))
        except (CorruptionFault, OSError, ValueError) as e:
            # undecodable / vanished / tier-CRC-dropped payload:
            # the entry is gone, the query recomputes
            return self._invalidate(entry, f"{type(e).__name__}: {e}",
                                    count_miss, tier)
        except Exception:
            # an injected raise (or any other load-path failure)
            # is a graceful miss, never a failed query
            return self._miss("miss", count_miss, tier)
        with _Locked(self._lock):
            if self._entries.get(pend.key) is entry:
                entry.hits += 1
                entry.seq = next(self._seq)  # LRU touch
            self.hits += 1
            if tier == "template":
                self.template_hits += 1
        self._emit("TemplateCacheHit" if tier == "template"
                   else "ResultCacheHit", key=pend.key[:16],
                   batches=len(batches),
                   rows=sum(b.nrows for b in batches))
        if tier == "template":
            self._note_sharing(templateCacheHit=True)
        else:
            self._note_sharing(resultCacheHit=True)
        return batches

    # --------------------------------------------------------------- fleet --
    def _fleet_load(self, pend: PendingResult, point: str,
                    count_miss: bool = True):
        """Consult the fleet tier after a local miss.  The verification
        discipline is the local tier's, re-run on the peer's bytes:
        fingerprint must match (statted fresh THIS process — a peer's
        view of the files proves nothing here), every part's CRC
        re-verified against the payload as unpickled.  Any doubt is a
        miss; the entry is a peer's to invalidate, not ours."""
        from spark_rapids_tpu.memory.spill import _payload_checksum
        tier = pend.tier
        try:
            got = self.fleet.lookup(pend.key)
        except Exception:
            got = None
        if got is None:
            return self._miss("miss", count_miss, tier)
        rec, owner = got
        try:
            if not isinstance(rec, dict) or \
                    rec.get("fingerprint") != pend.fingerprint:
                return self._miss("fleet-fingerprint-moved",
                                  count_miss, tier)
            # the chaos surface covers fleet loads too: the same
            # raise/delay/corrupt rules the local tier faces
            fire(point)
            schema = list(rec["schema"])
            batches = []
            for payload, crc, nrows in rec["parts"]:
                key = next((k for k in sorted(payload)
                            if payload[k].size > 0), None)
                if key is not None:
                    mutated = fire_mutate(point, payload[key])
                    if mutated is not payload[key]:
                        payload = dict(payload)
                        payload[key] = mutated
                if _payload_checksum(payload, nrows) != crc:
                    return self._miss("fleet-crc-mismatch",
                                      count_miss, tier)
                batches.append(_rebuild_batch(schema, payload, nrows))
        except Exception:
            return self._miss("miss", count_miss, tier)
        with _Locked(self._lock):
            self.hits += 1
            self.fleet_hits += 1
            if tier == "template":
                self.template_hits += 1
        self._emit("TemplateCacheHit" if tier == "template"
                   else "ResultCacheHit", key=pend.key[:16],
                   batches=len(batches),
                   rows=sum(b.nrows for b in batches),
                   tier="fleet", crossProcess=owner != os.getpid())
        if tier == "template":
            self._note_sharing(templateCacheHit=True)
        else:
            self._note_sharing(resultCacheHit=True)
        return batches

    def _fleet_publish(self, pend: PendingResult, schema,
                       staged) -> None:
        """Publish a freshly stored, pin-free result to the fleet
        store, carrying the session's CURRENT fence token — a zombie
        host's stale token is rejected at the store (see
        serving/fleetcache.py)."""
        try:
            rec = {"fingerprint": pend.fingerprint,
                   "schema": list(schema or []),
                   "parts": [(payload, crc, nrows)
                             for _, crc, nrows, payload in staged]}
            token = int(getattr(self.session, "fleet_epoch", 0))
            if self.fleet.publish(pend.key, rec, token):
                with _Locked(self._lock):
                    self.fleet_stores += 1
        except Exception:
            pass  # the fleet tier is an optimization, never a failure

    # --------------------------------------------------------------- store --
    def store(self, pend: PendingResult, batches) -> None:
        """Best-effort store of a freshly computed result under the
        token's pre-execution key/fingerprint.  Failures (unstattable
        inputs, a result over the whole budget, catalog pressure)
        just skip the store — the cache is an optimization."""
        if not self.enabled or self.catalog is None or \
                not pend.cacheable or pend.hit or pend.key is None:
            return
        from spark_rapids_tpu.memory.spill import _payload_checksum
        from spark_rapids_tpu.robustness.incremental import \
            _batch_payload
        parts = []
        try:
            with _Locked(self._lock):
                if pend.key in self._entries:
                    return  # a concurrent twin already stored it
            schema = None
            total = 0
            staged = []
            for b in batches:
                if schema is None:
                    schema = list(b.schema)
                payload = _batch_payload(b)
                nrows = int(b.nrows)
                crc = _payload_checksum(payload, nrows)
                copy = _rebuild_batch(schema, payload, nrows)
                staged.append((copy, crc, nrows, payload))
            from spark_rapids_tpu.serving import context as qc
            ctx = qc.current()
            owner_qid = ctx.qid if ctx is not None else None
            for copy, crc, nrows, _ in staged:
                h = self.catalog.register(
                    copy, priority=RESULT_CACHE_PRIORITY)
                self.catalog.demote(h, "HOST")
                parts.append((h, crc, nrows))
                total += h.stored_bytes
            if total > self.max_bytes:
                for h, _, _ in parts:
                    h.close()
                return
            with _Locked(self._lock):
                if pend.key in self._entries:
                    for h, _, _ in parts:
                        h.close()
                    return
                entry = _CachedResult(
                    pend.key, pend.fingerprint,
                    schema if schema is not None else [],
                    parts, next(self._seq), owner_qid,
                    pins=pend.pins)
                self._entries[pend.key] = entry
                self.stores += 1
                if pend.tier == "template":
                    self.template_stores += 1
                self._evict_over_budget_locked()
            # the store happens AFTER the final attempt's QueryEnd
            # closed, so the fact rides this event (queryId is still
            # the storing query's) — not the sharing dict, which the
            # envelope already snapshotted
            self._emit("TemplateCacheStore" if pend.tier == "template"
                       else "ResultCacheStore", key=pend.key[:16],
                       bytes=total, batches=len(parts))
            if self.fleet is not None and not pend.pins:
                # pin-free plans only: id()-keyed in-memory pins are
                # process-local, so a cross-process match is unsound
                self._fleet_publish(pend, schema, staged)
        except Exception:
            for h, _, _ in parts:
                try:
                    h.close()
                except Exception:
                    pass

    # --------------------------------------------------------- invalidation --
    def _invalidate_locked(self, entry: _CachedResult,
                           reason: str) -> None:
        self._entries.pop(entry.key, None)
        entry.close()
        self.invalidations += 1
        self._emit("ResultCacheInvalid", key=entry.key[:16],
                   reason=reason)

    def _evict_over_budget_locked(self) -> None:
        while self._entries and \
                sum(e.stored_bytes
                    for e in self._entries.values()) > self.max_bytes:
            victim = min(self._entries.values(), key=lambda e: e.seq)
            self._entries.pop(victim.key, None)
            bytes_ = victim.stored_bytes
            victim.close()
            self.evictions += 1
            self._emit("ResultCacheEvict", key=victim.key[:16],
                       bytes=bytes_, reason="max-bytes")

    def snapshot(self) -> Dict[str, int]:
        with _Locked(self._lock):
            return {
                "entries": len(self._entries),
                "bytes": sum(e.stored_bytes
                             for e in self._entries.values()),
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "templateHits": self.template_hits,
                "templateMisses": self.template_misses,
                "templateStores": self.template_stores,
                "fleetHits": self.fleet_hits,
                "fleetStores": self.fleet_stores,
            }

    def close(self) -> None:
        with _Locked(self._lock):
            for entry in list(self._entries.values()):
                entry.close()
            self._entries.clear()


class SharedStageCache(CheckpointManager):
    """The ``always_resume`` lineage store, shared across a session's
    tenants (see module docstring).  Reuses the CheckpointManager
    save/restore/CRC machinery verbatim; what changes is scope (the
    session, not one query), thread safety (concurrent queries share
    the entry map), event names, and the layout-rung ``clear()``
    contract (a per-query demotion must not wipe co-tenants' entries).
    """

    always_resume = True

    def __init__(self, session):
        from spark_rapids_tpu.config import rapids_conf as rc
        super().__init__(session)
        conf = session.conf
        self.enabled = bool(conf.get(rc.SERVING_SHARED_STAGE_ENABLED))
        self.max_bytes = int(
            conf.get(rc.SERVING_SHARED_STAGE_MAX_BYTES))
        # never HBM-resident: shared insurance must not compete with
        # any live query's batches for device memory
        self.tiers = ("HOST", "DISK")
        self.priority = SHARED_STAGE_PRIORITY
        self._mu = threading.RLock()
        # counters/tallies get their own small lock: restore() runs
        # UNLOCKED (see below), so its metric bumps must not race
        self._tally_mu = threading.Lock()
        # stage id -> owning query id (attribution for events/billing)
        self._owners: Dict[str, Optional[int]] = {}
        # per-thread (per-query) write/splice tallies, popped into the
        # QueryEnd sharing dict — store-local counters would smear
        # across concurrent tenants
        self._by_ident: Dict[int, Dict[str, int]] = {}
        # epoch tier: per standing-query store, a BY-REFERENCE
        # snapshot of its committed shareable stage ids (store id →
        # (store, epoch, frozenset(sids))).  Published ONLY from
        # IncrementalStateStore.commit, replaced wholesale each
        # commit, never advanced by a rollback — so everything
        # reachable through it is a committed epoch's work.  Payloads
        # stay in the owner store (no copy); a sid whose entry the
        # owner has since evicted simply misses (degrade = recompute).
        self._epoch_tiers: Dict[int, tuple] = {}
        # fleet tier (ISSUE 18): shareable saves (purely file-backed
        # input fingerprints — the planner's hint) publish to the
        # fleet store so peer HOSTS splice them; consulted after both
        # the local map and the epoch tier miss
        self.fleet = getattr(session, "fleet_cache", None)
        self.fleet_splices = 0
        self.fleet_publishes = 0

    # ----------------------------------------------------------- event taps --
    _EVENT_MAP = {"CheckpointWrite": "SharedStageWrite",
                  "CheckpointResume": "SharedStageSplice",
                  "CheckpointEvict": "SharedStageEvict",
                  "CheckpointInvalid": "SharedStageInvalid"}

    def _emit(self, event: str, **fields) -> None:
        mapped = self._EVENT_MAP.get(event, event)
        sid = fields.get("stageId")
        if sid is not None and sid in self._owners:
            fields["owner"] = self._owners.get(sid)
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session(mapped, session=self.session, **fields)

    def _bump(self, field: str, by: int = 1) -> None:
        # restore() bumps these WITHOUT the store lock held
        from spark_rapids_tpu.robustness.checkpoint import (
            checkpoint_metrics)
        checkpoint_metrics.bump(field, by)
        with self._tally_mu:
            self.local[field] += int(by)

    def _tally(self, field: str, by: int = 1) -> None:
        from spark_rapids_tpu.serving import context as qc
        ident = qc.effective_ident()
        with self._tally_mu:
            rec = self._by_ident.setdefault(ident, {})
            rec[field] = rec.get(field, 0) + by

    def take_query_stats(self) -> Dict[str, int]:
        """Pop the calling query's write/splice tallies (QueryEnd)."""
        from spark_rapids_tpu.serving import context as qc
        with self._tally_mu:
            return self._by_ident.pop(qc.effective_ident(), {})

    # ----------------------------------------------------------- operations --
    def save(self, sid: str, frame, stages: int = 1,
             shareable: bool = False) -> None:
        # saves hold the store lock end to end: they happen once per
        # NEW stage id (repeat saves early-exit in the base), and the
        # lock is what keeps _entries inserts + eviction iteration
        # consistent.  The HOT multi-tenant path — restore — runs
        # unlocked below.
        with _Locked(self._mu):
            known = sid in self._entries
            from spark_rapids_tpu.serving import context as qc
            ctx = qc.current()
            if not known:
                self._owners[sid] = ctx.qid if ctx is not None else None
            super().save(sid, frame, stages)
            if not known and sid in self._entries:
                self._tally("stageWrites")
                if shareable and self.fleet is not None:
                    self._fleet_publish_stage(sid)
            elif not known:
                self._owners.pop(sid, None)  # save refused/failed

    def restore(self, sid: str, mesh):
        # UNLOCKED: materializing + CRC-checking a multi-MB payload
        # under the store-wide lock would serialize every tenant's
        # splice (the defect class ResultCache._load was restructured
        # for).  Safe because the base restore only READS the entry
        # map (GIL-atomic get), every _entries MUTATION goes through
        # the locked save/drop/close paths, and a concurrent
        # eviction closing the handle mid-materialize surfaces as
        # OSError/ValueError -> drop -> recompute, the standard
        # degrade.
        frame = super().restore(sid, mesh)
        if frame is not None:
            self._tally("spliceResumes")
            return frame
        # miss in the cache's own entries: a standing query may have
        # published the sid with a committed epoch — ordinary queries
        # splice committed tick work through the same fallback the
        # co-subscribing ticks use
        frame = self.epoch_restore(sid, mesh)
        if frame is not None:
            return frame
        # last resort: a peer HOST may have published the sid to the
        # fleet store (shareable = file-backed fingerprint, so the
        # structural stage id proves the same bytes on any host)
        return self._fleet_restore(sid, mesh)

    # ------------------------------------------------------------ fleet tier --
    def _fleet_publish_stage(self, sid: str) -> None:
        """Publish a freshly saved SHAREABLE stage to the fleet store
        under ``"S:" + sid``, fence-token attached.  The payload is
        rebuilt from the just-registered (host-demoted) handle — no
        extra device sync — and carries the entry's canonical CRC so a
        peer re-verifies the exact bytes this host stamped."""
        entry = self._entries.get(sid)
        if entry is None:
            return
        try:
            batch = entry.handle.materialize()
            payload = {"__counts.data":
                       batch.columns["__counts"].host_values()
                       [:entry.nshards].astype(np.int32)}
            for i in range(len(entry.names)):
                col = batch.columns[f"c{i}"]
                payload[f"c{i}.data"] = col.host_values()
                v = col.host_validity()
                payload[f"c{i}.validity"] = v if v is not None else \
                    np.ones(col.capacity, dtype=bool)
            rec = {"names": list(entry.names),
                   "log_dtypes": list(entry.log_dtypes),
                   "enc": {k: list(v) for k, v in entry.enc.items()},
                   "nshards": int(entry.nshards),
                   "capacity": int(entry.capacity),
                   "crc": int(entry.crc),
                   "stages": int(entry.stages),
                   "payload": payload}
            token = int(getattr(self.session, "fleet_epoch", 0))
            if self.fleet.publish("S:" + sid, rec, token):
                with self._tally_mu:
                    self.fleet_publishes += 1
        except Exception:
            pass  # the fleet tier is an optimization, never a failure

    def _fleet_restore(self, sid: str, mesh):
        """Materialize ``sid`` from a peer's fleet-published payload,
        or None.  Runs UNLOCKED like restore(); the CRC gate re-runs
        on the bytes as unpickled, so a torn/rotted/foreign blob is a
        recompute, never wrong data."""
        if not self.enabled or self.fleet is None:
            return None
        try:
            got = self.fleet.lookup("S:" + sid)
        except Exception:
            return None
        if got is None:
            return None
        rec, owner = got
        try:
            from spark_rapids_tpu.memory.spill import _payload_checksum
            payload = rec["payload"]
            names = list(rec["names"])
            total = int(payload["c0.data"].shape[0]) if names else 0
            if _payload_checksum(payload, total) != int(rec["crc"]):
                return None
            from spark_rapids_tpu.parallel.dist_planner import \
                ShardedFrame
            from spark_rapids_tpu.parallel.mesh import host_put
            cols = [(host_put(mesh, payload[f"c{i}.data"]),
                     host_put(mesh, payload[f"c{i}.validity"]))
                    for i in range(len(names))]
            nrows = host_put(
                mesh, np.asarray(payload["__counts.data"], np.int32))
            frame = ShardedFrame(
                mesh, names, list(rec["log_dtypes"]), cols, nrows,
                {k: list(v) for k, v in rec["enc"].items()})
        except Exception:
            return None
        self._bump("resumes")
        self._bump("stagesSkipped", int(rec.get("stages", 1)))
        with self._tally_mu:
            self.fleet_splices += 1
        self._emit("CheckpointResume", stageId=sid,
                   stagesSaved=int(rec.get("stages", 1)), tier="fleet",
                   crossProcess=owner != os.getpid())
        self._tally("spliceResumes")
        return frame

    # ------------------------------------------------------------ epoch tier --
    def publish_epoch(self, store, sids: frozenset) -> None:
        """Replace ``store``'s snapshot with its newly COMMITTED
        shareable sids (called from IncrementalStateStore.commit
        only — the commit-time-only registration IS the tick-safety
        invariant: provisional work is unreachable here, and a
        rollback, publishing nothing, leaves the last committed
        snapshot standing)."""
        with _Locked(self._mu):
            self._epoch_tiers[store.store_id] = (
                store, store.epoch, frozenset(sids))

    def retract_epoch(self, store) -> None:
        """Drop ``store``'s snapshot (runner teardown)."""
        with _Locked(self._mu):
            self._epoch_tiers.pop(store.store_id, None)

    def epoch_restore(self, sid: str, mesh, exclude=None):
        """Materialize ``sid`` from some standing query's committed
        epoch, or None.  Runs UNLOCKED like restore() (one short
        locked snapshot of the tier map, then payload work outside the
        lock); the hit bills as a SPLICE of this cache — event
        (SharedStageSplice) and per-query tally both — because that is
        what it is: cross-query reuse of committed work.  ``exclude``
        skips the asking store's own snapshot (its local restore
        already missed; its own entries are not a co-subscriber's)."""
        if not self.enabled:
            return None
        with _Locked(self._mu):
            tiers = list(self._epoch_tiers.values())
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        for store, _epoch, sids in tiers:
            if store is exclude or sid not in sids:
                continue
            entry = store._entries.get(sid)
            if entry is None:
                continue  # owner evicted it since publication
            try:
                batch = entry.handle.materialize()
            except (CorruptionFault, OSError, ValueError):
                continue  # owner's problem; it degrades on next use
            frame = self._restore_body(sid, entry, batch, mesh)
            if frame is not None:
                self._tally("spliceResumes")
                return frame
        return None

    def drop(self, sid: str, reason: str, evict: bool = False) -> None:
        with _Locked(self._mu):
            super().drop(sid, reason, evict=evict)
            self._owners.pop(sid, None)

    def clear(self, reason: str) -> None:
        """A recovery-ladder layout rung demotes ONE query off the
        mesh; the shared store's committed entries are keyed to
        (subtree, mesh layout, input fingerprint), all of which
        survive the rung and stay valid for every other tenant — so
        clear() is deliberately a no-op (the per-query manager wipes
        its log here; the incremental store drops provisional only)."""

    def finish(self) -> None:
        """Never called per-query (the store outlives queries); a
        stray call must not wipe the shared state."""

    def close(self) -> None:
        """Session teardown: release every payload."""
        with _Locked(self._mu):
            for sid in list(self._entries):
                entry = self._entries.pop(sid)
                try:
                    entry.handle.close()
                except Exception:
                    pass
            self._owners.clear()
            self._by_ident.clear()
            self._epoch_tiers.clear()  # by-reference: owners hold
            # the payloads and release them in their own close()

    def snapshot(self) -> Dict[str, int]:
        with _Locked(self._mu):
            out = super().snapshot()
            out["fleetSplices"] = self.fleet_splices
            out["fleetPublishes"] = self.fleet_publishes
            return out
