"""Fair interleaving of admitted queries' batch loops.

Admission (serving/admission.py) decides WHO may touch the device;
nothing until now decided WHEN.  Once admitted, each query's batch loop
dispatched as fast as its driving (or pipeline-worker) thread could
run, so a long scan that got its slot first effectively occupied the
mesh FIFO query-at-a-time: a 10ms dashboard query admitted alongside
an SF100 scan still waited out the scan's entire dispatch stream.

:class:`FairInterleaver` is a cooperative, weighted round-robin
timeslice gate at the batch boundary:

- every admitted query registers an :class:`InterleaveTicket`
  (``QueryContext.admit``) and calls :func:`yield_slice` before each
  batch pull (``DataFrame._drive`` wraps the operator iterator) and at
  every distributed stage boundary (``DistPlanner.run``);
- queries advance in strict round-robin ticket order, each consuming
  its **quantum** of batch slices per turn — so every runnable query
  advances within one round, making starvation impossible by
  construction (the admission queue's FIFO guarantee, carried through
  execution);
- the quantum is weighted by the serving budgets the QueryContext
  already carries: a query declaring a byte weight lighter than the
  pool default gets proportionally more slices per round (bounded 8x),
  and a deadline-budgeted query gets double — light interactive
  queries stream through between a heavy query's batches instead of
  behind all of them;
- recovery-ladder re-drives keep their slot: the ticket lives on the
  QueryContext, which spans every attempt of one query action;
- the gate is **cooperative and content-blind**: it reorders when
  batches dispatch, never what they compute, so results are
  bit-identical with the knob off.  Waits are watchdog-cooperative
  (a deadline-budgeted query blocked at the gate still times out as a
  retryable fault) and traced as ``scheduler.timeslice`` spans.

A query that stops pulling batches (tail collect, host-side work)
holds its turn only until its context exits — ``unregister`` passes
the turn on; and a gate wait never blocks a query that is the only
registered one (single-tenant fast path: one atomic read).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import List, Optional


class InterleaveTicket:
    """One registered query's place in the round."""

    _seqs = itertools.count(1)

    __slots__ = ("seq", "quantum", "used", "granted", "wait_ns",
                 "rounds")

    def __init__(self, quantum: int):
        self.seq = next(InterleaveTicket._seqs)
        self.quantum = max(int(quantum), 1)
        self.used = 0        # slices consumed this turn
        self.granted = 0     # total slices granted (observability)
        self.wait_ns = 0     # total time blocked at the gate
        self.rounds = 0      # turns this ticket has taken

    def info(self) -> dict:
        return {"waitMs": round(self.wait_ns / 1e6, 3),
                "timeslices": self.granted,
                "quantum": self.quantum,
                "rounds": self.rounds}


class FairInterleaver:
    """Weighted round-robin timeslice scheduler for one session."""

    # bound on how far a light query's quantum may scale past the base
    MAX_WEIGHT_SCALE = 8
    # turn LEASE: a holder that has not consumed a slice within this
    # window is off-gate (cold compile, a long stage body, the
    # post-final-gate tail before its context exits) — waiters pass
    # the turn over it rather than stalling the whole round behind
    # work the gate cannot see.  The passed-over query rejoins on its
    # next gate like any other ticket; the scheduler is cooperative,
    # so this lease is what keeps one tenant's multi-second compile
    # from serializing every co-tenant.
    TURN_LEASE_S = 0.05

    def __init__(self, quantum_batches: int = 1):
        self.quantum_batches = max(int(quantum_batches), 1)
        self._cond = threading.Condition()
        self._order: List[InterleaveTicket] = []
        self._cur = 0
        self._turn_t0 = time.monotonic()  # when the turn last moved
        # cumulative observability (bench --concurrency / profiling)
        self.total_registered = 0
        self.total_slices = 0
        self.total_wait_ns = 0
        self.peak_tickets = 0
        self.turn_leases_expired = 0

    # ------------------------------------------------------------ weights --
    def quantum_for(self, ctx) -> int:
        """Slices per turn from the query's serving budgets: byte
        weights lighter than the pool default scale the quantum up
        (bounded), a deadline budget doubles it — the queries a human
        is waiting on advance more batches per round.  Every query
        gets at least one slice per round regardless."""
        q = self.quantum_batches
        session = getattr(ctx, "session", None)
        ctrl = getattr(session, "admission", None) if session else None
        weight = int(getattr(ctx, "memory_budget", 0) or 0)
        if ctrl is not None and weight:
            default = max(int(ctrl.default_weight), 1)
            if weight < default:
                q *= min(max(default // weight, 1),
                         self.MAX_WEIGHT_SCALE)
        if getattr(ctx, "deadline_budget_ms", 0):
            q *= 2
        return max(min(q, self.quantum_batches *
                       self.MAX_WEIGHT_SCALE * 2), 1)

    # ------------------------------------------------------------- rounds --
    def register(self, ctx) -> InterleaveTicket:
        ticket = InterleaveTicket(self.quantum_for(ctx))
        with self._cond:
            self._order.append(ticket)
            self.total_registered += 1
            self.peak_tickets = max(self.peak_tickets,
                                    len(self._order))
            self._cond.notify_all()
        return ticket

    def unregister(self, ticket: InterleaveTicket) -> None:
        """Drop a finished query from the round; if it held the turn,
        the turn passes to the next ticket immediately."""
        with self._cond:
            try:
                idx = self._order.index(ticket)
            except ValueError:
                return
            held_turn = idx == self._cur
            del self._order[idx]
            if idx < self._cur:
                self._cur -= 1  # same current ticket, shifted left
            if self._order and self._cur >= len(self._order):
                self._cur = 0  # the removed tail held the turn: wrap
            if held_turn and self._order:
                self._order[self._cur].used = 0
                self._order[self._cur].rounds += 1
                self._turn_t0 = time.monotonic()
            self._cond.notify_all()

    def _advance_locked(self) -> None:
        if not self._order:
            return
        self._cur = (self._cur + 1) % len(self._order)
        nxt = self._order[self._cur]
        nxt.used = 0
        nxt.rounds += 1
        self._turn_t0 = time.monotonic()
        self._cond.notify_all()

    def yield_slice(self, ticket: InterleaveTicket) -> None:
        """The batch-boundary gate: consume one slice when it is this
        ticket's turn (advancing the round when its quantum is spent),
        else block until the turn arrives.  Waits poll with watchdog
        cancellation checkpoints so a deadline-budgeted query blocked
        here still times out as a retryable fault instead of wedging;
        the wait is traced as a ``scheduler.timeslice`` span."""
        # single-tenant fast path: no lock, no wait (len is one atomic
        # read; a concurrent register just means the NEXT boundary
        # starts taking turns)
        if len(self._order) <= 1:
            ticket.used += 1
            ticket.granted += 1
            self.total_slices += 1
            return
        from spark_rapids_tpu.robustness import watchdog
        from spark_rapids_tpu.utils import tracing
        t0 = time.perf_counter_ns()
        waited = False
        with self._cond:
            while True:
                if ticket not in self._order:
                    break  # unregistered underneath us: never block
                cur = self._order[self._cur]
                if cur is ticket:
                    if ticket.used < ticket.quantum:
                        ticket.used += 1
                        ticket.granted += 1
                        self.total_slices += 1
                        self._turn_t0 = time.monotonic()
                        break
                    # quantum spent: pass the turn and (unless the
                    # round came straight back — everyone else left)
                    # wait for it to return
                    self._advance_locked()
                    continue
                if time.monotonic() - self._turn_t0 > \
                        self.TURN_LEASE_S:
                    # the holder is off-gate (compiling, mid-stage,
                    # or in its tail): pass the turn over it so the
                    # round keeps moving — it rejoins at its next gate
                    self.turn_leases_expired += 1
                    self._advance_locked()
                    continue
                waited = True
                # bounded waits so cancellation (watchdog trip,
                # deadline budget) is delivered instead of sleeping
                # on a condition no one may ever signal
                watchdog.checkpoint()
                self._cond.wait(0.02)
        if waited:
            wait_ns = time.perf_counter_ns() - t0
            ticket.wait_ns += wait_ns
            self.total_wait_ns += wait_ns
            if tracing._armed:
                tracing.emit_span("scheduler.timeslice", t0, wait_ns,
                                  is_async=False)

    def interleaved(self, iterator, ticket: InterleaveTicket):
        """Wrap an operator batch iterator so every pull passes
        through the timeslice gate (the ``DataFrame._drive`` hook —
        runs on the pipeline worker thread when pipelined, which is
        exactly the thread doing the dispatching)."""
        for batch in iterator:
            yield batch
            self.yield_slice(ticket)

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "tickets": len(self._order),
                "totalRegistered": self.total_registered,
                "totalSlices": self.total_slices,
                "totalWaitMs": round(self.total_wait_ns / 1e6, 3),
                "peakTickets": self.peak_tickets,
                "turnLeasesExpired": self.turn_leases_expired,
            }


def yield_current(session) -> None:
    """Gate the calling thread's query at a stage boundary, resolving
    the ticket through the thread's QueryContext — the hook the
    distributed planner calls between exchange stages (a distributed
    query's 'batches' are its stages)."""
    sched = getattr(session, "interleaver", None)
    if sched is None:
        return
    from spark_rapids_tpu.serving import context as qc
    ctx = qc.current()
    ticket: Optional[InterleaveTicket] = \
        getattr(ctx, "interleave_ticket", None) if ctx else None
    if ticket is not None:
        sched.yield_slice(ticket)
