"""QueryContext: every piece of per-query robustness state, scoped.

Before the serving layer, five registries attributed work to "the
query" through process- or session-global state that only stayed
correct because exactly one query ran at a time: the session's
in-flight query id (event attribution), the session's checkpoint
lineage manager, fault-injection rule scoping, watchdog cancellation
tokens, and the host-sync/OOM-retry thread-local mirrors.  Under
concurrent queries those globals splice state across queries — query
A's recovery events stamp B's id, A's cancellation lands on B, A's
eviction storm drains B's checkpoints.

A :class:`QueryContext` is the scope object that makes "per query"
real.  One is entered by ``DataFrame._execute_batches`` around the
whole recovery-driven execution (every attempt of one query action):

- it registers itself under the driving thread's ident in a
  process-wide registry, with the same worker-adoption discipline the
  other registries use (``exec/pipeline.worker_attribution`` adopts
  workers into it), so ``current()`` resolves the owning query from
  any thread doing its work;
- the session's ``_current_qid`` / ``checkpoints`` attributes become
  thread-keyed views through this registry — existing call sites keep
  reading/writing the same names and transparently get per-query
  state;
- it carries the query's budgets (memory bytes, host syncs, deadline)
  and the admission ticket, and accumulates the BudgetExhausted /
  admission facts the QueryEnd event reports;
- **exit is the containment boundary**: the context releases its
  admission ticket, clears its thread's watchdog token, drops its
  per-owner spill budget, and purges every adoption-registry entry
  that still maps a (possibly dead, possibly about-to-be-recycled)
  worker ident to this query — the thread-ident-reuse fix: the OS
  reuses idents, and a stale adoption would attribute a NEW query's
  syncs (or deliver its cancellation) to this dead one.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

# owner (driving) thread ident -> its active QueryContext
_contexts: Dict[int, "QueryContext"] = {}
# worker thread ident -> owner ident (same GIL-atomic dict discipline
# as inject._adopted / watchdog._adopted)
_adopted: Dict[int, int] = {}


def adopt_thread(owner_ident: int) -> None:
    """The calling thread acts for ``owner_ident``'s query (wired
    through exec/pipeline.worker_attribution alongside the other
    adoption registries)."""
    _adopted[threading.get_ident()] = owner_ident


def release_thread() -> None:
    _adopted.pop(threading.get_ident(), None)


def disown(ident: int) -> None:
    _adopted.pop(ident, None)


def effective_ident() -> int:
    ident = threading.get_ident()
    return _adopted.get(ident, ident)


def current() -> Optional["QueryContext"]:
    """The QueryContext the calling thread is working for, if any."""
    return _contexts.get(effective_ident())


def qid_for_ident(ident: int, session=None) -> Optional[int]:
    """Query id owned by a specific thread ident — the watchdog
    monitor uses this to stamp WatchdogTrip events with the OWNING
    query's id instead of reading a session-global field from the
    monitor thread.  Falls back to the session's thread-keyed qid map
    for paths that run outside a QueryContext."""
    ctx = _contexts.get(ident)
    if ctx is not None and ctx.qid is not None:
        return ctx.qid
    if session is not None:
        return getattr(session, "_qid_by_ident", {}).get(ident)
    return None


_deadline_tls = threading.local()


class deadline_override:
    """Scope a per-query deadline budget (ms) onto every QueryContext
    the calling thread opens inside the ``with`` block — how a fleet
    round gives each subscriber its own deadline-weighted quantum
    under one shared session conf.  0/None means no override."""

    def __init__(self, ms):
        self.ms = None if not ms else int(ms)

    def __enter__(self) -> "deadline_override":
        self._prev = getattr(_deadline_tls, "ms", None)
        if self.ms is not None:
            _deadline_tls.ms = self.ms
        return self

    def __exit__(self, *exc) -> bool:
        _deadline_tls.ms = self._prev
        return False


class QueryContext:
    """One query action's scope: identity, budgets, admission ticket.

    Context manager; re-entrant entry on the same thread is rejected
    (a nested query action would splice two queries' state — the
    nested call must run in its own thread, as concurrent clients do).
    """

    def __init__(self, session):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session = session
        conf = session.conf
        self.owner_ident: Optional[int] = None
        self.qid: Optional[int] = None
        # qids this context has carried (a query action draws a fresh
        # qid per attempt envelope; tests read the full set)
        self.qids: list = []
        self.memory_budget = conf.get(rc.SERVING_QUERY_MEMORY_BUDGET)
        self.sync_budget = conf.get(rc.SERVING_SYNC_BUDGET)
        self.deadline_budget_ms = conf.get(rc.SERVING_DEADLINE_BUDGET_MS)
        # thread-local per-query override (fleet subscribers carry
        # their OWN deadlines while sharing one session conf): the
        # fair interleaver widens deadline-carrying queries' quanta
        ov = getattr(_deadline_tls, "ms", None)
        if ov is not None:
            self.deadline_budget_ms = int(ov)
        self.syncs_used = 0
        self.ticket = None            # AdmissionTicket once admitted
        self.admission_wait_ms = 0.0
        self.admission_weight = 0
        self.checkpoints = None       # per-query CheckpointManager
        self.budget_events: list = []  # BudgetExhausted facts emitted
        # cross-query reuse facts accumulated for the QueryEnd
        # ``sharing`` dict (serving/reuse.py result-cache offer/store,
        # shared-stage tallies) — empty when every reuse knob is off,
        # so the knobs-off event stream is bit-identical to HEAD
        self.sharing: dict = {}
        # fair-interleaver slot (serving/scheduler.py), registered at
        # admit() and held across every attempt of this query action —
        # recovery-ladder re-drives keep their slot
        self.interleave_ticket = None
        self._budget_spilled = False   # memory ladder: spill fired once
        # unresolved async-exchange payload bytes charged to this query
        # (parallel/exchange_async.ExchangeWindow): in-flight exchange
        # buffers are real HBM the memory budget must see, tracked here
        # so QueryEnd can attribute the high-water mark per query
        self.exchange_inflight = 0
        self.exchange_inflight_peak = 0
        self._exchange_budget_noted = False
        self._lock = threading.Lock()

    # --------------------------------------------------------------- scope --
    def __enter__(self) -> "QueryContext":
        ident = threading.get_ident()
        if _contexts.get(ident) is not None:
            raise RuntimeError(
                "a QueryContext is already active on this thread; "
                "concurrent queries must run on distinct threads")
        self.owner_ident = ident
        _contexts[ident] = self
        if self.memory_budget:
            cat = getattr(self.session, "memory_catalog", None)
            if cat is not None:
                cat.set_owner_budget(ident, self.memory_budget)
        return self

    def __exit__(self, *exc) -> bool:
        ident = self.owner_ident
        try:
            if self.interleave_ticket is not None:
                sched = getattr(self.session, "interleaver", None)
                if sched is not None:
                    sched.unregister(self.interleave_ticket)
                self.interleave_ticket = None
            self.release_admission()
        finally:
            cat = getattr(self.session, "memory_catalog", None)
            if cat is not None and self.memory_budget:
                cat.clear_owner_budget(ident)
            # containment boundary: purge every adoption entry still
            # pointing at this query's owner ident.  A finished (or
            # wedged-and-abandoned) worker's stale adoption must not
            # survive into the ident's next life — the OS reuses
            # thread idents, and a recycled ident would attribute a
            # NEW query's syncs to this dead one, or deliver this
            # query's parked cancellation into an unrelated query.
            from spark_rapids_tpu.memory.retry import retry_metrics
            from spark_rapids_tpu.robustness import inject, watchdog
            from spark_rapids_tpu.utils import hostsync
            purge_owner(ident)
            inject.purge_owner(ident)
            watchdog.purge_owner(ident)
            hostsync.host_sync_metrics.purge_owner(ident)
            retry_metrics.purge_owner(ident)
            watchdog.clear_thread()
            # the finished query's qid must not survive on the thread:
            # the next query's PRE-attempt events (an AdmissionReject
            # before it ever draws a qid) would otherwise be stamped
            # with this dead query's id
            getattr(self.session, "_qid_by_ident", {}).pop(ident, None)
            if _contexts.get(ident) is self:
                del _contexts[ident]
        return False

    # ----------------------------------------------------------- admission --
    def admit(self) -> None:
        """Acquire the session's admission semaphore (no-op when the
        controller is disabled).  Blocks in FIFO order; a timeout or a
        full queue raises the typed AdmissionFault.  Admitted queries
        also join the fair interleaver's round (when enabled) — the
        ticket spans every attempt, so ladder re-drives keep their
        slot."""
        ctrl = getattr(self.session, "admission", None)
        if ctrl is not None and self.ticket is None:
            from spark_rapids_tpu.utils import tracing
            t0 = time.perf_counter()
            with tracing.span("admission.wait"):
                self.ticket = ctrl.acquire(session=self.session)
            self.admission_wait_ms = (time.perf_counter() - t0) * 1e3
            self.admission_weight = self.ticket.weight_bytes
        # the interleave ticket joins the round ONLY once admitted: a
        # QUEUED query's ticket would hold the round-robin turn while
        # never reaching a gate — admitted co-tenants would block at
        # their gates waiting on it, and with all slots held by
        # blocked tenants the queued query is never admitted either
        # (session-wide deadlock)
        sched = getattr(self.session, "interleaver", None)
        if sched is not None and self.interleave_ticket is None:
            self.interleave_ticket = sched.register(self)

    def release_admission(self) -> None:
        ctrl = getattr(self.session, "admission", None)
        if ctrl is not None and self.ticket is not None:
            ctrl.release(self.ticket)
            self.ticket = None

    def admission_info(self) -> dict:
        """QueryEnd payload: what admission cost this query."""
        if not self.admission_weight and not self.admission_wait_ms \
                and not self.exchange_inflight_peak:
            return {}
        info = {"waitMs": round(self.admission_wait_ms, 3),
                "weightBytes": self.admission_weight}
        if self.exchange_inflight_peak:
            info["exchangeInflightPeak"] = self.exchange_inflight_peak
        return info

    # ------------------------------------------------------------- budgets --
    def set_qid(self, qid: Optional[int]) -> None:
        self.qid = qid
        if qid is not None:
            self.qids.append(qid)

    def charge_syncs(self, n: int) -> None:
        """Host-sync budget ladder: count, and past the limit reject
        THIS query with a typed fault (emitting BudgetExhausted first
        so the trail explains the rejection)."""
        if not self.sync_budget:
            return
        with self._lock:
            self.syncs_used += n
            used, limit = self.syncs_used, self.sync_budget
            over = used > limit
        if over:
            self._emit_budget("syncs", used, limit, action="reject")
            from spark_rapids_tpu.robustness.faults import (
                BudgetExhaustedFault)
            raise BudgetExhaustedFault("syncs", used, limit)

    def charge_exchange_inflight(self, delta: int) -> None:
        """Account unresolved exchange payload bytes against this
        query.  Exceeding the memory budget is NOT a rejection — the
        in-flight window resolves oldest-first and the staging tier
        routes oversized payloads through host RAM — but the overrun
        is recorded once as a budget fact so the QueryEnd trail
        explains why staging/eviction engaged."""
        with self._lock:
            self.exchange_inflight = max(
                0, self.exchange_inflight + int(delta))
            self.exchange_inflight_peak = max(
                self.exchange_inflight_peak, self.exchange_inflight)
            over = (self.memory_budget
                    and self.exchange_inflight > self.memory_budget
                    and not self._exchange_budget_noted)
            if over:
                self._exchange_budget_noted = True
        if over:
            self._emit_budget("exchangeInflight", self.exchange_inflight,
                              self.memory_budget, action="stage")

    def note_memory_pressure(self, used: int, spilled: bool) -> None:
        """Memory budget ladder, called by the spill catalog: the
        first overrun self-spills (degrade) and records it; an overrun
        the self-spill could not cure rejects the query."""
        limit = self.memory_budget
        if spilled:
            first = not self._budget_spilled
            self._budget_spilled = True
            if first:
                self._emit_budget("memory", used, limit, action="spill")
            return
        self._emit_budget("memory", used, limit, action="reject")
        from spark_rapids_tpu.robustness.faults import (
            BudgetExhaustedFault)
        raise BudgetExhaustedFault("memory", used, limit)

    def _emit_budget(self, budget: str, used, limit, action: str) -> None:
        fact = {"budget": budget, "used": used, "limit": limit,
                "action": action}
        self.budget_events.append(fact)
        from spark_rapids_tpu.utils.events import emit_on_session
        extra = {"queryId": self.qid} if self.qid is not None else {}
        emit_on_session("BudgetExhausted", session=self.session,
                        **extra, **fact)


def purge_owner(owner_ident: int) -> None:
    """Drop every worker adoption in THIS registry that maps to
    ``owner_ident`` (the per-registry counterparts live in
    inject/watchdog/hostsync/retry and are called alongside)."""
    from spark_rapids_tpu.robustness.inject import purge_adoptions
    purge_adoptions(_adopted, owner_ident)
