from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions

__all__ = ["TpuSession", "functions"]
