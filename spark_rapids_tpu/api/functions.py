"""PySpark-flavored column DSL.

The user API the reference accelerates is Spark's DataFrame/Column DSL; this
module provides the same surface (col/lit/when/agg functions with the
familiar names) building this framework's expression trees.
"""

from __future__ import annotations

from typing import Optional, Union

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType, dtype_from_name
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops import arithmetic as arith
from spark_rapids_tpu.ops import predicates as preds
from spark_rapids_tpu.ops.cast import Cast
from spark_rapids_tpu.ops.expressions import (
    Alias, Expression, Literal, UnresolvedColumn)
from spark_rapids_tpu.plan.logical import AggregateExpression

ColumnLike = Union["Col", str, int, float, bool]


def _expr(c: ColumnLike) -> Expression:
    if isinstance(c, Col):
        return c.expr
    if isinstance(c, Expression):
        return c
    if isinstance(c, str):
        return UnresolvedColumn(c)
    return Literal(c)


def _lit_expr(c) -> Expression:
    """Like _expr but bare strings become string literals, not columns."""
    if isinstance(c, Col):
        return c.expr
    if isinstance(c, Expression):
        return c
    return Literal(c)


class Col:
    """Wrapper adding pythonic operators over Expression trees."""

    def __init__(self, expr: Expression):
        self.expr = expr

    # arithmetic
    def __add__(self, o):
        return Col(arith.Add(self.expr, _lit_expr(o)))

    def __radd__(self, o):
        return Col(arith.Add(_lit_expr(o), self.expr))

    def __sub__(self, o):
        return Col(arith.Subtract(self.expr, _lit_expr(o)))

    def __rsub__(self, o):
        return Col(arith.Subtract(_lit_expr(o), self.expr))

    def __mul__(self, o):
        return Col(arith.Multiply(self.expr, _lit_expr(o)))

    def __rmul__(self, o):
        return Col(arith.Multiply(_lit_expr(o), self.expr))

    def __truediv__(self, o):
        return Col(arith.Divide(self.expr, _lit_expr(o)))

    def __rtruediv__(self, o):
        return Col(arith.Divide(_lit_expr(o), self.expr))

    def __mod__(self, o):
        return Col(arith.Remainder(self.expr, _lit_expr(o)))

    def __neg__(self):
        return Col(arith.UnaryMinus(self.expr))

    # comparison
    def __eq__(self, o):  # type: ignore[override]
        return Col(preds.EqualTo(self.expr, _lit_expr(o)))

    def __ne__(self, o):  # type: ignore[override]
        return Col(preds.Not(preds.EqualTo(self.expr, _lit_expr(o))))

    def __lt__(self, o):
        return Col(preds.LessThan(self.expr, _lit_expr(o)))

    def __le__(self, o):
        return Col(preds.LessThanOrEqual(self.expr, _lit_expr(o)))

    def __gt__(self, o):
        return Col(preds.GreaterThan(self.expr, _lit_expr(o)))

    def __ge__(self, o):
        return Col(preds.GreaterThanOrEqual(self.expr, _lit_expr(o)))

    # logic
    def __and__(self, o):
        return Col(preds.And(self.expr, _lit_expr(o)))

    def __or__(self, o):
        return Col(preds.Or(self.expr, _lit_expr(o)))

    def __invert__(self):
        return Col(preds.Not(self.expr))

    # misc
    def alias(self, name: str) -> "Col":
        return Col(Alias(self.expr, name))

    def cast(self, dtype: Union[str, DataType],
             ansi: bool = False) -> "Col":
        """``ansi=True`` = Spark's AnsiCast: conversion failures raise
        instead of producing null/wrapping."""
        if isinstance(dtype, str):
            dtype = dtype_from_name(dtype)
        return Col(Cast(self.expr, dtype, ansi=ansi))

    def isNull(self) -> "Col":
        return Col(preds.IsNull(self.expr))

    def isNotNull(self) -> "Col":
        return Col(preds.IsNotNull(self.expr))

    def isin(self, *values) -> "Col":
        if len(values) == 1 and isinstance(values[0], (list, tuple, set)):
            values = tuple(values[0])
        # large non-string literal sets use the sorted-table membership
        # form (GpuInSet analog) instead of K chained equalities
        if len(values) > 16 and not any(isinstance(v, str)
                                        for v in values):
            return Col(preds.InSet(self.expr, list(values)))
        return Col(preds.In(self.expr, [Literal(v) for v in values]))

    def between(self, lo, hi) -> "Col":
        return Col(preds.And(
            preds.GreaterThanOrEqual(self.expr, _lit_expr(lo)),
            preds.LessThanOrEqual(self.expr, _lit_expr(hi))))

    # string predicates (literal patterns)
    def startswith(self, prefix: str) -> "Col":
        from spark_rapids_tpu.ops import stringops as S
        return Col(S.StartsWith(self.expr, prefix))

    def endswith(self, suffix: str) -> "Col":
        from spark_rapids_tpu.ops import stringops as S
        return Col(S.EndsWith(self.expr, suffix))

    def contains(self, needle: str) -> "Col":
        from spark_rapids_tpu.ops import stringops as S
        return Col(S.Contains(self.expr, needle))

    def rlike(self, pattern: str) -> "Col":
        from spark_rapids_tpu.ops.regexops import RLike
        return Col(RLike(self.expr, pattern))

    def getItem(self, key) -> "Col":
        if isinstance(key, str):
            return self.getField(key)
        from spark_rapids_tpu.ops.json_ops import StringSplit
        if isinstance(self.expr, StringSplit) and \
                self.expr.limit == -1:
            # split(c, d)[n] fuses to the device split_part kernel
            # (array<string> itself stays a host-only type)
            from spark_rapids_tpu.ops.regexops import SplitPart
            return Col(SplitPart(self.expr.children[0],
                                 self.expr.pattern, int(key)))
        from spark_rapids_tpu.ops.collections_ops import GetArrayItem
        from spark_rapids_tpu.ops.expressions import Literal
        return Col(GetArrayItem(self.expr, Literal(int(key))))

    def getField(self, field: str) -> "Col":
        from spark_rapids_tpu.ops.nested_ops import GetStructField
        return Col(GetStructField(self.expr, field))

    __getitem__ = getItem

    def like(self, pattern: str) -> "Col":
        from spark_rapids_tpu.ops import stringops as S
        return Col(S.Like(self.expr, pattern))

    def substr(self, pos: int, length: int = 2**31 - 1) -> "Col":
        from spark_rapids_tpu.ops import stringops as S
        return Col(S.Substring(self.expr, pos, length))

    def over(self, window: "Window") -> "Col":
        """agg_fn(...).over(window) — pyspark surface for window aggs."""
        from spark_rapids_tpu.exec.window import WindowExpression
        from spark_rapids_tpu.plan.logical import AggregateExpression
        e = self.expr
        if not isinstance(e, AggregateExpression):
            raise TypeError(".over() applies to aggregate functions")
        kind = {"sum": "sum", "count": "count", "min": "min", "max": "max",
                "avg": "avg"}.get(e.func.name)
        if kind is None:
            raise TypeError(f"{e.func.name} is not a window aggregate")
        return Col(WindowExpression(kind, window._spec(), child=e.func.child))

    def asc(self):
        return SortKey(self.expr, descending=False, nulls_first=True)

    def desc(self):
        return SortKey(self.expr, descending=True, nulls_first=False)

    def asc_nulls_first(self):
        return SortKey(self.expr, descending=False, nulls_first=True)

    def asc_nulls_last(self):
        return SortKey(self.expr, descending=False, nulls_first=False)

    def desc_nulls_first(self):
        return SortKey(self.expr, descending=True, nulls_first=True)

    def desc_nulls_last(self):
        return SortKey(self.expr, descending=True, nulls_first=False)

    def __repr__(self):
        return f"Col({self.expr})"


class SortKey:
    def __init__(self, expr: Expression, descending: bool,
                 nulls_first: bool):
        self.expr = expr
        self.descending = descending
        self.nulls_first = nulls_first

    def nullsFirst(self):
        return SortKey(self.expr, self.descending, True)

    def nullsLast(self):
        return SortKey(self.expr, self.descending, False)


def col(name: str) -> Col:
    return Col(UnresolvedColumn(name))


def lit(value, dtype: Optional[DataType] = None) -> Col:
    return Col(Literal(value, dtype))


def when(condition: Col, value) -> "CaseBuilder":
    return CaseBuilder([(condition.expr, _lit_expr(value))])


class CaseBuilder(Col):
    def __init__(self, branches):
        self.branches = branches
        super().__init__(preds.CaseWhen(branches))

    def when(self, condition: Col, value) -> "CaseBuilder":
        return CaseBuilder(self.branches + [(condition.expr,
                                             _lit_expr(value))])

    def otherwise(self, value) -> Col:
        return Col(preds.CaseWhen(self.branches, _lit_expr(value)))


def coalesce(*cols) -> Col:
    return Col(preds.Coalesce(*[_expr(c) for c in cols]))


def isnan(c) -> Col:
    return Col(preds.IsNaN(_expr(c)))


def greatest(*cols) -> Col:
    return Col(preds.Greatest(*[_expr(c) for c in cols]))


def least(*cols) -> Col:
    return Col(preds.Least(*[_expr(c) for c in cols]))


def abs(c) -> Col:  # noqa: A001 - mirrors pyspark.sql.functions.abs
    return Col(arith.Abs(_expr(c)))


def sqrt(c) -> Col:
    return Col(arith.Sqrt(_expr(c)))


def _unary_fn(cls):
    def fn(c) -> Col:
        return Col(cls(_expr(c)))
    fn.__name__ = cls.__name__.lower()
    return fn


# double-typed math unaries (reference CudfUnaryMathExpression family);
# the expression classes predate these wrappers — this exposes them on
# the pyspark-like surface
exp = _unary_fn(arith.Exp)
expm1 = _unary_fn(arith.Expm1)
log = _unary_fn(arith.Log)
log2 = _unary_fn(arith.Log2)
log10 = _unary_fn(arith.Log10)
log1p = _unary_fn(arith.Log1p)
sin = _unary_fn(arith.Sin)
cos = _unary_fn(arith.Cos)
tan = _unary_fn(arith.Tan)
cot = _unary_fn(arith.Cot)
asin = _unary_fn(arith.Asin)
acos = _unary_fn(arith.Acos)
atan = _unary_fn(arith.Atan)
sinh = _unary_fn(arith.Sinh)
cosh = _unary_fn(arith.Cosh)
tanh = _unary_fn(arith.Tanh)
asinh = _unary_fn(arith.Asinh)
acosh = _unary_fn(arith.Acosh)
atanh = _unary_fn(arith.Atanh)
degrees = _unary_fn(arith.ToDegrees)
radians = _unary_fn(arith.ToRadians)
rint = _unary_fn(arith.Rint)
signum = _unary_fn(arith.Signum)
cbrt = _unary_fn(arith.Cbrt)
floor = _unary_fn(arith.Floor)
ceil = _unary_fn(arith.Ceil)
ceiling = ceil
bitwise_not = _unary_fn(arith.BitwiseNot)
bitwiseNOT = bitwise_not
ln = log


def _col_or_lit(v) -> Expression:
    """pyspark coercion: str/Col = column reference, else literal."""
    if isinstance(v, (str, Col)):
        return _expr(v)
    return _lit_expr(v)


def atan2(y, x) -> Col:
    return Col(arith.Atan2(_col_or_lit(y), _col_or_lit(x)))


def hypot(a, b) -> Col:
    return Col(arith.Hypot(_col_or_lit(a), _col_or_lit(b)))


def bround(c, scale: int = 0) -> Col:
    return Col(arith.BRound(_expr(c), scale))


def pmod(dividend, divisor) -> Col:
    return Col(arith.Pmod(_col_or_lit(dividend), _col_or_lit(divisor)))


def shiftleft(c, n: int) -> Col:
    return Col(arith.ShiftLeft(_expr(c), _lit_expr(n)))


def shiftright(c, n: int) -> Col:
    return Col(arith.ShiftRight(_expr(c), _lit_expr(n)))


def shiftrightunsigned(c, n: int) -> Col:
    return Col(arith.ShiftRightUnsigned(_expr(c), _lit_expr(n)))


def round(c, scale: int = 0) -> Col:  # noqa: A001
    return Col(arith.Round(_expr(c), scale))


def pow(base, exp) -> Col:  # noqa: A001
    return Col(arith.Pow(_expr(base), _lit_expr(exp)))


def rand(seed: int = 0) -> Col:
    return Col(arith.Rand(seed))


# ----------------------------------------------------------------- aggregates

def _agg(func_cls, c, **kw) -> Col:
    return Col(AggregateExpression(func_cls(_expr(c), **kw)))


def sum(c) -> Col:  # noqa: A001
    return _agg(agg.Sum, c)


def count(c="*") -> Col:
    # NB: don't write `c == "*"` — Col.__eq__ builds an expression
    if (isinstance(c, str) and c == "*") or \
            (isinstance(c, Col) and isinstance(c.expr, Literal)):
        return Col(AggregateExpression(agg.Count(None)))
    return _agg(agg.Count, c)


def avg(c) -> Col:
    return _agg(agg.Average, c)


mean = avg


def min(c) -> Col:  # noqa: A001
    return _agg(agg.Min, c)


def max(c) -> Col:  # noqa: A001
    return _agg(agg.Max, c)


def first(c, ignore_nulls: bool = False) -> Col:
    return Col(AggregateExpression(agg.First(_expr(c), ignore_nulls)))


def stddev(c) -> Col:
    """Sample standard deviation (Spark stddev / stddev_samp)."""
    return _agg(agg.StddevSamp, c)


stddev_samp = stddev


def stddev_pop(c) -> Col:
    return _agg(agg.StddevPop, c)


def variance(c) -> Col:
    """Sample variance (Spark variance / var_samp)."""
    return _agg(agg.VarianceSamp, c)


var_samp = variance


def var_pop(c) -> Col:
    return _agg(agg.VariancePop, c)


def collect_list(c) -> Col:
    return Col(AggregateExpression(agg.CollectList(_expr(c))))


def collect_set(c) -> Col:
    return Col(AggregateExpression(agg.CollectSet(_expr(c))))


def last(c, ignore_nulls: bool = False) -> Col:
    return Col(AggregateExpression(agg.Last(_expr(c), ignore_nulls)))


# --------------------------------------------------------------------- udfs

def udf(f=None, returnType: str = "string"):
    """Register a Python UDF.

    The udf-compiler analog: the function's bytecode is compiled to a TPU
    expression tree when possible; otherwise it runs as a host black box.
    """
    from spark_rapids_tpu.columnar.dtypes import dtype_from_name

    def wrap(fn):
        rt = dtype_from_name(returnType) if isinstance(returnType, str) \
            else returnType

        def call(*cols) -> Col:
            from spark_rapids_tpu.udf.compiler import compile_udf
            from spark_rapids_tpu.udf.python_exec import PythonUDF
            args = [_expr(c) for c in cols]
            compiled = compile_udf(fn, args)
            if compiled is not None:
                return Col(compiled)
            return Col(PythonUDF(fn, rt, args))

        call.__name__ = getattr(fn, "__name__", "udf")
        call.fn = fn
        return call

    if f is not None:
        return wrap(f)
    return wrap


pandas_udf = udf


def tpu_udf(f=None, returnType: str = "double"):
    """Register a user-supplied JAX function as a columnar expression —
    the RapidsUDF analog (a UDF providing its own columnar evaluation,
    RapidsUDF.java:40).  ``fn`` receives the raw per-column jnp value
    arrays and returns one array; it traces INTO the enclosing stage's
    XLA program, so it fuses with the surrounding query for free."""
    from spark_rapids_tpu.columnar.dtypes import dtype_from_name

    def wrap(fn):
        rt = dtype_from_name(returnType) if isinstance(returnType, str) \
            else returnType

        def call(*cols) -> Col:
            from spark_rapids_tpu.udf.python_exec import JaxUDF
            return Col(JaxUDF(fn, rt, [_expr(c) for c in cols]))

        call.__name__ = getattr(fn, "__name__", "tpu_udf")
        call.fn = fn
        return call

    if f is not None:
        return wrap(f)
    return wrap


# ------------------------------------------------------------------ strings

def length(c) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.Length(_expr(c)))


def upper(c) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.Upper(_expr(c)))


def lower(c) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.Lower(_expr(c)))


def initcap(c) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.InitCap(_expr(c)))


def substring(c, pos: int, length_: int = 2**31 - 1) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.Substring(_expr(c), pos, length_))


def concat(*cols) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.ConcatStrings(*[_expr(c) for c in cols]))


def concat_ws(sep: str, *cols) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    parts = []
    for i, c in enumerate(cols):
        if i:
            parts.append(Literal(sep))
        parts.append(_expr(c))
    return Col(S.ConcatStrings(*parts))


def trim(c) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.StringTrim(_expr(c)))


def ltrim(c) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.StringTrimLeft(_expr(c)))


def rtrim(c) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.StringTrimRight(_expr(c)))


def lpad(c, width: int, pad: str = " ") -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.StringLPad(_expr(c), width, pad))


def rpad(c, width: int, pad: str = " ") -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.StringRPad(_expr(c), width, pad))


def locate(substr: str, c, start: int = 1) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.StringLocate(substr, _expr(c), start))


def substring_index(c, delim: str, count: int) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.SubstringIndex(_expr(c), delim, count))


def repeat(c, n: int) -> Col:
    from spark_rapids_tpu.ops import stringops as S
    return Col(S.StringRepeat(_expr(c), n))


# ---------------------------------------------------------------- date/time

def _dt(cls, c) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(getattr(D, cls)(_expr(c)))


def year(c) -> Col:
    return _dt("Year", c)


def month(c) -> Col:
    return _dt("Month", c)


def dayofmonth(c) -> Col:
    return _dt("DayOfMonth", c)


def dayofweek(c) -> Col:
    return _dt("DayOfWeek", c)


def weekday(c) -> Col:
    return _dt("WeekDay", c)


def dayofyear(c) -> Col:
    return _dt("DayOfYear", c)


def quarter(c) -> Col:
    return _dt("Quarter", c)


def hour(c) -> Col:
    return _dt("Hour", c)


def minute(c) -> Col:
    return _dt("Minute", c)


def second(c) -> Col:
    return _dt("Second", c)


def last_day(c) -> Col:
    return _dt("LastDay", c)


def next_day(c, day_of_week: str) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(D.NextDay(_expr(c), day_of_week))


def date_add(c, days) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(D.DateAdd(_expr(c), _lit_expr(days)))


def date_sub(c, days) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(D.DateSub(_expr(c), _lit_expr(days)))


def datediff(end, start) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(D.DateDiff(_expr(end), _expr(start)))


def add_months(c, months) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(D.AddMonths(_expr(c), _lit_expr(months)))


def months_between(a, b) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(D.MonthsBetween(_expr(a), _expr(b)))


def trunc(c, fmt: str) -> Col:
    from spark_rapids_tpu.ops import datetime_ops as D
    return Col(D.TruncDate(_expr(c), fmt))


def unix_timestamp(c) -> Col:
    return _dt("UnixTimestamp", c)


def from_unixtime(c) -> Col:
    return _dt("FromUnixTime", c)


# ------------------------------------------------------------------- windows

class Window:
    """Window spec builder (pyspark.sql.Window surface)."""

    unboundedPreceding = None
    unboundedFollowing = None
    currentRow = 0

    def __init__(self, partition=(), orders=(), frame=None):
        self._partition = list(partition)
        self._orders = list(orders)
        self._frame = frame

    @classmethod
    def partitionBy(cls, *cols) -> "Window":
        return cls(partition=[_expr(c) for c in cols])

    def orderBy(self, *keys) -> "Window":
        orders = []
        for k in keys:
            if isinstance(k, SortKey):
                orders.append((k.expr, k.descending, k.nulls_first))
            else:
                orders.append((_expr(k), False, True))
        return Window(self._partition, orders, self._frame)

    def rowsBetween(self, start, end) -> "Window":
        from spark_rapids_tpu.exec.window import Frame
        return Window(self._partition, self._orders,
                      Frame("rows", start, end))

    def rangeBetween(self, start, end) -> "Window":
        from spark_rapids_tpu.exec.window import Frame
        return Window(self._partition, self._orders,
                      Frame("range", start, end))

    def _spec(self):
        from spark_rapids_tpu.exec.window import WindowSpec
        return WindowSpec(self._partition, self._orders, self._frame)


class _WindowFunc(Col):
    """A window function waiting for .over(window)."""

    def __init__(self, kind: str, child=None, offset: int = 1, default=None):
        self._kind = kind
        self._child = child
        self._offset = offset
        self._default = default
        # not usable as a plain Col until .over()

    def over(self, window: Window) -> Col:
        from spark_rapids_tpu.exec.window import WindowExpression
        return Col(WindowExpression(
            self._kind, window._spec(),
            child=None if self._child is None else _expr(self._child),
            offset=self._offset,
            default=None if self._default is None
            else _lit_expr(self._default)))


def row_number() -> _WindowFunc:
    return _WindowFunc("row_number")


def rank() -> _WindowFunc:
    return _WindowFunc("rank")


def dense_rank() -> _WindowFunc:
    return _WindowFunc("dense_rank")


def percent_rank() -> _WindowFunc:
    return _WindowFunc("percent_rank")


def lead(c, offset: int = 1, default=None) -> _WindowFunc:
    return _WindowFunc("lead", c, offset, default)


def lag(c, offset: int = 1, default=None) -> _WindowFunc:
    return _WindowFunc("lag", c, offset, default)


def window_sum(c) -> _WindowFunc:
    return _WindowFunc("sum", c)


def window_count(c="*") -> _WindowFunc:
    return _WindowFunc(
        "count", None if isinstance(c, str) and c == "*" else c)


def window_min(c) -> _WindowFunc:
    return _WindowFunc("min", c)


def window_max(c) -> _WindowFunc:
    return _WindowFunc("max", c)


def window_avg(c) -> _WindowFunc:
    return _WindowFunc("avg", c)


# --------------------------------------------------------------- collections --

class _ExplodeMarker(Expression):
    """select-time marker routed into an L.Generate node by DataFrame.select
    (Spark's Generate planning of explode/posexplode)."""

    def __init__(self, child: Expression, position: bool):
        self.children = (child,)
        self.position = position

    @property
    def child(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self):
        return self.child.dtype.element

    def with_children(self, children):
        return _ExplodeMarker(children[0], self.position)

    @property
    def name(self) -> str:
        return "col"


def array(*cols) -> Col:
    from spark_rapids_tpu.ops.collections_ops import CreateArray
    return Col(CreateArray(*[_expr(c) for c in cols]))


def size(c) -> Col:
    from spark_rapids_tpu.ops.collections_ops import Size
    return Col(Size(_expr(c)))


def array_contains(c, value) -> Col:
    from spark_rapids_tpu.ops.collections_ops import ArrayContains
    return Col(ArrayContains(_expr(c), _lit_expr(value)))


def get_array_item(c, index) -> Col:
    from spark_rapids_tpu.ops.collections_ops import GetArrayItem
    return Col(GetArrayItem(_expr(c), _lit_expr(index)))


def element_at(c, index) -> Col:
    from spark_rapids_tpu.ops.collections_ops import ElementAt
    return Col(ElementAt(_expr(c), _lit_expr(index)))


def struct(*cols) -> Col:
    """struct(c1, c2, ...) — field names come from each column's
    name/alias (complexTypeCreator.scala CreateNamedStruct)."""
    from spark_rapids_tpu.ops.nested_ops import CreateNamedStruct
    pairs = []
    for c in cols:
        e = _expr(c)
        from spark_rapids_tpu.ops.expressions import Alias
        if isinstance(e, Alias):
            pairs.append((e.alias, e.children[0]))
        else:
            pairs.append((e.name, e))
    return Col(CreateNamedStruct(pairs))


def create_map(*entries) -> Col:
    """create_map(k1, v1, k2, v2, ...)."""
    from spark_rapids_tpu.ops.nested_ops import CreateMap
    return Col(CreateMap(*[_lit_expr(e) for e in entries]))


def map_keys(c) -> Col:
    from spark_rapids_tpu.ops.nested_ops import MapKeys
    return Col(MapKeys(_expr(c)))


def map_values(c) -> Col:
    from spark_rapids_tpu.ops.nested_ops import MapValues
    return Col(MapValues(_expr(c)))


def get_map_value(c, key) -> Col:
    from spark_rapids_tpu.ops.nested_ops import GetMapValue
    return Col(GetMapValue(_expr(c), _lit_expr(key)))


def sort_array(c, asc: bool = True) -> Col:
    from spark_rapids_tpu.ops.collections_ops import SortArray
    return Col(SortArray(_expr(c), asc))


def array_min(c) -> Col:
    from spark_rapids_tpu.ops.collections_ops import ArrayMin
    return Col(ArrayMin(_expr(c)))


def array_max(c) -> Col:
    from spark_rapids_tpu.ops.collections_ops import ArrayMax
    return Col(ArrayMax(_expr(c)))


def slice(c, start: int, length: int) -> Col:  # noqa: A001
    from spark_rapids_tpu.ops.collections_ops import Slice
    return Col(Slice(_expr(c), start, length))


def array_repeat(c, times: int) -> Col:
    """Bare strings are COLUMN references (PySpark semantics); use
    F.lit("x") to repeat a literal string."""
    from spark_rapids_tpu.ops.collections_ops import ArrayRepeat
    return Col(ArrayRepeat(_expr(c), times))


def reverse(c) -> Col:
    """reverse() over arrays (element order) or strings (byte-wise;
    ASCII-only incompat, like the engine's other byte kernels)."""
    from spark_rapids_tpu.ops.collections_ops import Reverse
    return Col(Reverse(_expr(c)))


def ascii(c) -> Col:
    from spark_rapids_tpu.ops.stringops import Ascii
    return Col(Ascii(_expr(c)))


def chr(c) -> Col:  # noqa: A001
    from spark_rapids_tpu.ops.stringops import Chr
    return Col(Chr(_expr(c)))


def explode(c) -> Col:
    return Col(_ExplodeMarker(_expr(c), position=False))


def posexplode(c) -> Col:
    return Col(_ExplodeMarker(_expr(c), position=True))


# -------------------------------------------------------------------- regex --

def rlike(c, pattern: str) -> Col:
    from spark_rapids_tpu.ops.regexops import RLike
    return Col(RLike(_expr(c), pattern))


def regexp_replace(c, pattern: str, replacement: str) -> Col:
    from spark_rapids_tpu.ops.regexops import RegExpReplace
    return Col(RegExpReplace(_expr(c), pattern, replacement))


def replace(c, search: str, replacement: str) -> Col:
    from spark_rapids_tpu.ops.regexops import StringReplace
    return Col(StringReplace(_expr(c), search, replacement))


def concat_ws(sep: str, *cols) -> Col:
    from spark_rapids_tpu.ops.regexops import ConcatWs
    return Col(ConcatWs(sep, *[_expr(c) for c in cols]))


def translate(c, from_str: str, to_str: str) -> Col:
    from spark_rapids_tpu.ops.regexops import Translate
    return Col(Translate(_expr(c), from_str, to_str))


# ---------------------------------------------------------------- misc ids --

def hash(*cols) -> Col:  # noqa: A001 - Spark calls it hash()
    from spark_rapids_tpu.ops.misc_exprs import Murmur3Hash
    return Col(Murmur3Hash(*[_expr(c) for c in cols]))


def md5(c) -> Col:
    from spark_rapids_tpu.ops.misc_exprs import Md5
    return Col(Md5(_expr(c)))


def monotonically_increasing_id() -> Col:
    from spark_rapids_tpu.ops.misc_exprs import _BatchIdMarker
    return Col(_BatchIdMarker("mid"))


def spark_partition_id() -> Col:
    from spark_rapids_tpu.ops.misc_exprs import _BatchIdMarker
    return Col(_BatchIdMarker("pid"))


def input_file_name() -> Col:
    """Source file path of each row (resolves against the file scan;
    the DataFrame layer enables the scan's metadata column on use)."""
    from spark_rapids_tpu.plan.logical import FileRelation
    return Col(UnresolvedColumn(FileRelation.INPUT_FILE_COL))


class _PandasAggCall(Col):
    """Marker produced by a grouped-agg pandas UDF call; GroupedData.agg
    routes it into an AggInPandas node (never evaluated as an
    expression)."""

    def __init__(self, fn, return_type, arg_name: str):
        self.fn = fn
        self.return_type = return_type
        self.arg_name = arg_name
        self.out_name = f"{getattr(fn, '__name__', 'udf')}({arg_name})"

    @property
    def expr(self):
        raise TypeError("grouped-agg pandas UDFs are only valid inside "
                        "groupBy().agg()")

    @expr.setter
    def expr(self, v):  # pragma: no cover
        pass

    def alias(self, name: str) -> "_PandasAggCall":
        out = _PandasAggCall(self.fn, self.return_type, self.arg_name)
        out.out_name = name
        return out

    def over(self, window: "Window") -> "_PandasWindowCall":
        """Window form: the UDF evaluates once per frame and the scalar
        broadcasts to the frame's anchor row (GpuWindowInPandasExec
        analog)."""
        return _PandasWindowCall(self, window)


class _PandasWindowCall(Col):
    """Marker for pandas-UDF-over-window; DataFrame.select routes it
    into a WindowInPandas node."""

    def __init__(self, call: _PandasAggCall, window: "Window"):
        self.call = call
        self.window = window
        self.out_name = call.out_name

    @property
    def expr(self):
        raise TypeError("windowed pandas UDFs are only valid in select()")

    @expr.setter
    def expr(self, v):  # pragma: no cover
        pass

    def alias(self, name: str) -> "_PandasWindowCall":
        out = _PandasWindowCall(self.call, self.window)
        out.out_name = name
        return out

    def spec_data(self):
        """(partition_names, [(order_name, desc, nulls_first)], frame) —
        host execution needs plain column names."""
        from spark_rapids_tpu.ops.expressions import UnresolvedColumn

        def name_of(e, what):
            if isinstance(e, UnresolvedColumn):
                return e.col_name
            raise ValueError(
                f"windowed pandas UDFs: {what} must be plain columns, "
                f"got {e}")

        w = self.window
        parts = [name_of(e, "partitionBy") for e in w._partition]
        orders = [(name_of(e, "orderBy"), d, nf)
                  for e, d, nf in w._orders]
        if len({nf for _, _, nf in orders}) > 1:
            # pandas sort_values has one global na_position; refusing
            # beats silently mis-framing
            raise ValueError(
                "windowed pandas UDFs require a consistent nulls-first/"
                "nulls-last across orderBy keys")
        frame = w._frame
        if frame is None:
            from spark_rapids_tpu.exec.window import Frame
            frame = Frame("range", None, 0) if orders else \
                Frame("rows", None, None)
        elif frame.kind == "range":
            # explicit range frames: only running bounds are supported
            # (Spark's WindowInPandas restriction), and like Spark a
            # range frame requires an ordering
            if not (frame.lo is None and frame.hi in (0, None)):
                raise ValueError(
                    "windowed pandas UDFs support rows-based frames and "
                    "the running range frame only")
            if not orders:
                raise ValueError(
                    "a range window frame requires orderBy")
        return parts, orders, frame


def pandas_agg_udf(f=None, returnType: str = "double"):
    """Grouped-aggregate pandas UDF (Spark's pandas_udf with GROUPED_AGG):
    ``fn(pd.Series) -> scalar``, one call per group
    (GpuAggregateInPandasExec analog)."""
    from spark_rapids_tpu.columnar.dtypes import dtype_from_name

    def wrap(fn):
        rt = dtype_from_name(returnType) if isinstance(returnType, str) \
            else returnType

        def call(col_name) -> _PandasAggCall:
            if not isinstance(col_name, str):
                raise TypeError("grouped-agg pandas UDFs take a column "
                                "NAME argument")
            return _PandasAggCall(fn, rt, col_name)

        call.__name__ = getattr(fn, "__name__", "udf")
        call.fn = fn
        return call

    if f is not None:
        return wrap(f)
    return wrap


# --------------------------------------------- grouping sets (rollup/cube) --

class _GroupingIdMarker(Expression):
    """Placeholder for ``grouping_id()``; GroupedData.agg rewrites it to
    the Expand-produced grouping-id column (GpuExpandExec lowering)."""

    children = ()

    @property
    def dtype(self):
        return dts.INT64

    @property
    def nullable(self):
        return False

    @property
    def name(self):
        return "grouping_id()"

    def emit(self, ctx):
        raise RuntimeError(
            "grouping_id() is only valid in rollup/cube/groupingSets "
            "aggregations")

    def cache_key(self):
        return ("_GroupingIdMarker",)


class _GroupingMarker(Expression):
    """Placeholder for ``grouping(col)`` (1 when the column is
    aggregated away in this output row, else 0)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return _GroupingMarker(children[0])

    @property
    def dtype(self):
        return dts.INT32

    @property
    def nullable(self):
        return False

    @property
    def name(self):
        return f"grouping({self.children[0].name})"

    def emit(self, ctx):
        raise RuntimeError(
            "grouping() is only valid in rollup/cube/groupingSets "
            "aggregations")

    def cache_key(self):
        return ("_GroupingMarker", self.children[0].cache_key())


def grouping_id() -> Col:
    """Bit vector of the aggregated-away grouping columns (Spark
    ``grouping_id()``; bit i, MSB-first over the grouping columns, is 1
    when column i is rolled up in this row)."""
    return Col(_GroupingIdMarker())


def grouping(c) -> Col:
    """1 when the grouping column is aggregated away in this row, else 0
    (Spark ``grouping``; returns int32 where Spark returns tinyint)."""
    return Col(_GroupingMarker(_expr(c)))


# ------------------------------------------------ expression-tail surface --

def get_json_object(c, path: str) -> Col:
    """Extract a JSONPath subset ($.field, $.a.b, $[n]) from a JSON
    string column (host-evaluated: CPU fallback single-process, or the
    dictionary lowering on a mesh)."""
    from spark_rapids_tpu.ops.json_ops import GetJsonObject
    return Col(GetJsonObject(_expr(c), path))


def split(c, pattern: str, limit: int = -1) -> Col:
    """split(str, regex) -> array<string> (host-evaluated; the indexed
    device form is ``split_part``)."""
    from spark_rapids_tpu.ops.json_ops import StringSplit
    return Col(StringSplit(_expr(c), pattern, limit))


def date_format(c, fmt: str) -> Col:
    """Format a date/timestamp with a fixed-width pattern
    (yyyy/MM/dd/HH/mm/ss + separators) on device; other patterns fall
    back to CPU."""
    from spark_rapids_tpu.ops.datetime_ops import DateFormatClass
    return Col(DateFormatClass(_expr(c), fmt))


def to_unix_timestamp(c, fmt: Optional[str] = None) -> Col:
    """Seconds since the epoch; string inputs parse via the cast path
    (default ISO format — like unix_timestamp with a format arg)."""
    from spark_rapids_tpu.ops.datetime_ops import ToUnixTimestamp
    return Col(ToUnixTimestamp(_expr(c)))


def _parse_duration_us(s: str) -> int:
    import re as _re
    m = _re.fullmatch(
        r"\s*(\d+)\s*(microsecond|millisecond|second|minute|hour|day|"
        r"week)s?\s*", s)
    if not m:
        raise ValueError(f"cannot parse duration {s!r}")
    n = int(m.group(1))
    unit = {"microsecond": 1, "millisecond": 1_000, "second": 1_000_000,
            "minute": 60_000_000, "hour": 3_600_000_000,
            "day": 86_400_000_000, "week": 604_800_000_000}[m.group(2)]
    return n * unit


def window(c, window_duration: str, slide_duration: Optional[str] = None,
           start_time: str = "0 seconds") -> Col:
    """Tumbling/sliding time-window bucketing: a (start, end) struct
    column for groupBy (GpuTimeWindow analog)."""
    from spark_rapids_tpu.ops.datetime_ops import TimeWindow
    from spark_rapids_tpu.ops.nested_ops import CreateNamedStruct
    win = _parse_duration_us(window_duration)
    slide = _parse_duration_us(slide_duration) if slide_duration else win
    start = _parse_duration_us(start_time)
    e = _expr(c)
    return Col(Alias(CreateNamedStruct(
        [("start", TimeWindow(e, win, slide, start, "start")),
         ("end", TimeWindow(e, win, slide, start, "end"))]), "window"))
