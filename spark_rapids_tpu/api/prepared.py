"""Prepared statements: hoist once, bind per run, execute at QPS.

``session.prepare(df)`` runs the literal-hoisting pass
(plan/template.py) ONCE and returns a :class:`PreparedStatement`.
Each ``handle.run(p0=..., p1=...)`` binds a new parameter vector and
executes — skipping parsing, planning and override translation on
repeats (the baseline-rung physical plan is cached on the handle) while
still passing through admission, deadline budgets, the recovery ladder
and span tracing like any ad-hoc query.  Because the ParamSlot cache
keys are value-free, repeats share one traced program per stage across
literal churn: zero retraces, zero persistent-tier recompiles, zero
planning passes after warmup.

The handle's ParamSlots are mutable shared state: ``run`` serializes
executions with a per-handle lock, so one handle is safe to call from
many threads (runs queue) but concurrent throughput wants one handle
per thread — ``prepare`` is cheap and handles with identical plans
share every jit/AOT entry anyway.

Requires ``spark.rapids.tpu.template.enabled`` (default off): with the
conf off, plans execute on the exact-key path and ``prepare`` refuses
rather than silently returning a handle that re-plans every run.
"""

from __future__ import annotations

import threading
from typing import List, Tuple

from spark_rapids_tpu.api.dataframe import DataFrame


class PreparedStatement:
    """A hoisted plan template plus a cached physical plan.

    Construct via :meth:`TpuSession.prepare`.  ``info`` is the
    :class:`~spark_rapids_tpu.plan.template.TemplateInfo`; ``refusals``
    lists the (reason, expr) pairs the hoister left inline — a handle
    with refusals still works, it just shares less (the profiling
    health check surfaces templates whose refusals cost them reuse).
    """

    def __init__(self, session, df: DataFrame):
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.plan.template import hoist_literals
        if not session.conf.get(rc.TEMPLATE_ENABLED):
            raise RuntimeError(
                "session.prepare requires "
                f"{rc.TEMPLATE_ENABLED.key}=true (plan templates are "
                "default-off; ad-hoc execution is unaffected)")
        self.session = session
        self.dataframe = df
        self.info = hoist_literals(df.plan)
        # baseline-rung physical plan, planned ONCE here (classic
        # prepared-statement semantics: prepare pays for planning so
        # no run ever does — a run whose first miss planned lazily
        # would smuggle a planning pass into the serving window) and
        # reused on every repeat (physical plans are stateless —
        # execute() returns a fresh iterator).  Recovery-ladder rungs
        # (cpu_only / split-batch) re-plan per attempt and never touch
        # this slot.
        self.exec_plan = session.plan(self.info.plan)
        self.run_count = 0
        self._lock = threading.Lock()
        # the frame that executes: the ORIGINAL plan for event/explain
        # text, with the back-pointer _execute_batches reads to adopt
        # this handle's pre-hoisted template and cached physical plan
        self._frame = DataFrame(session, df.plan)
        self._frame._prepared = self

    # ------------------------------------------------------------ facts --
    @property
    def param_count(self) -> int:
        return self.info.param_count

    @property
    def fingerprint(self) -> str:
        return self.info.fingerprint

    @property
    def refusals(self) -> List[Tuple[str, str]]:
        return list(self.info.refusals)

    def describe(self) -> str:
        """Human-readable slot table + refusal list (docs/debugging)."""
        lines = [f"template {self.info.fingerprint[:16]} "
                 f"({self.param_count} parameter(s))"]
        for s in self.info.slots:
            lines.append(f"  $p{s.index}: {s.dtype.name} "
                         f"= {s.value!r}")
        for reason, expr in self.info.refusals:
            lines.append(f"  inline [{reason}]: {expr}")
        return "\n".join(lines)

    # ------------------------------------------------------------- runs --
    def _resolve(self, args, kwargs) -> Tuple:
        """Positional args (full vector) or ``pN=...`` keywords
        (partial: unnamed slots keep their previous binding)."""
        n = self.info.param_count
        if args and kwargs:
            raise TypeError(
                "pass parameters positionally or by name, not both")
        if args:
            return tuple(args)
        vals = list(self.info.values())
        for k, v in kwargs.items():
            if not (len(k) > 1 and k[0] == "p" and k[1:].isdigit()):
                raise TypeError(
                    f"unknown parameter {k!r}; slots are named "
                    f"p0..p{n - 1}")
            i = int(k[1:])
            if i >= n:
                raise TypeError(
                    f"parameter p{i} out of range; template has "
                    f"{n} slot(s)")
            vals[i] = v
        return tuple(vals)

    def run_batches(self, *args, **params):
        """Bind and execute, returning raw columnar batches — the
        no-conversion entry the QPS bench drives."""
        values = self._resolve(args, params)
        with self._lock:
            self.info.bind(values)
            self.run_count += 1
            return self._frame._execute_batches()

    def run(self, *args, **params) -> List[tuple]:
        """Bind and execute, returning rows like ``df.collect()``."""
        values = self._resolve(args, params)
        with self._lock:
            self.info.bind(values)
            self.run_count += 1
            return self._frame.collect()

    def run_pandas(self, *args, **params):
        values = self._resolve(args, params)
        with self._lock:
            self.info.bind(values)
            self.run_count += 1
            return self._frame.to_pandas()
