"""DataFrame API over logical plans."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

from spark_rapids_tpu.api.functions import Col, SortKey, _expr, _lit_expr
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops.expressions import (
    Alias, BoundReference, Expression, UnresolvedColumn)
from spark_rapids_tpu.plan import logical as L


class PivotedGroupedData:
    """Pivot rewrite: each aggregate over x becomes, per pivot value v,
    the same aggregate over IF(p == v, x, NULL) — the standard pivot
    lowering (nulls are ignored by every aggregate), so no new kernel is
    needed and the result matches GpuPivotFirst."""

    def __init__(self, df: DataFrame, group_exprs, pivot_expr, values):
        self.df = df
        self.group_exprs = group_exprs
        self.pivot_expr = pivot_expr
        self.values = values

    def agg(self, *aggs: "Col") -> DataFrame:
        import copy
        from spark_rapids_tpu.ops import predicates as preds
        from spark_rapids_tpu.ops.expressions import Alias, Literal
        from spark_rapids_tpu.plan.logical import AggregateExpression
        agg_exprs = [_expr(a) for a in aggs]
        out: List[Expression] = []
        for v in self.values:
            for e in agg_exprs:
                alias = e.alias if isinstance(e, Alias) else None
                inner = e.children[0] if isinstance(e, Alias) else e
                if not isinstance(inner, AggregateExpression):
                    raise ValueError("pivot aggregates must be aggregate "
                                     "expressions")
                func = copy.copy(inner.func)
                # CASE WHEN p == v THEN x END (implicit null else): every
                # aggregate ignores nulls, realizing the pivot.  count()
                # has no child: count rows where p == v via CASE -> 1.
                cond = preds.EqualTo(self.pivot_expr, Literal(v))
                if func.child is not None:
                    child_name = func.child.name
                    func.child = preds.CaseWhen([(cond, func.child)])
                else:
                    child_name = "*"
                    func.child = preds.CaseWhen([(cond, Literal(1))])
                if len(agg_exprs) == 1:
                    name = str(v)
                else:
                    name = f"{v}_{alias}" if alias else \
                        f"{v}_{func.name}({child_name})"
                out.append(Alias(AggregateExpression(func), name))
        return DataFrame(self.df.session, L.Aggregate(
            self.group_exprs, out, self.df.plan))

    def sum(self, c) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        return self.agg(F.sum(c))

    def count(self) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        return self.agg(F.count(self.pivot_expr))

    def min(self, c) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        return self.agg(F.min(c))

    def max(self, c) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        return self.agg(F.max(c))

    def avg(self, c) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        return self.agg(F.avg(c))


class CoGroupedData:
    """groupBy(a).cogroup(other.groupBy(b)).applyInPandas(fn, schema):
    fn(left_group_df, right_group_df) per key in the union of keys
    (GpuFlatMapCoGroupsInPandasExec analog)."""

    def __init__(self, left: "GroupedData", right: "GroupedData"):
        self.left = left
        self.right = right

    def applyInPandas(self, fn, schema) -> DataFrame:
        lnames = [e.name for e in self.left.group_exprs]
        rnames = [e.name for e in self.right.group_exprs]
        return DataFrame(self.left.df.session, L.CoGroupMapInPandas(
            fn, _parse_schema(schema), lnames, rnames,
            self.left.df.plan, self.right.df.plan))


def _parse_schema(schema):
    """'a int, b string' or [(name, DataType)] -> Schema."""
    from spark_rapids_tpu.columnar.dtypes import dtype_from_name
    if isinstance(schema, str):
        out = []
        for part in schema.split(","):
            name, tname = part.strip().split()
            out.append((name, dtype_from_name(tname)))
        return out
    return list(schema)


def _file_meta_needs(exprs, schema) -> set:
    """Which file-metadata column groups these expressions reference
    that the schema doesn't expose yet."""
    present = {n for n, _ in schema}
    needs = set()
    for e in exprs:
        for r in e.references():
            if r == L.FileRelation.INPUT_FILE_COL and r not in present:
                needs.add("input_file")
            elif (r == "_metadata" or r.startswith("_metadata.")) and \
                    "_metadata.file_path" not in present:
                needs.add("metadata")
    return needs


def _attach_file_meta(plan: L.LogicalPlan, needs: set):
    """Rebuild the plan with file-metadata columns enabled on its
    FileRelation leaves.  Metadata columns append to the END of the scan
    schema, so bound ordinals in intermediate Filter/Limit/Sort nodes
    stay valid; anything else between the reference and the scan is
    unsupported (as in Spark, metadata columns resolve against the
    scan)."""
    import copy
    if isinstance(plan, L.FileRelation):
        new = copy.copy(plan)
        new.pushed_filters = list(plan.pushed_filters)
        new.file_meta = set(plan.file_meta) | needs
        return new
    if isinstance(plan, (L.Filter, L.Limit, L.Sort)):
        child = _attach_file_meta(plan.children[0], needs)
        if child is None:
            return None
        new = copy.copy(plan)
        new.children = (child,)
        return new
    return None


def _persistent_delta(before: dict, after: dict) -> dict:
    """Per-query persistent jit-cache deltas for the QueryEnd fusion
    dict (ops/jit_cache.persistent_info snapshots).  Process-global
    counters, same attribution contract as the pipeline dict's
    jitCacheHits/Misses: under concurrent queries the deltas smear
    across overlapping envelopes — fine for the health checks (which
    key on zero-vs-nonzero), wrong tool for per-tenant billing."""
    return {
        "persistentEnabled": bool(after.get("enabled")),
        "persistentHits": after.get("hits", 0) - before.get("hits", 0),
        "persistentMisses":
            after.get("misses", 0) - before.get("misses", 0),
        "persistentInvalid":
            after.get("invalid", 0) - before.get("invalid", 0),
        "persistentStores":
            after.get("stores", 0) - before.get("stores", 0),
    }


def _is_window(e: Expression) -> bool:
    from spark_rapids_tpu.exec.window import WindowExpression
    inner = e.children[0] if isinstance(e, Alias) else e
    return isinstance(inner, WindowExpression)


def _contains_window(e: Expression) -> bool:
    from spark_rapids_tpu.exec.window import WindowExpression
    if isinstance(e, WindowExpression):
        return True
    return any(_contains_window(c) for c in e.children)


class DataFrame:
    def __init__(self, session, plan: L.LogicalPlan):
        self.session = session
        self.plan = plan
        # plan-template state (plan/template.py): _template is the
        # TemplateInfo active for the CURRENT execution (set fresh per
        # _execute_batches); _prepared is the owning PreparedStatement
        # handle, whose pre-hoisted template and cached physical plan
        # repeats reuse
        self._template = None
        self._prepared = None

    # ------------------------------------------------------------- transforms --
    @property
    def schema(self):
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return [n for n, _ in self.plan.schema]

    def select(self, *cols: Union[Col, str]) -> "DataFrame":
        from spark_rapids_tpu.ops.nested_ops import \
            expand_nested_projections
        routed_pw = self._route_pandas_windows(cols)
        if routed_pw is not None:
            return routed_pw
        exprs = [_expr(c) for c in cols]
        needs = _file_meta_needs(exprs, self.plan.schema)
        if needs:
            attached = _attach_file_meta(self.plan, needs)
            if attached is None:
                raise ValueError(
                    "input_file_name()/_metadata are only available "
                    "above a file scan (optionally through "
                    "filter/limit/sort)")
            return DataFrame(self.session, attached).select(*cols)
        exprs = expand_nested_projections(exprs, self.plan.schema)
        gen = self._route_generate(exprs)
        if gen is not None:
            return gen
        routed = self._route_batch_ids(exprs)
        if routed is not None:
            return routed
        win_idx = {i for i, e in enumerate(exprs) if _contains_window(e)}
        if win_idx:
            # lift every WindowExpression (top-level OR nested inside
            # arithmetic, e.g. rev * 100 / sum(rev) over (...)) into a
            # hidden column of one Window node, then project the
            # rewritten expressions over it
            from spark_rapids_tpu.exec.window import WindowExpression
            child_names = [n for n, _ in self.plan.schema]
            prefix = "__w"
            while any(n.startswith(prefix) for n in child_names):
                prefix += "_"
            wexprs: List = []

            def extract(e):
                if isinstance(e, WindowExpression):
                    h = f"{prefix}{len(wexprs)}"
                    wexprs.append((h, e))
                    return UnresolvedColumn(h)
                if not e.children:
                    return e
                return e.with_children([extract(c) for c in e.children])

            final: List[Expression] = []
            for i, e in enumerate(exprs):
                if i not in win_idx:
                    final.append(e)
                    continue
                out_name = e.name if not isinstance(e, Alias) else None
                r = extract(e)
                # a bare window (or windowed arithmetic) keeps its
                # pretty output name; Alias.with_children keeps its own
                final.append(r if out_name is None else
                             Alias(r, out_name))
            wplan = L.Window(wexprs, self.plan)
            return DataFrame(self.session, L.Project(final, wplan))
        return DataFrame(self.session, L.Project(exprs, self.plan))

    def _route_pandas_windows(self, cols) -> Optional["DataFrame"]:
        """Route pandas-UDF-over-window markers into a WindowInPandas
        node, then select the requested columns on top.  Result columns
        get collision-proof internal names so replacing an existing
        column (withColumn semantics) never duplicates a schema entry;
        the final projection re-enters select() so nested expansion /
        explode routing still apply to the other columns."""
        from spark_rapids_tpu.api.functions import _PandasWindowCall
        if not any(isinstance(c, _PandasWindowCall) for c in cols):
            return None
        child_names = [n for n, _ in self.plan.schema]
        prefix = "_pw"
        while any(n.startswith(prefix) for n in child_names):
            prefix += "_"
        calls, final = [], []
        for c in cols:
            if isinstance(c, _PandasWindowCall):
                internal = f"{prefix}{len(calls)}"
                calls.append((internal, c.call.fn, c.call.arg_name,
                              c.call.return_type, c.spec_data()))
                final.append(Alias(UnresolvedColumn(internal),
                                   c.out_name))
            else:
                final.append(c)
        base = DataFrame(self.session,
                         L.WindowInPandas(calls, self.plan))
        return base.select(*final)

    def _route_generate(self, exprs) -> Optional["DataFrame"]:
        """Route F.explode/F.posexplode in a select into an L.Generate
        node (Spark plans Generate the same way)."""
        from spark_rapids_tpu.api.functions import _ExplodeMarker
        from spark_rapids_tpu.ops.expressions import Alias

        def marker_of(e):
            inner = e.children[0] if isinstance(e, Alias) else e
            return inner if isinstance(inner, _ExplodeMarker) else None

        marked = [(i, e, marker_of(e)) for i, e in enumerate(exprs)]
        gens = [(i, e, m) for i, e, m in marked if m is not None]
        if not gens:
            return None
        if len(gens) > 1:
            raise ValueError("only one explode per select is supported")
        i, e, m = gens[0]
        required = [x for j, x in enumerate(exprs) if j != i]
        col_name = e.alias if isinstance(e, Alias) else "col"
        return DataFrame(self.session, L.Generate(
            m.child, required, m.position, self.plan, col_name=col_name))

    def _route_batch_ids(self, exprs) -> Optional["DataFrame"]:
        """monotonically_increasing_id()/spark_partition_id() need batch
        state: insert a BatchId node and rewrite markers to its columns."""
        from spark_rapids_tpu.ops.misc_exprs import _BatchIdMarker

        def rewrite(e):
            if isinstance(e, _BatchIdMarker):
                return UnresolvedColumn(
                    "__mid" if e.kind == "mid" else "__pid")
            if not e.children:
                return e
            return e.with_children([rewrite(c) for c in e.children])

        def has_marker(e):
            if isinstance(e, _BatchIdMarker):
                return True
            return any(has_marker(c) for c in e.children)

        if not any(has_marker(e) for e in exprs):
            return None
        base = L.BatchId(self.plan)
        out = []
        for e in exprs:
            r = rewrite(e)
            if isinstance(r, UnresolvedColumn) and r.col_name in (
                    "__mid", "__pid"):
                r = Alias(r, e.name)
            out.append(r)
        return DataFrame(self.session, L.Project(out, base))

    def filter(self, condition: Col) -> "DataFrame":
        cond = _expr(condition)
        needs = _file_meta_needs([cond], self.plan.schema)
        if needs:
            attached = _attach_file_meta(self.plan, needs)
            if attached is None:
                raise ValueError(
                    "input_file_name()/_metadata are only available "
                    "above a file scan (optionally through "
                    "filter/limit/sort)")
            return DataFrame(self.session, attached).filter(condition)
        return DataFrame(self.session, L.Filter(cond, self.plan))

    where = filter

    def withColumn(self, name: str, c: Col) -> "DataFrame":
        from spark_rapids_tpu.api.functions import _PandasWindowCall
        if isinstance(c, _PandasWindowCall):
            wrapped = c.alias(name)
        else:
            wrapped = Alias(_expr(c), name)
        exprs: List = []
        replaced = False
        for n, _ in self.plan.schema:
            if n == name:
                exprs.append(wrapped)
                replaced = True
            else:
                exprs.append(UnresolvedColumn(n))
        if not replaced:
            exprs.append(wrapped)
        return self.select(*exprs)

    with_column = withColumn

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        exprs = [Alias(UnresolvedColumn(n), new) if n == old
                 else UnresolvedColumn(n) for n, _ in self.plan.schema]
        return DataFrame(self.session, L.Project(exprs, self.plan))

    def drop(self, *names: str) -> "DataFrame":
        exprs = [UnresolvedColumn(n) for n, _ in self.plan.schema
                 if n not in names]
        return DataFrame(self.session, L.Project(exprs, self.plan))

    def groupBy(self, *cols: Union[Col, str]) -> "GroupedData":
        from spark_rapids_tpu.ops.datetime_ops import TimeWindow
        from spark_rapids_tpu.ops.nested_ops import CreateNamedStruct
        exprs: List[Expression] = []
        for c in cols:
            e = _expr(c)
            inner = e.children[0] if isinstance(e, Alias) else e
            if isinstance(inner, CreateNamedStruct):
                # struct group keys (e.g. F.window(...)) shred into one
                # key per field; the shredded names reassemble into the
                # struct column at the output boundary
                first = inner.pairs[0][1]
                if isinstance(first, TimeWindow) and \
                        first.slide_us < first.window_us:
                    if len(cols) != 1:
                        raise ValueError(
                            "sliding window(...) must be the only "
                            "grouping column")
                    return self._group_by_sliding_window(e, first)
                name = e.name if isinstance(e, Alias) else "struct"
                exprs.extend(Alias(fe, f"{name}.{fn}")
                             for fn, fe in inner.pairs)
                continue
            exprs.append(e)
        return GroupedData(self, exprs)

    def _group_by_sliding_window(self, aliased, tw) -> "GroupedData":
        """Sliding time windows: each row belongs to up to
        ceil(window/slide) overlapping windows — expand one replica per
        overlap (Spark's TimeWindowing rule lowers through Expand the
        same way), keep replicas whose window really contains the
        timestamp, then group by (start, end)."""
        from spark_rapids_tpu.exec.expand import Expand
        from spark_rapids_tpu.ops import predicates as preds
        from spark_rapids_tpu.ops.datetime_ops import TimeWindow
        name = aliased.name if isinstance(aliased, Alias) else "window"
        s_col, e_col = f"{name}.start", f"{name}.end"
        k = -(-tw.window_us // tw.slide_us)
        base_names = [n for n, _ in self.plan.schema]
        projections = []
        for i in range(k):
            shift = i * tw.slide_us
            proj: List[Expression] = [UnresolvedColumn(n)
                                      for n in base_names]
            proj.append(Alias(TimeWindow(tw.child, tw.window_us,
                                         tw.slide_us, tw.start_us,
                                         "start", shift), s_col))
            proj.append(Alias(TimeWindow(tw.child, tw.window_us,
                                         tw.slide_us, tw.start_us,
                                         "end", shift), e_col))
            projections.append(proj)
        expand = Expand(projections, base_names + [s_col, e_col],
                        self.plan)
        cond = preds.GreaterThan(UnresolvedColumn(e_col), tw.child)
        filtered = L.Filter(cond, expand)
        return GroupedData(DataFrame(self.session, filtered),
                           [UnresolvedColumn(s_col),
                            UnresolvedColumn(e_col)])

    group_by = groupBy

    def rollup(self, *cols: Union[Col, str]) -> "GroupedData":
        """GROUP BY ROLLUP: hierarchical subtotals (a,b) -> (a) -> ()
        (lowered through Expand — GpuExpandExec analog)."""
        from spark_rapids_tpu.exec.expand import rollup_sets
        exprs = [_expr(c) for c in cols]
        return GroupedData(self, exprs, sets=rollup_sets(len(exprs)))

    def cube(self, *cols: Union[Col, str]) -> "GroupedData":
        """GROUP BY CUBE: all 2^n grouping-column subsets."""
        from spark_rapids_tpu.exec.expand import cube_sets
        exprs = [_expr(c) for c in cols]
        return GroupedData(self, exprs, sets=cube_sets(len(exprs)))

    def groupingSets(self, sets, *cols: Union[Col, str]) -> "GroupedData":
        """Explicit GROUPING SETS: ``sets`` is a list of lists of column
        names (each a subset of ``cols``)."""
        exprs = [_expr(c) for c in cols]
        names = [e.name for e in exprs]
        idx_sets = []
        for s in sets:
            idx = []
            for item in s:
                nm = item if isinstance(item, str) else _expr(item).name
                if nm not in names:
                    raise ValueError(
                        f"grouping set column {nm!r} is not in the "
                        f"grouping columns {names}")
                idx.append(names.index(nm))
            idx_sets.append(idx)
        return GroupedData(self, exprs, sets=idx_sets)

    grouping_sets = groupingSets

    def agg(self, *aggs: Col) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on, how: str = "inner"
             ) -> "DataFrame":
        how = {"left_outer": "left", "right_outer": "right",
               "outer": "full", "full_outer": "full", "leftsemi": "semi",
               "left_semi": "semi", "leftanti": "anti",
               "left_anti": "anti"}.get(how, how)
        if isinstance(on, (str,)) or (isinstance(on, (list, tuple)) and
                                      all(isinstance(k, str) for k in on)):
            keys = [on] if isinstance(on, str) else list(on)
            lk = [UnresolvedColumn(k) for k in keys]
            rk = [UnresolvedColumn(k) for k in keys]
            return DataFrame(self.session, L.Join(
                self.plan, other.plan, lk, rk, how, using=keys))
        if isinstance(on, (list, tuple)):
            # PySpark form: a list of Column conditions, AND-ed together
            from spark_rapids_tpu.ops import predicates as preds
            exprs = [_expr(c) for c in on]
            combined = exprs[0]
            for c in exprs[1:]:
                combined = preds.And(combined, c)
            on = Col(combined)
        # expression join condition: split equi conjuncts (left-col ==
        # right-col) into hash-join keys, the rest into a residual
        # condition (GpuHashJoin equi extraction; pure-residual inner
        # joins become nested-loop = cross + filter)
        cond = _expr(on)
        lnames = {n for n, _ in self.plan.schema}
        rnames = {n for n, _ in other.plan.schema}
        dup = lnames & rnames
        if dup:
            raise ValueError(
                f"expression joins need distinct column names on the two "
                f"sides; duplicated: {sorted(dup)}")
        from spark_rapids_tpu.ops import predicates as preds

        def conjuncts(e):
            if isinstance(e, preds.And):
                return conjuncts(e.children[0]) + conjuncts(e.children[1])
            return [e]

        def side_of(e):
            refs = set(e.references())
            if refs and refs <= lnames:
                return "l"
            if refs and refs <= rnames:
                return "r"
            return None

        lk, rk, residual = [], [], []
        for c in conjuncts(cond):
            if isinstance(c, preds.EqualTo):
                a, b = c.children
                sa, sb = side_of(a), side_of(b)
                if sa == "l" and sb == "r":
                    lk.append(a)
                    rk.append(b)
                    continue
                if sa == "r" and sb == "l":
                    lk.append(b)
                    rk.append(a)
                    continue
            residual.append(c)
        condition = None
        if residual:
            condition = residual[0]
            for c in residual[1:]:
                condition = preds.And(condition, c)
        return DataFrame(self.session, L.Join(
            self.plan, other.plan, lk, rk, how, condition=condition))

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self.session, L.Join(
            self.plan, other.plan, [], [], "cross"))

    def orderBy(self, *keys: Union[Col, str, SortKey]) -> "DataFrame":
        orders = []
        for k in keys:
            if isinstance(k, SortKey):
                orders.append((k.expr, k.descending, k.nulls_first))
            else:
                orders.append((_expr(k), False, True))
        return DataFrame(self.session, L.Sort(orders, self.plan))

    sort = orderBy

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self.session, L.Limit(n, self.plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self.session, L.Union([self.plan, other.plan]))

    unionAll = union

    def distinct(self) -> "DataFrame":
        return DataFrame(self.session, L.Aggregate(
            [UnresolvedColumn(n) for n, _ in self.plan.schema], [],
            self.plan))

    # --------------------------------------------------------------- caching --
    def cache(self) -> "DataFrame":
        """Mark this plan for caching: the first action materializes it as
        compressed host columnar frames (ParquetCachedBatchSerializer
        analog); later queries containing this plan read the cache."""
        self.session.cache_manager.register(self.plan)
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        self.session.cache_manager.unregister(self.plan)
        return self

    @property
    def is_cached(self) -> bool:
        return self.session.cache_manager.lookup(self.plan) is not None

    # --------------------------------------------------------------- actions --
    def _execute_batches(self) -> List[ColumnarBatch]:
        # every query action runs inside a QueryContext (serving/): the
        # per-query scope for event attribution, checkpoint lineage,
        # budgets and injection scoping — its exit purges stale
        # thread-ident adoptions so nothing of this query leaks into
        # the next one that recycles a thread.  Admission (the
        # byte-weighted fair semaphore) is acquired before any device
        # work and released on completion or fatal exit; a rejection
        # is a typed AdmissionFault for THIS query only.
        #
        # Under the context, the recovery driver re-drives classified
        # transient faults down the degradation ladder (retry -> spill
        # -> smaller batches -> single device -> CPU); fatal faults
        # re-raise untouched (robustness/driver.py).  Mesh sessions
        # additionally carry a per-query stage-checkpoint lineage log
        # so retry-class re-attempts resume from the last completed
        # exchange stage instead of re-running from source
        from spark_rapids_tpu.robustness.checkpoint import (
            CheckpointManager)
        from spark_rapids_tpu.robustness.driver import QueryRetryDriver
        from spark_rapids_tpu.serving.context import QueryContext
        with QueryContext(self.session) as ctx:
            # plan-keyed result cache (serving/reuse.py): consulted
            # BEFORE planning or admission — a verified hit (exact
            # plan text + matching input fingerprint + CRC) answers
            # with zero executions and zero queueing; the token
            # carries the PRE-execution fingerprint for the store.
            # A continuous-ingest tick's OWN executions bypass BOTH
            # reuse stores (no lookup, no store, no shared-stage
            # registration): a tick's plans over transient state
            # relations carry id()-keyed in-memory fingerprints whose
            # no-alias invariant ("the owning plan keeps its batches
            # alive") does not hold for state batches freed at the
            # next commit, and shared writes would outlive the epoch
            # store's rollback — the tick's crash-consistency
            # contract rests on the epoch store alone, and committed
            # tick work shares through the commit-published epoch
            # tier instead.  The gate is the tick-EXECUTION marker,
            # not the coarse tick-scope one: an ordinary query issued
            # from within a tick callback (an on_commit sink-side
            # lookup) caches normally
            # (robustness/incremental.in_tick_execution)
            from spark_rapids_tpu.robustness.incremental import (
                in_tick_execution)
            tick = in_tick_execution()
            # parameterized plan templates (plan/template.py): hoist
            # constant literals into typed parameter slots so the
            # jit / AOT / fused-stage tiers key on the TEMPLATE and
            # the values ride as device-scalar dispatch arguments —
            # zero retrace across literal churn.  Default-off; tick
            # executions keep the exact path (their plans are over
            # transient state relations).  A prepared handle
            # (api/prepared.py) injects its pre-hoisted template
            # instead of re-hoisting per run.
            from spark_rapids_tpu.config import rapids_conf as rc
            prep = getattr(self, "_prepared", None)
            info = None
            if prep is not None:
                info = prep.info
                self._template = info
            elif not tick and \
                    self.session.conf.get(rc.TEMPLATE_ENABLED):
                from spark_rapids_tpu.plan.template import (
                    hoist_literals)
                info = hoist_literals(self.plan)
                self._template = info if info.hoisted else None
            else:
                self._template = None
            if info is not None:
                # template facts ride the QueryEnd sharing dict: the
                # profiling health check groups repeats by fingerprint
                # and explains a template that bought nothing via the
                # refusal list (knobs-off streams stay HEAD-identical
                # — this only fires when template.enabled is on)
                ctx.sharing["template"] = {
                    "fingerprint": info.fingerprint[:16],
                    "params": info.param_count,
                    "refusals": sorted({r for r, _ in info.refusals}),
                }
            cache = getattr(self.session, "result_cache", None)
            pend = None
            use_template_cache = (
                cache is not None and not tick
                and self._template is not None
                and self.session.conf.get(
                    rc.TEMPLATE_RESULT_CACHE_ENABLED))
            if use_template_cache:
                # template tier: keyed on (template fingerprint,
                # parameter vector).  The template PLAN's exact key is
                # value-free (ParamSlot cache keys carry no binding),
                # so templated runs must never key the exact tier on
                # it — two bindings would alias.
                pend = cache.offer_template(self._template)
            elif cache is not None and not tick:
                pend = cache.offer(self.plan)
            if pend is not None and pend.hit:
                return self._answer_from_cache(pend)
            ctx.admit()
            if pend is not None and ctx.admission_wait_ms > 0.5:
                # the query actually QUEUED: an identical twin ahead
                # of it may have stored the answer while it waited
                # (the dashboard-stampede shape — N near-simultaneous
                # duplicates should cost ONE execution, not N), so
                # re-consult before paying for a redundant run.  The
                # first offer already counted this query's miss —
                # count_miss=False keeps the hit rate honest.
                pend = cache.offer_template(
                    self._template, count_miss=False) \
                    if use_template_cache else \
                    cache.offer(self.plan, count_miss=False)
                if pend.hit:
                    return self._answer_from_cache(pend)
            driver = QueryRetryDriver(self.session)
            # cross-query stage cache: when enabled, the SHARED
            # always_resume store rides as this query's checkpoint
            # manager — completed exchange stages register for every
            # tenant and input-fingerprinted subtrees splice on first
            # attempts.  The per-query manager is the fallback (its
            # lineage dies with the query).
            shared = getattr(self.session, "shared_stages", None)
            use_shared = (shared is not None and shared.enabled
                          and not tick
                          and getattr(self.session, "mesh", None)
                          is not None
                          and self.session.checkpoints is None)
            mgr = None
            if use_shared:
                self.session.checkpoints = shared
            else:
                mgr = CheckpointManager.for_query(self.session)
            try:
                batches = driver.run(self._attempt_batches)
                if pend is not None:
                    cache.store(pend, batches)
                return batches
            except Exception as exc:
                # a fatal/exhausted ladder still flushes its full
                # recovery/watchdog/checkpoint trail to the eventlog,
                # so post-mortems see what was tried —
                # QueryInfo.recovery is no longer complete only when
                # the ladder succeeds
                self._flush_fatal_trail(driver, exc)
                raise
            finally:
                if use_shared:
                    # detach only (never finish(): the shared store's
                    # entries outlive this query by design); drain any
                    # tally the QueryEnd didn't pop (events disabled)
                    # so recycled thread idents never inherit it
                    shared.take_query_stats()
                    if self.session.checkpoints is shared:
                        self.session.checkpoints = None
                elif mgr is not None:
                    mgr.finish()

    def _answer_from_cache(self, pend) -> List[ColumnarBatch]:
        """Result-cache hit: emit a complete (trivial) query envelope
        so the event stream, profiling and concurrency timeline see
        the query, then answer from the store — zero executions."""
        events = getattr(self.session, "events", None)
        note = "template-cache hit" \
            if getattr(pend, "tier", "exact") == "template" \
            else "result-cache hit"
        if events is not None and events.enabled:
            qid = next(self.session._query_ids)
            self.session._current_qid = qid
            events.emit("QueryStart", queryId=qid,
                        logicalPlan=self.plan.tree_string(),
                        physicalPlan="ResultCache",
                        explain=note)
            events.emit("QueryEnd", queryId=qid, status="success",
                        durationMs=0.0, metrics={}, spill={},
                        retry={}, sharing=self._sharing_info(),
                        explain=note)
        self.session.last_dist_explain = note
        return pend.batches

    def _flush_fatal_trail(self, driver, exc: BaseException) -> None:
        ev = getattr(self.session, "events", None)
        if ev is None or not ev.enabled:
            return
        from spark_rapids_tpu.robustness.watchdog import watchdog_metrics
        mgr = getattr(self.session, "checkpoints", None)
        try:
            ev.emit(
                "QueryFatal",
                queryId=getattr(self.session, "_current_qid", None),
                error=f"{type(exc).__name__}: {exc}",
                recovery=list(getattr(driver, "trail", [])),
                watchdog=watchdog_metrics.snapshot(),
                checkpoint=mgr.snapshot() if mgr is not None else {})
        except Exception:
            pass  # the post-mortem record must never mask the fault

    def _attempt_batches(self, mode) -> List[ColumnarBatch]:
        # every attempt runs in a watchdog query scope: stale
        # cancellation tokens from a previous attempt are cleared, and
        # the query's deadline budget (serving.deadlineBudgetMs, else
        # spark.rapids.tpu.watchdog.queryDeadlineMs) bounds this
        # attempt's wall time — an overrun is a retryable TimeoutFault
        # delivered at the next checkpoint, so a hung attempt
        # re-drives down the ladder instead of blocking forever
        from spark_rapids_tpu.robustness import watchdog
        from spark_rapids_tpu.serving import context as qc
        ctx = qc.current()
        deadline = ctx.deadline_budget_ms \
            if ctx is not None and ctx.deadline_budget_ms else None
        with watchdog.query_scope(self.session, deadline_ms=deadline):
            return self._attempt_batches_impl(mode)

    def _admission_info(self) -> dict:
        """What admission cost this query (QueryEnd payload)."""
        from spark_rapids_tpu.serving import context as qc
        ctx = qc.current()
        return ctx.admission_info() if ctx is not None else {}

    def _sharing_info(self) -> dict:
        """Cross-query reuse facts for the QueryEnd ``sharing`` dict:
        result-cache hit/miss flags (serving/reuse.py offer notes; a
        STORE lands after the envelope closed and rides the
        ResultCacheStore event instead), the shared stage store's
        write/splice tallies for this query, and the interleaver's
        wait/slice accounting.  EMPTY —
        and therefore absent from the event — when every reuse knob is
        off, so the knobs-off event stream is bit-identical to HEAD."""
        from spark_rapids_tpu.serving import context as qc
        ctx = qc.current()
        out = {}
        if ctx is not None:
            out.update(ctx.sharing)
            t = ctx.interleave_ticket
            if t is not None:
                out["interleave"] = t.info()
        shared = getattr(self.session, "shared_stages", None)
        if shared is not None and shared.enabled:
            out.update(shared.take_query_stats())
        return out

    def _attempt_batches_impl(self, mode) -> List[ColumnarBatch]:
        import time as _time
        from spark_rapids_tpu.api.session import TpuSession
        # conf resolved at call time (retry budget, semaphore) follows
        # the session EXECUTING the query, not the last-constructed one
        TpuSession._active = self.session
        # a failure before this attempt draws its qid must not inherit
        # the previous query's id on its RecoveryAction events
        self.session._current_qid = None
        mesh = getattr(self.session, "mesh", None)
        if mesh is not None and \
                (not mode.use_mesh or mode.batch_scale != 1.0):
            self.session.last_dist_explain = (
                "demoted: single-device replan (query recovery)"
                if mode.batch_scale == 1.0 else
                "demoted: single-device split-batch replan "
                "(query recovery)")
        template = getattr(self, "_template", None)
        if mesh is not None and template is not None:
            # distributed/parallel kernels build EmitContexts without
            # a parameter vector: a templated plan executes on the
            # single-process engine (whose stage + fused-aggregate
            # kernels thread params) rather than silently failing
            # every slot emit on the mesh
            self.session.last_dist_explain = (
                "template: single-process execution "
                "(parameterized kernels)")
        if mode.use_mesh and mode.batch_scale == 1.0 and \
                mesh is not None and template is None:
            # mesh session: offer the plan to the distributed planner
            # first (planner-inserted exchange analog); unsupported plans
            # fall through to the single-process engine.  The split
            # rung (batch_scale < 1) skips this branch: the distributed
            # plan has no batch knob, so re-offering it would re-run
            # the identical plan that just failed
            from spark_rapids_tpu.exec.fusion import (fusion_metrics,
                                                      hash_wire_delta)
            from spark_rapids_tpu.ops.jit_cache import persistent_info
            from spark_rapids_tpu.parallel.dist_planner import (
                try_distributed)
            from spark_rapids_tpu.parallel.exchange_async import (
                ExchangeOverlapMetrics, overlap_metrics_for_session)
            from spark_rapids_tpu.parallel.shuffle import (
                ShuffleWireMetrics, metrics_for_session)
            from spark_rapids_tpu.utils import tracing
            events = getattr(self.session, "events", None)
            t0 = _time.perf_counter()
            wire = metrics_for_session(self.session)
            wire0 = wire.snapshot()
            fm0 = fusion_metrics.snapshot()
            overlap = overlap_metrics_for_session(self.session)
            overlap0 = overlap.snapshot()
            pjit0 = persistent_info()
            # gray-failure counter snapshot: QueryEnd pins THIS query's
            # hedge/quarantine deltas (None tracker = knob off, and the
            # event field is absent — bit-identical A/B)
            gray = getattr(self.session, "gray_health", None)
            gray0 = gray.query_counters() if gray is not None else None
            # the envelope opens BEFORE execution so everything the
            # attempt emits mid-flight — CheckpointWrite/Resume,
            # RecoveryAction, WatchdogTrip — carries this attempt's
            # qid and parses into the right QueryInfo (a failed
            # distributed attempt used to leave them unattributed);
            # QueryEnd restates the final explain once it is known
            qid = None
            if events is not None and events.enabled:
                qid = next(self.session._query_ids)
                self.session._current_qid = qid
                events.emit(
                    "QueryStart", queryId=qid,
                    logicalPlan=self.plan.tree_string(),
                    physicalPlan="DistributedPlan",
                    explain="distributed attempt")

            def _end(status, shuffle):
                # the span drain runs for EVERY envelope exit — events
                # on or off, success or failure — so trace files exist
                # for faulted attempts and buffers never pile up
                wall_ms = (_time.perf_counter() - t0) * 1e3
                spans = tracing.finish_query(self.session, qid,
                                             wall_ms, status)
                # cost-model ledger drain (every exit too: a faulted
                # attempt's envelope carries its replan decision, and
                # the ledger never leaks into the next query); absent
                # from the event when the model is off — HEAD parity
                cm = getattr(self.session, "cost_model", None)
                planner = cm.finish_query() if cm is not None else None
                self.session.last_planner_stats = planner
                if qid is not None:
                    fusion = dict(getattr(self.session,
                                          "last_fusion_stats", None)
                                  or {})
                    fusion.update(_persistent_delta(pjit0,
                                                    persistent_info()))
                    fusion.update(hash_wire_delta(fm0))
                    sh = self._sharing_info()
                    fleet = None
                    if gray is not None:
                        delta = type(gray).counters_delta(
                            gray.query_counters(), gray0)
                        if any(delta.values()) or gray.suspect_hosts():
                            fleet = dict(delta)
                            fleet["suspectHosts"] = gray.suspect_hosts()
                    events.emit(
                        "QueryEnd", queryId=qid, status=status,
                        durationMs=round(wall_ms, 3),
                        metrics={}, spill={}, retry={},
                        distributed=True, shuffle=shuffle,
                        fusion=fusion, spans=spans,
                        admission=self._admission_info(),
                        # absent entirely when every reuse knob is
                        # off — the knobs-off event stream must stay
                        # bit-identical to HEAD
                        **({"sharing": sh} if sh else {}),
                        **({"planner": planner} if planner else {}),
                        **({"fleet": fleet} if fleet else {}),
                        explain=self.session.last_dist_explain)

            try:
                dist = try_distributed(
                    self.session, self.plan,
                    resume=getattr(mode, "resume", False))
            except Exception as exc:
                _end(f"failed: {type(exc).__name__}: {exc}", {})
                raise
            if dist is not None:
                # per-query shuffle-wire delta: collectives launched,
                # bytes moved, padding ratio, overflow retries —
                # QueryInfo.shuffle in the eventlog tools, flagged by
                # the profiling health check when padding > 4x or an
                # exchange fell back to per-column collectives
                shuffle = ShuffleWireMetrics.summarize(
                    ShuffleWireMetrics.delta(wire.snapshot(), wire0))
                # async exchange/compute overlap + host-staging deltas
                # ride the same QueryInfo.shuffle dict (the
                # exchangeOverlapMs metric the MULTICHIP tail and the
                # profiling "exchange overlap" line report)
                shuffle.update(ExchangeOverlapMetrics.delta(
                    overlap.snapshot(), overlap0))
                # session attribute contract: None when the query never
                # exchanged (a distributed scan/filter); the event log
                # still gets the (zeros) dict so every distributed
                # query's QueryInfo.shuffle is present
                self.session.last_shuffle_stats = \
                    shuffle if shuffle.get("exchanges") else None
                _end("success", shuffle)
                return dist
            # unsupported plan: close the envelope cleanly (the
            # fallback reason rides in explain — not a failure) and
            # fall through to the single-process engine, which opens
            # its own
            _end("success", {})
        overrides = None
        if mode.batch_scale != 1.0:
            # split-batch rung: re-plan with the scan/coalesce batch
            # sizes scaled down so every operator's working set
            # shrinks.  Planned through a one-off TpuOverrides — batch
            # sizes are captured into the exec nodes at plan time — so
            # the session's conf is never mutated and concurrent
            # queries on other threads keep their own sizes
            from spark_rapids_tpu.config import rapids_conf as rc
            from spark_rapids_tpu.plan.overrides import TpuOverrides
            conf = self.session.conf
            for entry in (rc.READER_BATCH_SIZE_ROWS,
                          rc.BATCH_SIZE_BYTES):
                conf = conf.set(entry.key, max(
                    1, int(conf.get(entry) * mode.batch_scale)))
            overrides = TpuOverrides(conf, self.session.cache_manager)
        return self._run_single_process(mode, overrides)

    def _drive(self, exec_plan) -> List[ColumnarBatch]:
        """Materialize the plan's batches — through the asynchronous
        pipeline driver (exec/pipeline.py) when enabled, else the
        sequential pull loop.  Pipeline stats land on
        ``session.last_pipeline_stats`` either way (None when
        sequential) so benches and the event log can attribute overlap
        wins."""
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session.last_pipeline_stats = None
        conf = self.session.conf
        # fair interleaver (serving/scheduler.py): every batch pull
        # passes the weighted round-robin timeslice gate, so admitted
        # queries share the device batch-for-batch instead of FIFO
        # occupancy.  When pipelined, the wrapped iterator runs on the
        # worker thread — exactly the thread doing the dispatching.
        source = exec_plan.execute()
        sched = getattr(self.session, "interleaver", None)
        if sched is not None:
            from spark_rapids_tpu.serving import context as qc
            ctx = qc.current()
            ticket = getattr(ctx, "interleave_ticket", None) \
                if ctx is not None else None
            if ticket is not None:
                source = sched.interleaved(source, ticket)
        if not conf.get(rc.PIPELINE_ENABLED):
            return list(source)
        from spark_rapids_tpu.exec.pipeline import (
            PipelineStats, pipelined)
        stats = PipelineStats(conf.get(rc.PIPELINE_DEPTH))
        try:
            return list(pipelined(
                source, stats.depth,
                catalog=getattr(self.session, "memory_catalog", None),
                stats=stats,
                semaphore=getattr(self.session, "semaphore", None)))
        finally:
            self.session.last_pipeline_stats = stats

    def _run_single_process(self, mode,
                            overrides=None) -> List[ColumnarBatch]:
        import time as _time
        template = getattr(self, "_template", None)
        logical = template.plan if template is not None else self.plan
        prep = getattr(self, "_prepared", None)
        if mode.cpu_only:
            exec_plan = self.session.plan_cpu_only(logical)
        elif prep is not None and template is not None \
                and overrides is None:
            # prepared repeat on the baseline rung: reuse the handle's
            # cached physical plan — zero planning / override-translation
            # passes.  Ladder re-drives (cpu_only above, split-batch
            # overrides here) re-plan: their rung parameters are
            # captured into exec nodes at plan time.
            exec_plan = prep.exec_plan
            if exec_plan is None:
                exec_plan = self.session.plan(logical)
                prep.exec_plan = exec_plan
        else:
            exec_plan = self.session.plan(logical,
                                          overrides=overrides)
        self._last_exec = exec_plan
        from spark_rapids_tpu.utils import tracing
        events = getattr(self.session, "events", None)
        if events is None or not events.enabled:
            from spark_rapids_tpu.exec.fusion import (
                collect_runtime_savings, fusion_metrics, hash_wire_delta)
            from spark_rapids_tpu.ops.jit_cache import persistent_info
            self.session._current_qid = None
            p0 = persistent_info()
            fm0 = fusion_metrics.snapshot()
            t0 = _time.perf_counter()
            status = "success"
            try:
                return self._drive(exec_plan)
            except Exception as e:
                status = f"failed: {type(e).__name__}"
                raise
            finally:
                # session attribute contract matches the distributed
                # path: last_fusion_stats is set whether or not an
                # event log is attached (bench/tests read it)
                ov = overrides or self.session.overrides
                fusion = dict(getattr(ov, "last_fusion", None) or {})
                fusion.update(collect_runtime_savings(exec_plan))
                fusion.update(_persistent_delta(p0, persistent_info()))
                fusion.update(hash_wire_delta(fm0))
                self.session.last_fusion_stats = fusion
                # span drain runs with or without an event log: bench
                # reads session.last_span_stats, and trace files must
                # exist for logless sessions too
                tracing.finish_query(
                    self.session, None,
                    (_time.perf_counter() - t0) * 1e3, status)
                cm = getattr(self.session, "cost_model", None)
                self.session.last_planner_stats = \
                    cm.finish_query() if cm is not None else None
        qid = next(self.session._query_ids)
        # the recovery driver stamps RecoveryAction events with the qid
        # of the attempt that failed
        self.session._current_qid = qid
        events.emit("QueryStart", queryId=qid,
                    logicalPlan=self.plan.tree_string(),
                    physicalPlan=exec_plan.tree_string(),
                    explain=(overrides or
                             self.session.overrides).last_explain)
        cat = getattr(self.session, "memory_catalog", None)
        host0 = cat.spilled_to_host_total if cat else 0
        disk0 = cat.spilled_to_disk_total if cat else 0
        from spark_rapids_tpu.memory.retry import retry_metrics
        # thread-local view: concurrent queries on other threads must not
        # contaminate this query's attribution
        retry0 = retry_metrics.snapshot_local()
        from spark_rapids_tpu.exec.fusion import fusion_metrics
        from spark_rapids_tpu.ops.jit_cache import (cache_info,
                                                    persistent_info)
        jit0 = cache_info()
        pjit0 = persistent_info()
        fm0 = fusion_metrics.snapshot()
        t0 = _time.perf_counter()
        status = "success"
        try:
            return self._drive(exec_plan)
        except Exception as e:
            status = f"failed: {type(e).__name__}: {e}"
            raise
        finally:
            # per-query deltas of the session-cumulative spill counters
            spill = {} if cat is None else {
                "spilledToHostBytes": cat.spilled_to_host_total - host0,
                "spilledToDiskBytes": cat.spilled_to_disk_total - disk0,
            }
            retry1 = retry_metrics.snapshot_local()
            ps = getattr(self.session, "last_pipeline_stats", None)
            jit1 = cache_info()
            pipeline = ps.as_dict() if ps is not None else {}
            pipeline["jitCacheHits"] = jit1["hits"] - jit0["hits"]
            pipeline["jitCacheMisses"] = \
                jit1["misses"] - jit0["misses"]
            # per-query whole-stage fusion attribution: planned chains
            # from the planner, runtime dispatch savings from the
            # executed tree, persistent-tier deltas from the jit cache
            from spark_rapids_tpu.exec.fusion import (
                collect_runtime_savings, hash_wire_delta)
            ov = overrides or self.session.overrides
            fusion = dict(getattr(ov, "last_fusion", None) or {})
            fusion.update(collect_runtime_savings(exec_plan))
            fusion.update(_persistent_delta(pjit0, persistent_info()))
            fusion.update(hash_wire_delta(fm0))
            self.session.last_fusion_stats = fusion
            wall_ms = (_time.perf_counter() - t0) * 1e3
            spans = tracing.finish_query(self.session, qid, wall_ms,
                                         status)
            sh = self._sharing_info()
            node_metrics = exec_plan.collect_metrics()
            cm = getattr(self.session, "cost_model", None)
            planner = None
            if cm is not None:
                # per-op observed device us/row — the evidence the
                # unified CBO reads over its calibration file (the
                # metrics were already materialized for the event)
                cm.fold_op_metrics(node_metrics)
                planner = cm.finish_query()
            self.session.last_planner_stats = planner
            events.emit(
                "QueryEnd", queryId=qid, status=status,
                durationMs=round(wall_ms, 3),
                metrics=node_metrics, spill=spill,
                retry={k: retry1[k] - retry0[k] for k in retry1},
                pipeline=pipeline, fusion=fusion, spans=spans,
                admission=self._admission_info(),
                # absent when every reuse knob is off (HEAD parity)
                **({"sharing": sh} if sh else {}),
                **({"planner": planner} if planner else {}))

    def to_arrow(self):
        import pyarrow as pa
        from spark_rapids_tpu.columnar import nested
        batches = self._execute_batches()
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch
            table = empty_batch(self.plan.schema).to_arrow()
        else:
            table = pa.concat_tables(b.to_arrow() for b in batches)
        # shredded struct/map columns reassemble at the output boundary
        return nested.assemble_table(table)

    def createOrReplaceTempView(self, name: str) -> None:
        self.session.register_view(name, self)

    create_or_replace_temp_view = createOrReplaceTempView

    def to_device_batches(self):
        """ML interop, streaming form (ColumnarRdd analog —
        /root/reference sql-plugin ColumnarRdd: export the device table
        per partition to ML consumers without a host round trip).
        Yields the engine's internal device-resident ColumnarBatches
        one at a time (bounded memory — batches are NOT materialized up
        front; this path skips query event logging); columns expose jax
        arrays as ``.data``/``.validity``."""
        from spark_rapids_tpu.api.session import TpuSession
        TpuSession._active = self.session
        exec_plan = self.session.plan(self.plan)
        self._last_exec = exec_plan
        yield from exec_plan.execute()

    def to_jax(self):
        """ML interop, materialized form: the full result as a dict of
        column name -> jax device array (plus ``name__mask`` boolean
        validity arrays for nullable columns), trimmed to the row
        count.  Fixed-width columns only — strings/nested types have no
        dense tensor form; project them away first."""
        import jax.numpy as jnp
        from spark_rapids_tpu.ops.concat import concat_batches
        names = [n for n, _ in self.plan.schema]
        for name, dt in self.plan.schema:
            if dt.has_offsets or dt.is_nested:
                raise ValueError(
                    f"to_jax(): column {name!r} has type {dt}; only "
                    "fixed-width columns export as dense arrays")
            if name.endswith("__mask") and \
                    name[:-len("__mask")] in names:
                raise ValueError(
                    f"to_jax(): column {name!r} collides with the "
                    "validity-mask output key for "
                    f"{name[:-len('__mask')]!r}; alias it first")
        batches = self._execute_batches()
        if not batches:
            return {name: jnp.zeros(0, dtype=dt.storage)
                    for name, dt in self.plan.schema}
        merged = concat_batches(batches)
        out = {}
        n = merged.nrows
        for name, col in merged.columns.items():
            out[name] = col.data[:n]
            if col.validity is not None:
                out[name + "__mask"] = col.validity[:n]
        return out

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    toPandas = to_pandas

    def collect(self) -> List[tuple]:
        table = self.to_arrow()
        cols = [table.column(i).to_pylist()
                for i in range(table.num_columns)]
        return list(zip(*cols)) if cols else []

    def mapInPandas(self, fn, schema) -> "DataFrame":
        return DataFrame(self.session, L.MapInPandas(
            fn, _parse_schema(schema), self.plan))

    @property
    def write(self):
        from spark_rapids_tpu.io.writers import DataFrameWriter
        return DataFrameWriter(self)

    def count(self) -> int:
        from spark_rapids_tpu.api import functions as F
        rows = self.agg(F.count().alias("n")).collect()
        return int(rows[0][0])

    def show(self, n: int = 20) -> None:
        print(self.limit(n).to_pandas().to_string(index=False))

    def explain(self, mode: str = "formatted") -> None:
        exec_plan = self.session.plan(self.plan)
        print("== Logical Plan ==")
        print(str(self.plan))
        print("== Physical Plan ==")
        print(exec_plan.tree_string())
        print("== TPU Overrides ==")
        print(self.session.overrides.last_explain)


class GroupedData:
    def __init__(self, df: DataFrame, group_exprs: List[Expression],
                 sets: Optional[List[List[int]]] = None):
        self.df = df
        self.group_exprs = group_exprs
        self.sets = sets  # rollup/cube/grouping-sets index lists

    def agg(self, *aggs: Col) -> DataFrame:
        from spark_rapids_tpu.api.functions import _PandasAggCall
        if self.sets is not None:
            return self._agg_grouping_sets(aggs)
        pandas_aggs = [a for a in aggs if isinstance(a, _PandasAggCall)]
        if pandas_aggs:
            if len(pandas_aggs) != len(aggs):
                raise ValueError("cannot mix grouped-agg pandas UDFs "
                                 "with built-in aggregates")
            names = [e.name for e in self.group_exprs]
            specs = [(a.out_name, a.fn, a.arg_name, a.return_type)
                     for a in pandas_aggs]
            return DataFrame(self.df.session, L.AggInPandas(
                names, specs, self.df.plan))
        agg_exprs = [_expr(a) for a in aggs]
        return DataFrame(self.df.session, L.Aggregate(
            self.group_exprs, agg_exprs, self.df.plan))

    def _agg_grouping_sets(self, aggs) -> DataFrame:
        """Lower rollup/cube/grouping sets: Expand (one projection per
        grouping set, aggregated-away keys nulled, plus the grouping-id
        literal) -> Aggregate keyed on (keys..., grouping_id) -> final
        projection resolving grouping()/grouping_id() markers.
        Reference: GpuExpandExec rule (GpuOverrides.scala:3170)."""
        from spark_rapids_tpu.api.functions import (
            _GroupingIdMarker, _GroupingMarker)
        from spark_rapids_tpu.exec.expand import (
            Expand, GROUPING_ID_COL)
        from spark_rapids_tpu.ops import arithmetic as arith
        from spark_rapids_tpu.ops.expressions import Literal
        import numpy as np

        child = self.df.plan
        child_names = [n for n, _ in child.schema]
        n = len(self.group_exprs)

        # group columns: bare refs use the child column directly;
        # computed keys materialize as hidden columns first
        group_cols: List[str] = []
        pre_exprs: List[Expression] = []
        for i, e in enumerate(self.group_exprs):
            if isinstance(e, UnresolvedColumn) and \
                    e.col_name in child_names:
                group_cols.append(e.col_name)
            else:
                hidden = e.name if e.name not in child_names \
                    else f"__gs{i}"
                pre_exprs.append(Alias(e, hidden))
                group_cols.append(hidden)
        base = child
        if pre_exprs:
            base = L.Project(
                [UnresolvedColumn(c) for c in child_names] + pre_exprs,
                child)
        base_names = [nm for nm, _ in base.schema]

        # key slots are SEPARATE copies of the grouping columns (nulled
        # per set); the base columns pass through untouched so aggregate
        # children over a grouping column still see the real values
        # (Spark's Expand does the same duplication)
        from spark_rapids_tpu.exec.expand import grouping_set_projections
        key_exprs = [UnresolvedColumn(c).bind(base.schema)
                     for c in group_cols]
        projections = grouping_set_projections(
            key_exprs, self.sets,
            [UnresolvedColumn(nm) for nm in base_names])
        key_slots = [f"__gk{i}" for i in range(n)]
        expand = Expand(
            projections, key_slots + base_names + [GROUPING_ID_COL],
            base)

        gid_ref = UnresolvedColumn(GROUPING_ID_COL)

        def rewrite(e: Expression) -> Expression:
            if isinstance(e, _GroupingIdMarker):
                return gid_ref
            if isinstance(e, _GroupingMarker):
                target = e.children[0].name
                if target not in group_cols:
                    raise ValueError(
                        f"grouping({target}) references a non-grouping "
                        f"column; grouping columns: {group_cols}")
                bit = n - 1 - group_cols.index(target)
                from spark_rapids_tpu.ops.cast import Cast
                from spark_rapids_tpu.columnar import dtypes as _dts
                return Cast(
                    arith.BitwiseAnd(
                        arith.ShiftRight(gid_ref, Literal(bit)),
                        Literal(np.int64(1))), _dts.INT32)
            if not e.children:
                return e
            return e.with_children([rewrite(c) for c in e.children])

        agg_items: List[Expression] = []
        final_tail: List[Expression] = []  # post-agg select list tail

        def has_marker(e):
            if isinstance(e, (_GroupingIdMarker, _GroupingMarker)):
                return True
            return any(has_marker(c) for c in e.children)

        for a in aggs:
            e = _expr(a)
            if has_marker(e):
                r = rewrite(e)
                final_tail.append(r if isinstance(r, Alias)
                                  else Alias(r, e.name))
            else:
                agg_items.append(e)
                final_tail.append(UnresolvedColumn(e.name))

        agg_plan = L.Aggregate(
            [Alias(UnresolvedColumn(s), c)
             for s, c in zip(key_slots, group_cols)] + [gid_ref],
            agg_items, expand)
        final = [UnresolvedColumn(c) for c in group_cols] + final_tail
        return DataFrame(self.df.session, L.Project(final, agg_plan))

    def count(self) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        return self.agg(F.count().alias("count"))

    def pivot(self, col, values) -> "PivotedGroupedData":
        """df.groupBy(k).pivot(p, [v1, v2]).sum(x): one output column per
        pivot value (GpuPivotFirst, AggregateFunctions.scala:530).
        Values must be listed explicitly (Spark's implicit distinct-scan
        variant needs an extra query)."""
        return PivotedGroupedData(self.df, self.group_exprs, _expr(col),
                                  list(values))

    def applyInPandas(self, fn, schema) -> DataFrame:
        names = [e.name for e in self.group_exprs]
        return DataFrame(self.df.session, L.MapInPandas(
            fn, _parse_schema(schema), self.df.plan, group_names=names))

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        return CoGroupedData(self, other)

    def _simple(self, fname, *cols) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        fn = getattr(F, fname)
        names = cols or [n for n, dt in self.df.plan.schema
                         if dt.is_numeric and
                         n not in {e.name for e in self.group_exprs}]
        return self.agg(*[fn(c).alias(f"{fname}({c})") for c in names])

    def sum(self, *cols):  # noqa: A003
        return self._simple("sum", *cols)

    def avg(self, *cols):
        return self._simple("avg", *cols)

    def min(self, *cols):  # noqa: A003
        return self._simple("min", *cols)

    def max(self, *cols):  # noqa: A003
        return self._simple("max", *cols)
