"""TpuSession: entry point (the SparkSession + SQLPlugin bootstrap analog).

Where the reference's SQLPlugin hooks into an existing SparkSession
(Plugin.scala:57-70 injecting ColumnarOverrideRules), this standalone engine
owns the session: it holds the RapidsConf, the planner (TpuOverrides), and
the device runtime handles (memory manager + semaphore come with the memory
task).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

from spark_rapids_tpu.api.dataframe import DataFrame
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config.rapids_conf import RapidsConf
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.overrides import TpuOverrides


class DataFrameReader:
    def __init__(self, session: "TpuSession"):
        self.session = session
        self._options: Dict[str, str] = {}

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def _make(self, paths, file_format) -> DataFrame:
        from spark_rapids_tpu.io.bucketing import read_spec
        from spark_rapids_tpu.io.readers import infer_file_schema
        if isinstance(paths, str):
            paths = [paths]
        schema = infer_file_schema(paths, file_format)
        # a _bucket_spec.json sidecar marks a bucketed table (enables
        # equality-filter bucket pruning, io/bucketing.py)
        bucket_spec = read_spec(paths[0]) if len(paths) == 1 else None
        rel = L.FileRelation(paths, file_format, schema, self._options,
                             bucket_spec=bucket_spec)
        return DataFrame(self.session, rel)

    def parquet(self, *paths: str) -> DataFrame:
        return self._make(list(paths), "parquet")

    def orc(self, *paths: str) -> DataFrame:
        return self._make(list(paths), "orc")

    def csv(self, *paths: str) -> DataFrame:
        return self._make(list(paths), "csv")


class TpuSession:
    _active: Optional["TpuSession"] = None

    def __init__(self, conf: Optional[Union[RapidsConf, Dict]] = None,
                 mesh=None):
        """``mesh``: a ``jax.sharding.Mesh`` — supported queries then run
        distributed over it (parallel/dist_planner.py); alternatively set
        spark.rapids.sql.distributed.numShards to build one here."""
        if isinstance(conf, dict):
            conf = RapidsConf(conf)
        self.conf = conf or RapidsConf()
        from spark_rapids_tpu.exec.cache import CacheManager
        self.cache_manager = CacheManager()
        self.overrides = TpuOverrides(self.conf, self.cache_manager)
        self.last_dist_explain = ""
        self.last_scan_stats = None  # set by the sharded distributed scan
        self.last_pipeline_stats = None  # exec/pipeline.py PipelineStats
        # per-query shuffle-wire summary (parallel/shuffle.py
        # ShuffleWireMetrics.summarize): collectives, bytes moved,
        # padding ratio, slot-overflow retries of the last distributed
        # query; None when the query never exchanged
        self.last_shuffle_stats = None
        # per-query whole-stage fusion summary (exec/fusion.py):
        # fusedStages/fusedOperators/dispatchesSaved + persistent
        # jit-cache hit/miss deltas; None before the first query
        self.last_fusion_stats = None
        self.last_planning_error = None  # set by suppressPlanningFailure
        # persistent jit-cache tier (ops/jit_cache.py): process-global,
        # (re)configured from this session's conf — AOT-serialized
        # executables survive the process under jitCache.dir
        from spark_rapids_tpu.config import rapids_conf as _rc
        from spark_rapids_tpu.ops import jit_cache as _jc
        _jc.configure_persistent(
            self.conf.get(_rc.JIT_CACHE_DIR) or None,
            self.conf.get(_rc.JIT_CACHE_MAX_BYTES))
        # multi-controller bring-up MUST precede the first jax.devices()
        # call (mesh construction below): jax.distributed.initialize is
        # what makes the fleet's global devices visible
        self._init_fleet_runtime()
        self.mesh = mesh
        if self.mesh is None:
            from spark_rapids_tpu.config import rapids_conf as rc
            n = self.conf.get(rc.DISTRIBUTED_NUM_SHARDS)
            if n:
                from spark_rapids_tpu.parallel.mesh import make_mesh
                self.mesh = make_mesh(n)
        self._init_fleet_membership()
        self._init_memory()
        self._init_observability()
        if self.fleet_membership is not None:
            # the JOIN beat waits for the event logger so HostJoin
            # lands in the log (membership itself must exist earlier:
            # the serving caches read fleet_cache at construction)
            self.fleet_membership.beat(force=True)
        TpuSession._active = self

    def _init_fleet_runtime(self) -> None:
        """Join the multi-controller fleet when
        spark.rapids.tpu.fleet.coordinator/.processId/.numProcesses are
        configured (parallel/mesh.py init_fleet); single-controller
        configs no-op."""
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.parallel import mesh as mesh_lib
        self._fleet_multi = mesh_lib.init_fleet(
            self.conf.get(rc.FLEET_COORDINATOR),
            self.conf.get(rc.FLEET_PROCESS_ID),
            self.conf.get(rc.FLEET_NUM_PROCESSES))

    def _init_fleet_membership(self) -> None:
        """Stand up host membership + the fleet-scoped cache store.
        Three shapes: a real multi-controller fleet (hosts = jax
        processes), a logical-host fleet (fleet.logicalHosts partitions
        of a single-process mesh — the tier-1-testable simulation), or
        no fleet at all (every attribute None, zero overhead)."""
        import threading

        import jax
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.parallel import mesh as mesh_lib
        self.fleet_membership = None
        self.fleet_cache = None
        self.fleet_epoch = 0
        self._logical_hosts_assigned = False
        n_hosts, host = 1, 0
        if self._fleet_multi:
            n_hosts, host = jax.process_count(), jax.process_index()
        elif self.mesh is not None:
            logical = self.conf.get(rc.FLEET_LOGICAL_HOSTS)
            if logical >= 2:
                mesh_lib.assign_logical_hosts(self.mesh, logical)
                self._logical_hosts_assigned = True
                n_hosts = len(mesh_lib.mesh_hosts(self.mesh))
        if n_hosts > 1:
            self.fleet_membership = mesh_lib.HostMembership(
                mesh_lib.membership_dir(
                    self.conf.get(rc.FLEET_MEMBERSHIP_DIR),
                    self.conf.get(rc.FLEET_COORDINATOR)),
                host_id=host, n_hosts=n_hosts,
                heartbeat_ms=self.conf.get(rc.FLEET_HEARTBEAT_MS),
                missed_fatal=self.conf.get(rc.FLEET_MISSED_BEATS_FATAL),
                session=self)
        cache_dir = self.conf.get(rc.FLEET_CACHE_DIR)
        if cache_dir:
            from spark_rapids_tpu.serving.fleetcache import FleetStore
            self.fleet_cache = FleetStore(cache_dir, session=self)
            self.fleet_epoch = self.fleet_cache.fence_epoch()
        # gray-failure (fail-slow) runtime: default-off — None keeps
        # every consumption site a single getattr and the hot path
        # bit-identical to the knob-off run
        self.gray_health = None
        self.gray_deadlines = None
        self._full_mesh = None  # pre-quarantine mesh, for rejoin
        self._quarantined = set()  # hosts soft-shrunk but NOT lost
        self._gray_inflight = 0  # queries in flight (safe-boundary gate)
        self._gray_lock = threading.Lock()
        if self.conf.get(rc.GRAY_FAILURE_ENABLED):
            from spark_rapids_tpu.robustness.grayfailure import (
                DeadlineCalibrator, HostHealthTracker)
            if self.fleet_membership is not None:
                self.gray_health = HostHealthTracker(
                    session=self, host_id=host, n_hosts=n_hosts,
                    suspect_factor=self.conf.get(rc.FLEET_SUSPECT_FACTOR),
                    window=self.conf.get(rc.FLEET_SUSPECT_WINDOW),
                    min_samples=self.conf.get(rc.FLEET_SUSPECT_MIN_SAMPLES),
                    quarantine_after_ms=self.conf.get(
                        rc.FLEET_QUARANTINE_AFTER_MS),
                    rejoin_after_ms=self.conf.get(rc.FLEET_REJOIN_AFTER_MS),
                    hedge_percentile=self.conf.get(rc.FLEET_HEDGE_PERCENTILE),
                    hedge_margin=self.conf.get(rc.FLEET_HEDGE_MARGIN),
                    hedge_floor_ms=self.conf.get(rc.FLEET_HEDGE_FLOOR_MS))
            self.gray_deadlines = DeadlineCalibrator(
                floor_ms=self.conf.get(rc.WATCHDOG_CALIBRATION_FLOOR_MS),
                ceiling_ms=self.conf.get(rc.WATCHDOG_CALIBRATION_CEILING_MS),
                margin=self.conf.get(rc.WATCHDOG_CALIBRATION_MARGIN),
                min_samples=self.conf.get(
                    rc.WATCHDOG_CALIBRATION_MIN_SAMPLES))

    def shrink_fleet_mesh(self, lost_host: int = -1) -> bool:
        """The shrink rung's side effect (robustness/driver.py): swap
        ``session.mesh`` for one rebuilt over the surviving hosts, so
        the re-driven attempt plans distributed on what's left.  The
        fleet cache's fence epoch bumps atomically with the swap — a
        publish in flight from the lost host carries the OLD epoch and
        is rejected (it could hold bytes computed on the dead layout).
        ``lost_host`` names the casualty when known (-1: take the
        membership registry's lost set, else drop the highest-indexed
        remote host — the injected-loss-with-no-named-host case).
        Returns False when there is nothing to shrink."""
        from spark_rapids_tpu.parallel import mesh as mesh_lib
        membership = self.fleet_membership
        if membership is None or self.mesh is None:
            return False
        hosts_before = mesh_lib.mesh_hosts(self.mesh)
        lost = set(membership.lost)
        if lost_host >= 0:
            lost.add(lost_host)
        lost.discard(membership.host)
        if not (lost & set(hosts_before)):
            remote = [h for h in hosts_before if h != membership.host]
            if not remote:
                return False
            lost = {max(remote)}
        new_mesh = mesh_lib.surviving_mesh(self.mesh, lost)
        membership.lost |= lost
        from_devices = int(self.mesh.devices.size)
        if self._full_mesh is None:
            self._full_mesh = self.mesh  # rejoin's restore point
        self.mesh = new_mesh
        if self.fleet_cache is not None:
            self.fleet_epoch = self.fleet_cache.bump_fence(
                reason="shrink")
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session(
            "MeshShrink", self,
            fromHosts=len(hosts_before),
            toHosts=len(mesh_lib.mesh_hosts(new_mesh)),
            fromDevices=from_devices,
            toDevices=int(new_mesh.devices.size),
            lostHosts=sorted(lost), reason="host_loss")
        return True

    def quarantine_host(self, host: int) -> bool:
        """Gray-failure soft-shrink: drain a SUSPECT host out of the
        mesh through the SAME machinery the hard shrink rung uses
        (mesh swap + fence-epoch bump) — but the host is NOT judged
        lost: its beats keep flowing through the membership registry so
        the health tracker can watch it recover and rejoin it later."""
        from spark_rapids_tpu.parallel import mesh as mesh_lib
        if self.mesh is None or host < 0:
            return False
        hosts_before = mesh_lib.mesh_hosts(self.mesh)
        if host not in hosts_before or len(hosts_before) < 2:
            return False
        membership = self.fleet_membership
        if membership is not None and host == membership.host:
            return False  # never quarantine ourselves
        if self._full_mesh is None:
            self._full_mesh = self.mesh
        self._quarantined.add(host)
        drop = set(self._quarantined)
        if membership is not None:
            drop |= set(membership.lost)
        new_mesh = mesh_lib.surviving_mesh(self._full_mesh, drop)
        from_devices = int(self.mesh.devices.size)
        self.mesh = new_mesh
        if self.fleet_cache is not None:
            self.fleet_epoch = self.fleet_cache.bump_fence(
                reason="quarantine")
        tracker = self.gray_health
        if tracker is not None:
            tracker.mark_quarantined(host)
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session(
            "HostQuarantine", self, host=host,
            fromHosts=len(hosts_before),
            toHosts=len(mesh_lib.mesh_hosts(new_mesh)),
            fromDevices=from_devices,
            toDevices=int(new_mesh.devices.size))
        emit_on_session(
            "MeshShrink", self,
            fromHosts=len(hosts_before),
            toHosts=len(mesh_lib.mesh_hosts(new_mesh)),
            fromDevices=from_devices,
            toDevices=int(new_mesh.devices.size),
            lostHosts=sorted({host}), reason="quarantine")
        return True

    def rejoin_fleet_mesh(self, host: int) -> bool:
        """The shrink rung's inverse (new with gray failure): fold a
        recovered quarantined host back into the mesh at a safe
        boundary — caller guarantees no query in flight.  The fence
        epoch bumps AGAIN (advanced twice across quarantine→rejoin), so
        entries published against the shrunken layout are fenced from
        the restored one."""
        from spark_rapids_tpu.parallel import mesh as mesh_lib
        if self._full_mesh is None or host not in self._quarantined:
            return False
        self._quarantined.discard(host)
        membership = self.fleet_membership
        if membership is not None:
            membership.rejoin(host)
        drop = set(self._quarantined)
        if membership is not None:
            drop |= set(membership.lost)
        hosts_before = mesh_lib.mesh_hosts(self.mesh)
        from_devices = int(self.mesh.devices.size)
        new_mesh = (self._full_mesh if not drop
                    else mesh_lib.surviving_mesh(self._full_mesh, drop))
        self.mesh = new_mesh
        if not drop:
            self._full_mesh = None  # fully restored
        if self.fleet_cache is not None:
            self.fleet_epoch = self.fleet_cache.bump_fence(
                reason="rejoin")
        tracker = self.gray_health
        if tracker is not None:
            tracker.mark_rejoined(host)
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session(
            "HostRejoin", self, host=host,
            fromHosts=len(hosts_before),
            toHosts=len(mesh_lib.mesh_hosts(new_mesh)),
            fromDevices=from_devices,
            toDevices=int(new_mesh.devices.size))
        return True

    def maybe_apply_gray_actions(self) -> None:
        """Apply due quarantine/rejoin transitions — called from the
        recovery driver at a safe boundary (before a query's first
        attempt, when this is the only query in flight): mesh swaps
        never touch a plan mid-execution."""
        tracker = self.gray_health
        if tracker is None:
            return
        tracker.poll()
        with self._gray_lock:
            if self._gray_inflight > 1:
                return  # another query mid-flight: not a safe boundary
            for h in tracker.quarantine_due():
                self.quarantine_host(h)
            for h in tracker.rejoin_due():
                self.rejoin_fleet_mesh(h)

    def _init_observability(self) -> None:
        import itertools
        import uuid
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.utils.events import EventLogger
        self._query_ids = itertools.count(1)
        self.session_id = uuid.uuid4().hex[:12]
        # recovery actions (robustness/driver.py) in arrival order —
        # the in-memory mirror of the RecoveryAction event stream, so
        # tests and tools can read the trail without an event-log dir
        self.recovery_log = []
        # thread-keyed backing stores for the _current_qid /
        # checkpoints properties: one session serves concurrent
        # queries, each on its own driving thread, and a single
        # session-global "the qid in flight" would stamp query A's
        # recovery/watchdog/checkpoint events with query B's id
        self._qid_by_ident = {}
        self._checkpoints_by_ident = {}
        self._current_qid = None  # qid of the attempt in flight
        self.events = EventLogger(
            self.conf.get(rc.EVENT_LOG_DIR) or None, self.session_id,
            conf_snapshot=dict(self.conf.settings),
            flush_ms=self.conf.get(rc.EVENT_LOG_FLUSH_MS))
        # span-tracing runtime (utils/tracing.py): process-global, the
        # jitCache-tier discipline — this session's trace conf wins.
        # The observation store persists beside the AOT cache dir when
        # one is configured (warm starts get warm evidence), else
        # beside the trace exports.
        from spark_rapids_tpu.utils import tracing
        trace_dir = self.conf.get(rc.TRACE_DIR) or None
        self.last_span_stats = None  # QueryEnd spans rollup mirror
        tracing.configure(
            enabled=bool(self.conf.get(rc.TRACE_ENABLED) or trace_dir),
            trace_dir=trace_dir,
            max_events=self.conf.get(rc.TRACE_MAX_EVENTS),
            obs_dir=(self.conf.get(rc.JIT_CACHE_DIR) or trace_dir
                     or None))
        # self-tuning cost-based planner (plan/costmodel.py): one
        # evidence-fed decision authority over every tuning knob,
        # default-off — None keeps every consumption site a single
        # getattr and plans bit-identical to HEAD
        self.cost_model = None
        self.last_planner_stats = None  # QueryEnd planner dict mirror
        if self.conf.get(rc.COSTMODEL_ENABLED):
            from spark_rapids_tpu.plan.costmodel import CostModel
            self.cost_model = CostModel(self, self.conf)

    # per-query state views: call sites keep reading/writing
    # ``session._current_qid`` / ``session.checkpoints`` and get the
    # CALLING query's value — resolution is by effective thread ident
    # (worker threads adopted via exec/pipeline.worker_attribution
    # resolve to their driving query)
    @property
    def _current_qid(self):
        from spark_rapids_tpu.serving import context as qc
        return getattr(self, "_qid_by_ident", {}).get(
            qc.effective_ident())

    @_current_qid.setter
    def _current_qid(self, qid) -> None:
        from spark_rapids_tpu.serving import context as qc
        ident = qc.effective_ident()
        if qid is None:
            self._qid_by_ident.pop(ident, None)
        else:
            self._qid_by_ident[ident] = qid
        ctx = qc.current()
        if ctx is not None:
            ctx.set_qid(qid)

    @property
    def checkpoints(self):
        from spark_rapids_tpu.serving import context as qc
        return getattr(self, "_checkpoints_by_ident", {}).get(
            qc.effective_ident())

    @checkpoints.setter
    def checkpoints(self, mgr) -> None:
        from spark_rapids_tpu.serving import context as qc
        ident = qc.effective_ident()
        if mgr is None:
            self._checkpoints_by_ident.pop(ident, None)
        else:
            self._checkpoints_by_ident[ident] = mgr
        ctx = qc.current()
        if ctx is not None:
            ctx.checkpoints = mgr

    def stop(self) -> None:
        """Close the session's observability resources (SessionEnd)
        and sweep its spill tier — live handles close, orphaned
        ``buf-*`` spill/temp files are deleted, and the catalog's own
        temp dir is removed (the RapidsDiskStore shutdown analog)."""
        self.events.close()
        from spark_rapids_tpu.utils import tracing
        obs = tracing.observation_store()
        if obs is not None:
            obs.flush()
        cm = getattr(self, "cost_model", None)
        if cm is not None:
            try:
                cm.store.flush()
            except Exception:
                pass  # evidence persistence must not block teardown
        for store_attr in ("result_cache", "shared_stages"):
            store = getattr(self, store_attr, None)
            if store is not None:
                try:
                    store.close()
                except Exception:
                    pass  # teardown must reach the catalog sweep
        membership = getattr(self, "fleet_membership", None)
        if membership is not None:
            membership.leave()
        if getattr(self, "_logical_hosts_assigned", False):
            # module-level simulation state must not leak into the
            # next session's link classification
            from spark_rapids_tpu.parallel.mesh import \
                clear_logical_hosts
            clear_logical_hosts()
        cat = getattr(self, "memory_catalog", None)
        if cat is not None:
            cat.close()
        if TpuSession._active is self:
            TpuSession._active = None

    def _init_memory(self) -> None:
        """GpuDeviceManager.initializeGpuAndMemory analog: size the spill
        catalog from HBM and install the admission semaphore."""
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.memory.spill import (
            SpillableBatchCatalog, TpuSemaphore, set_default_catalog)
        device_budget = self.conf.get(rc.DEVICE_MEMORY_LIMIT)
        if not device_budget:
            import jax
            try:
                stats = jax.devices()[0].memory_stats() or {}
                hbm = stats.get("bytes_limit", 16 << 30)
            except Exception:
                hbm = 16 << 30
            # GpuDeviceManager.scala:170-245 sizing contract: subtract
            # the runtime reserve, apply alloc fraction, clamp to the
            # max fraction, and fail fast below the min fraction
            reserve = self.conf.get(rc.MEM_RESERVE)
            usable = max(hbm - reserve, 0)
            device_budget = int(usable * self.conf.get(rc.MEM_POOL_FRACTION))
            max_budget = int(usable * self.conf.get(rc.MEM_MAX_ALLOC_FRACTION))
            device_budget = min(device_budget, max_budget)
            min_budget = int(hbm * self.conf.get(rc.MEM_MIN_ALLOC_FRACTION))
            if device_budget < min_budget:
                raise ValueError(
                    f"device pool {device_budget} bytes is below "
                    f"minAllocFraction*HBM ({min_budget}); lower "
                    "spark.rapids.memory.tpu.reserve / raise "
                    "allocFraction, or lower minAllocFraction")
        from spark_rapids_tpu import native
        self.memory_catalog = SpillableBatchCatalog(
            device_budget=device_budget,
            host_budget=self.conf.get(rc.HOST_SPILL_STORAGE_SIZE),
            frame_codec=native.codec_level(
                self.conf.get(rc.SHUFFLE_COMPRESSION_CODEC)),
            disk_write_threads=self.conf.get(rc.SPILL_DISK_WRITE_THREADS),
            integrity_check=self.conf.get(rc.SPILL_INTEGRITY_ENABLED),
            checkpoint_floor=self.conf.get(
                rc.SERVING_CHECKPOINT_FLOOR_BYTES),
            host_codec=native.codec_level(
                self.conf.get(rc.ENCODING_STORAGE_HOST_CODEC)))
        set_default_catalog(self.memory_catalog)
        self.semaphore = TpuSemaphore(
            self.conf.get(rc.CONCURRENT_TPU_TASKS))
        # session-level admission control (serving/admission.py): the
        # query-granularity GpuSemaphore — at most concurrentQueries
        # in flight, their memory weights fitting in
        # hbmAdmissionFraction of the device budget; 0 disables
        n_adm = self.conf.get(rc.SERVING_CONCURRENT_QUERIES)
        if n_adm > 0:
            from spark_rapids_tpu.serving.admission import (
                AdmissionController)
            self.admission = AdmissionController(
                max_queries=n_adm,
                hbm_bytes=int(device_budget * self.conf.get(
                    rc.SERVING_HBM_ADMISSION_FRACTION)),
                default_weight=self.conf.get(
                    rc.SERVING_QUERY_MEMORY_BUDGET),
                timeout_ms=self.conf.get(
                    rc.SERVING_ADMISSION_TIMEOUT_MS),
                max_queue=self.conf.get(rc.SERVING_MAX_QUEUED_QUERIES))
        else:
            self.admission = None
        # fair interleaving + cross-query reuse (serving/scheduler.py,
        # serving/reuse.py) — all default-off; None attributes keep the
        # knobs-off hot path to a single getattr
        self.interleaver = None
        if self.conf.get(rc.SERVING_INTERLEAVE_ENABLED):
            from spark_rapids_tpu.serving.scheduler import (
                FairInterleaver)
            self.interleaver = FairInterleaver(
                self.conf.get(rc.SERVING_INTERLEAVE_QUANTUM))
        self.result_cache = None
        if self.conf.get(rc.SERVING_RESULT_CACHE_ENABLED):
            from spark_rapids_tpu.serving.reuse import ResultCache
            self.result_cache = ResultCache(self)
        self.shared_stages = None
        if self.conf.get(rc.SERVING_SHARED_STAGE_ENABLED):
            from spark_rapids_tpu.serving.reuse import SharedStageCache
            self.shared_stages = SharedStageCache(self)

    # --------------------------------------------------------------- builders --
    @classmethod
    def builder(cls) -> "SessionBuilder":
        return SessionBuilder()

    @classmethod
    def active(cls) -> "TpuSession":
        if cls._active is None:
            cls._active = TpuSession()
        return cls._active

    def set_conf(self, key: str, value) -> None:
        from spark_rapids_tpu.config import rapids_conf as rc
        old_log_dir = self.conf.get(rc.EVENT_LOG_DIR)
        self.conf = self.conf.set(key, value)
        self.overrides = TpuOverrides(self.conf, self.cache_manager)
        if self.conf.get(rc.EVENT_LOG_DIR) != old_log_dir:
            # rebuild the logger so a post-construction eventLog.dir
            # change takes effect instead of being silently ignored
            self.events.close()
            self._init_observability()

    # ------------------------------------------------------------ data inputs --
    def create_dataframe(self, data, schema: Optional[Sequence[str]] = None
                         ) -> DataFrame:
        import pandas as pd
        import pyarrow as pa
        from spark_rapids_tpu.columnar.nested import check_reserved_names
        if isinstance(data, pd.DataFrame):
            check_reserved_names(data.columns)
            batch = ColumnarBatch.from_pandas(data)
        elif isinstance(data, pa.Table):
            check_reserved_names(data.column_names)
            batch = ColumnarBatch.from_arrow(data)
        elif isinstance(data, dict):
            check_reserved_names(data.keys())
            batch = ColumnarBatch.from_pydict(data)
        elif isinstance(data, ColumnarBatch):
            batch = data
        elif isinstance(data, list) and schema is not None:
            batch = ColumnarBatch.from_pydict(
                {name: [row[i] for row in data]
                 for i, name in enumerate(schema)})
        else:
            raise TypeError(f"cannot create DataFrame from {type(data)}")
        rel = L.InMemoryRelation([batch], batch.schema)
        return DataFrame(self, rel)

    createDataFrame = create_dataframe

    def create_dataframe_from_jax(self, arrays: dict,
                                  masks: Optional[dict] = None
                                  ) -> DataFrame:
        """ML-interop ingest: build a DataFrame directly from jax device
        arrays (zero host round trip — the inverse of
        ``DataFrame.to_jax``: ``name__mask`` keys route automatically
        into validity).  ``masks``: optional {name: bool array}
        validity, merged with any inline ``__mask`` keys."""
        from spark_rapids_tpu.columnar.column import (
            Column, bucket_capacity)
        from spark_rapids_tpu.columnar.dtypes import from_numpy_dtype
        from spark_rapids_tpu.columnar.nested import check_reserved_names
        import jax.numpy as jnp
        import numpy as np
        masks = dict(masks or {})
        # round-trip support: to_jax() emits validity as '<name>__mask'
        inline = {n: a for n, a in arrays.items()
                  if n.endswith("__mask")}
        if inline:
            arrays = {n: a for n, a in arrays.items() if n not in inline}
            for n, a in inline.items():
                base = n[:-len("__mask")]
                if base not in arrays:
                    raise ValueError(
                        f"mask key {n!r} has no matching column "
                        f"{base!r}")
                masks.setdefault(base, a)
        check_reserved_names(arrays.keys())
        unknown = set(masks) - set(arrays)
        if unknown:
            raise ValueError(f"masks for unknown column(s) {unknown}")
        cols = {}
        nrows = None
        for name, arr in arrays.items():
            arr = jnp.asarray(arr)
            if arr.ndim != 1:
                raise ValueError(
                    f"column {name!r}: expected 1-D array, got "
                    f"shape {arr.shape}")
            if nrows is None:
                nrows = arr.shape[0]
            elif arr.shape[0] != nrows:
                raise ValueError(
                    f"column {name!r}: length {arr.shape[0]} != {nrows}")
            dt = from_numpy_dtype(np.dtype(arr.dtype))
            cap = bucket_capacity(nrows)
            if arr.shape[0] < cap:
                arr = jnp.concatenate(
                    [arr, jnp.zeros(cap - arr.shape[0], dtype=arr.dtype)])
            validity = masks.get(name)
            if validity is not None:
                validity = jnp.asarray(validity).astype(bool)
                if validity.shape[0] != nrows:
                    raise ValueError(
                        f"mask for {name!r}: length "
                        f"{validity.shape[0]} != {nrows}")
                validity = jnp.concatenate(
                    [validity,
                     jnp.zeros(cap - validity.shape[0], dtype=bool)])
            cols[name] = Column(dt, arr, nrows, validity=validity)
        batch = ColumnarBatch(cols, nrows or 0)
        rel = L.InMemoryRelation([batch], batch.schema)
        return DataFrame(self, rel)

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.Range(start, end, step))

    @property
    def read(self) -> DataFrameReader:
        return DataFrameReader(self)

    # ------------------------------------------------------------- SQL --
    def register_view(self, name: str, df: DataFrame) -> None:
        """Temp-view registry backing ``session.sql`` FROM clauses
        (df.createOrReplaceTempView forwards here)."""
        if not hasattr(self, "_views"):
            self._views = {}
        self._views[name.lower()] = df

    def table(self, name: str) -> DataFrame:
        views = getattr(self, "_views", {})
        key = name.lower()
        if key not in views:
            raise KeyError(
                f"unknown table or view {name!r}; register with "
                "df.createOrReplaceTempView(name)")
        return views[key]

    def sql(self, query: str) -> DataFrame:
        """Run a SQL SELECT over registered temp views (the SQL string
        entry point; parsing/lowering in spark_rapids_tpu/sql/)."""
        from spark_rapids_tpu.sql import parse, resolve
        return resolve(self, parse(query))

    def prepare(self, df: DataFrame):
        """Prepare ``df`` as a parameterized plan template
        (api/prepared.py): the literal-hoisting pass runs ONCE, and
        each ``handle.run(p0=..., ...)`` binds a fresh parameter
        vector and executes — zero re-planning, zero retracing and
        zero recompilation across literal churn, while admission,
        budgets, the recovery ladder and span tracing all still
        apply.  Requires ``spark.rapids.tpu.template.enabled``."""
        from spark_rapids_tpu.api.prepared import PreparedStatement
        return PreparedStatement(self, df)

    # --------------------------------------------------- continuous ingest --
    def incremental(self, df: DataFrame, fact: Optional[str] = None,
                    watermark_delay_ms: Optional[int] = None):
        """Stand ``df`` up as a continuous-ingest micro-batch query
        (robustness/incremental.py): the returned
        :class:`MicroBatchRunner`'s ``tick(new_paths)`` ingests
        appended files and answers over everything ingested so far,
        re-executing only the delta and merging with crash-consistent
        committed state — any mid-tick fault rolls back to the last
        committed epoch and the tick degrades to a full recompute.
        Aggregates, delta-joins (new fact batches × unchanged
        dimension state), windowed aggregation with watermark
        eviction, and provably-mergeable top-N all tick
        incrementally; anything else ticks as a full re-execution
        with lineage splice.  Every commit also yields an
        exactly-once :class:`SinkCommit` (``runner.last_sink_commit``,
        or the ``runner.on_commit`` callback).  ``fact`` designates
        the append-target scan for multi-scan plans (a fact⋈dim join
        over two file tables): pass any path already in the fact
        table's file list.  ``watermark_delay_ms`` overrides the
        session watermark conf for THIS runner.  Governed by
        ``spark.rapids.tpu.incremental.*``."""
        from spark_rapids_tpu.robustness.incremental import (
            MicroBatchRunner)
        return MicroBatchRunner(self, df, fact=fact,
                                watermark_delay_ms=watermark_delay_ms)

    def fleet(self):
        """A standing-query fleet over one append-only stream
        (serving/fleet.py): ``fleet().subscribe(df, ...)`` registers
        standing queries; each ``tick(new_paths)`` round pulls the
        delta ONCE and fans the batches out to every subscriber,
        whose epochs commit/roll back independently and whose
        committed stage work cross-splices through the epoch-aware
        shared stage cache.  Every subscriber tick returns an
        exactly-once :class:`SinkCommit`.  Governed by
        ``spark.rapids.tpu.fleet.*``."""
        from spark_rapids_tpu.serving.fleet import FleetRunner
        return FleetRunner(self)

    # --------------------------------------------------------------- planning --
    def plan(self, logical: L.LogicalPlan, overrides=None):
        from spark_rapids_tpu.config import rapids_conf as rc
        # a caller may plan through a one-off TpuOverrides (the recovery
        # driver's split-batch rung scales batch sizes this way) without
        # mutating session state under concurrent queries
        ov = overrides if overrides is not None else self.overrides
        if self.conf.get(rc.SUPPRESS_PLANNING_FAILURE):
            # sql.suppressPlanningFailure: a bug in TPU planning demotes
            # the whole query to the CPU fallback chain instead of
            # failing it (RapidsConf.scala suppressPlanningFailure)
            try:
                exec_plan = ov.apply(logical)
            except Exception as exc:
                import warnings
                # surface the root cause: the CPU chain may itself lack
                # a branch for some node, and that later error must not
                # eat the actual planner bug
                warnings.warn(
                    f"TPU planning failed ({type(exc).__name__}: {exc}); "
                    "demoting the whole query to the CPU fallback chain "
                    "(spark.rapids.sql.suppressPlanningFailure)",
                    RuntimeWarning, stacklevel=2)
                self.last_planning_error = exc
                exec_plan = self.plan_cpu_only(logical)
        else:
            exec_plan = ov.apply(logical)
        if self.conf.get(rc.PROFILE_TRACE):
            def mark(node):
                node.trace_ops = True
                for c in node.children:
                    mark(c)
            mark(exec_plan)
        return exec_plan

    def plan_cpu_only(self, logical: L.LogicalPlan):
        """Plan the whole query onto the CPU fallback chain — the
        terminal rung of the recovery ladder (robustness/driver.py)
        and the suppressPlanningFailure demotion target."""
        from spark_rapids_tpu.exec.fallback import CpuFallbackExec

        def whole_cpu(n):
            return CpuFallbackExec(n, [whole_cpu(c) for c in n.children])
        return whole_cpu(logical)


class SessionBuilder:
    def __init__(self):
        self._conf: Dict[str, str] = {}

    def config(self, key: str, value) -> "SessionBuilder":
        self._conf[key] = value
        return self

    def getOrCreate(self) -> TpuSession:
        return TpuSession(RapidsConf(self._conf))
