"""Python UDF integration: black-box UDF expression + pandas-UDF execs.

Counterpart of SURVEY.md section 2.7: `GpuUserDefinedFunction`/`RapidsUDF`
(compiled-else-blackbox dispatch), and the pandas exec family
(GpuArrowEvalPythonExec / GpuMapInPandasExec / GpuFlatMapGroupsInPandasExec).
The reference ships batches to external Python workers over Arrow IPC with
the semaphore released; this engine IS Python, so a "worker" is a host
function call on the arrow-converted batch — the device is released in the
same way (no TPU work while the UDF runs).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.ops.expressions import Expression


class PythonUDF(Expression):
    """Uncompilable UDF: runs on the host (the reference's CPU fallback)."""

    def __init__(self, fn: Callable, return_type: DataType,
                 args: Sequence[Expression], name: str = ""):
        self.fn = fn
        self.return_type = return_type
        self.children = tuple(args)
        self._name = name or getattr(fn, "__name__", "udf")

    def with_children(self, children):
        return PythonUDF(self.fn, self.return_type, children, self._name)

    def bind(self, schema):
        return self.with_children([c.bind(schema) for c in self.children])

    @property
    def dtype(self) -> DataType:
        return self.return_type

    @property
    def name(self) -> str:
        return f"{self._name}(...)"

    def emit(self, ctx):
        raise RuntimeError("PythonUDF executes on the host, not the TPU")

    def cache_key(self):
        return ("PythonUDF", id(self.fn),
                tuple(c.cache_key() for c in self.children))


class TpuMapInPandasExec(TpuExec):
    """df.mapInPandas (GpuMapInPandasExec analog)."""

    def __init__(self, fn: Callable, out_schema: Schema, child: TpuExec):
        super().__init__(child)
        self.fn = fn
        self._schema = list(out_schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        for batch in self.children[0].execute():
            for out in self.fn(iter([batch.to_pandas()])):
                if len(out):
                    yield ColumnarBatch.from_pandas(
                        out[[n for n, _ in self._schema]])


class TpuFlatMapGroupsInPandasExec(TpuExec):
    """groupBy().applyInPandas (GpuFlatMapGroupsInPandasExec analog):
    groups are split with the engine's own machinery, the user fn runs per
    group on the host."""

    def __init__(self, fn: Callable, out_schema: Schema,
                 group_names: List[str], child: TpuExec):
        super().__init__(child)
        self.fn = fn
        self.group_names = group_names
        self._schema = list(out_schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        tables = [b.to_arrow() for b in self.children[0].execute()]
        if not tables:
            return
        df = pa.concat_tables(tables).to_pandas()
        for _, group in df.groupby(self.group_names, dropna=False,
                                   sort=False):
            out = self.fn(group)
            if len(out):
                yield ColumnarBatch.from_pandas(
                    out[[n for n, _ in self._schema]].reset_index(drop=True))


class JaxUDF(Expression):
    """User-supplied JAX function as a columnar expression — the
    RapidsUDF analog (sql-plugin RapidsUDF.java:40: a UDF that provides
    its own columnar evaluation).  On TPU this is the cheapest possible
    UDF: the function traces straight into the enclosing stage's XLA
    program and fuses with everything around it.

    ``fn(*value_arrays) -> value_array`` over the raw (capacity,) jnp
    arrays; null handling is the engine's (output row null iff any input
    row null), so ``fn`` sees padded/null slots and must simply be
    elementwise-safe over them.
    """

    def __init__(self, fn: Callable, return_type: DataType,
                 args: Sequence[Expression], name: str = ""):
        self.fn = fn
        self.return_type = return_type
        self.children = tuple(args)
        self._name = name or getattr(fn, "__name__", "jax_udf")

    def with_children(self, children):
        return JaxUDF(self.fn, self.return_type, children, self._name)

    @property
    def dtype(self) -> DataType:
        return self.return_type

    @property
    def name(self) -> str:
        return f"{self._name}(...)"

    def emit(self, ctx):
        import jax.numpy as jnp
        from spark_rapids_tpu.ops.expressions import (
            ColVal, combine_validity)
        args = []
        validity = None
        for c in self.children:
            cv = c.emit(ctx)
            v = cv.values
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v, (ctx.capacity,))
            args.append(v)
            validity = combine_validity(validity, cv.validity)
        out = self.fn(*args)
        if getattr(out, "shape", None) != (ctx.capacity,):
            raise ValueError(
                f"tpu_udf {self._name} must return a ({ctx.capacity},) "
                f"array, got {getattr(out, 'shape', type(out))}")
        return ColVal(self.return_type, out, validity)

    def cache_key(self):
        return ("JaxUDF", id(self.fn),
                tuple(c.cache_key() for c in self.children))


def _find_python_udfs(expr: Expression) -> List[PythonUDF]:
    out = []
    if isinstance(expr, PythonUDF):
        out.append(expr)
    for c in expr.children:
        out.extend(_find_python_udfs(c))
    return out


def _replace_udfs(expr: Expression, mapping) -> Expression:
    if isinstance(expr, PythonUDF):
        return mapping[id(expr)]
    if not expr.children:
        return expr
    return expr.with_children(
        [_replace_udfs(c, mapping) for c in expr.children])


class TpuArrowEvalPythonExec(TpuExec):
    """Scalar Python UDF projection (GpuArrowEvalPythonExec analog,
    python/GpuArrowEvalPythonExec.scala).  Per batch: UDF *arguments*
    evaluate on device in one stage, only those columns cross to the
    host (arrow), the admission semaphore is RELEASED while the Python
    functions run (:285-289 in the reference — no device work happens),
    results come back as columns, and the remaining projection — with
    each UDF call replaced by a reference to its result column — runs on
    device.  Streaming: never materializes more than one batch."""

    def __init__(self, exprs: Sequence[Expression], child: TpuExec):
        from spark_rapids_tpu.ops.compiler import StageFn
        from spark_rapids_tpu.ops.expressions import BoundReference
        super().__init__(child)
        self.exprs = list(exprs)
        self._udfs: List[PythonUDF] = []
        seen = set()
        for e in self.exprs:
            for u in _find_python_udfs(e):
                if id(u) not in seen:
                    seen.add(id(u))
                    self._udfs.append(u)
        if not self._udfs:
            raise ValueError("no PythonUDF in projection")
        for u in self._udfs:
            for a in u.children:
                if _find_python_udfs(a):
                    # nested black-box UDFs take the whole-plan CPU path
                    # (the planner's _udf_only_failure rejects them too)
                    raise ValueError("nested PythonUDFs unsupported")
        in_dtypes = [dt for _, dt in child.schema]
        # result-column names must not collide with child columns
        prefix = "_udf"
        child_names = [n for n, _ in child.schema]
        while any(n.startswith(prefix) for n in child_names):
            prefix += "_"
        self._result_prefix = prefix
        # stage A: only the UDF argument expressions (child columns are
        # reused from the input batch, not re-materialized)
        self._args_per_udf = [list(u.children) for u in self._udfs]
        arg_exprs = [a for args in self._args_per_udf for a in args]
        self._stage_a = StageFn(arg_exprs, in_dtypes)
        # stage B: the projection over child columns + UDF result columns
        n = len(child.schema)
        mapping = {id(u): BoundReference(n + j, u.return_type,
                                         name=f"{prefix}{j}")
                   for j, u in enumerate(self._udfs)}
        self._rewritten = [_replace_udfs(e, mapping) for e in self.exprs]
        self._stage_b_dtypes = in_dtypes + [u.return_type
                                            for u in self._udfs]
        self._stage_b = StageFn(self._rewritten, self._stage_b_dtypes)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return [(e.name, e.dtype) for e in self.exprs]

    def describe(self):
        names = [u._name for u in self._udfs]
        return f"TpuArrowEvalPythonExec[{', '.join(names)}]"

    def _semaphore(self):
        from spark_rapids_tpu.api.session import TpuSession
        s = TpuSession._active
        return s.semaphore if s is not None else None

    @staticmethod
    def _num_workers() -> int:
        from spark_rapids_tpu.api.session import TpuSession
        from spark_rapids_tpu.config import rapids_conf as rc
        s = TpuSession._active
        return s.conf.get(rc.PYTHON_NUM_WORKERS) if s is not None else 0

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.columnar.column import Column
        for batch in self.child.execute():
            if batch.nrows == 0:
                continue
            arg_cols = self._stage_a(batch)
            # device->host transfer of the argument columns happens while
            # still ADMITTED; only the pure-Python function calls run with
            # the semaphore released (no device work in that window)
            arg_lists_all = [c.to_pylist() for c in arg_cols]
            sem = self._semaphore()
            if sem is not None:
                sem.release_if_held()
            outs_per_udf = []
            k = 0
            num_workers = self._num_workers()
            for u, args in zip(self._udfs, self._args_per_udf):
                arg_lists = arg_lists_all[k:k + len(args)]
                k += len(args)
                if not arg_lists:
                    outs_per_udf.append(
                        [u.fn() for _ in range(batch.nrows)])
                    continue
                out = None
                if num_workers >= 1:
                    from spark_rapids_tpu.udf import worker_pool as WP
                    # cheap declines first: never materialize the row
                    # list for a pool path that won't run
                    if WP.worth_trying(u.fn, batch.nrows, num_workers):
                        out = WP.eval_rows(u.fn, list(zip(*arg_lists)),
                                           num_workers)
                if out is None:
                    # inline path consumes the zip lazily — no
                    # materialized row-tuple list
                    out = [None if any(v is None for v in row) else
                           u.fn(*row) for row in zip(*arg_lists)]
                outs_per_udf.append(out)
            if sem is not None:
                sem.acquire_if_necessary()
            results: List[Column] = []
            for u, out in zip(self._udfs, outs_per_udf):
                if u.return_type.is_string:
                    results.append(Column.from_strings(
                        [None if v is None else str(v) for v in out],
                        capacity=batch.capacity))
                else:
                    import numpy as np
                    validity = np.array([v is not None for v in out])
                    filled = np.array(
                        [0 if v is None else v for v in out],
                        dtype=u.return_type.storage)
                    results.append(Column.from_numpy(
                        filled, dtype=u.return_type,
                        validity=None if validity.all() else validity,
                        capacity=batch.capacity))
            extended = batch
            for j, rc in enumerate(results):
                extended = extended.with_column(
                    f"{self._result_prefix}{j}", rc)
            outs = self._stage_b(extended)
            names = [e.name for e in self.exprs]
            yield ColumnarBatch(
                {nm: c for nm, c in zip(names, outs)}, batch.nrows)


def _norm_key(key) -> tuple:
    """Normalize a pandas group key: NaN members collapse to None so
    null keys from two sides cogroup together (NaN != NaN)."""
    key = key if isinstance(key, tuple) else (key,)
    return tuple(None if (isinstance(x, float) and pd.isna(x)) else x
                 for x in key)


def _child_pandas(exec_node: TpuExec) -> pd.DataFrame:
    """Concatenate every child batch into one pandas frame (empty frame
    with the right columns when the child yields nothing)."""
    import pyarrow as pa
    tables = [b.to_arrow() for b in exec_node.execute()]
    if not tables:
        from spark_rapids_tpu.columnar.batch import empty_batch
        return empty_batch(exec_node.schema).to_pandas()
    return pa.concat_tables(tables).to_pandas()


def _batch_from_pandas_schema(df: pd.DataFrame, schema: Schema
                              ) -> ColumnarBatch:
    """Build a batch with columns COERCED to the declared schema (pandas
    loses dtypes on empty/object/nullable columns)."""
    import numpy as np
    from spark_rapids_tpu.columnar.column import Column
    cols = {}
    for name, dt in schema:
        s = df[name]
        if dt.is_string:
            cols[name] = Column.from_strings(
                [None if v is None or
                 (not isinstance(v, str) and pd.isna(v)) else str(v)
                 for v in s])
        elif dt.is_array:
            cols[name] = Column.from_arrays(
                [None if v is None or
                 (not isinstance(v, (list, tuple, np.ndarray))
                  and pd.isna(v)) else list(v) for v in s], dt.element)
        else:
            valid = s.notna().to_numpy()
            filled = s.fillna(0).to_numpy()
            cols[name] = Column.from_numpy(
                np.asarray(filled).astype(dt.storage, copy=False),
                dtype=dt, validity=None if valid.all() else valid)
    return ColumnarBatch(cols, len(df))


class TpuAggregateInPandasExec(TpuExec):
    """groupBy().agg(grouped-agg pandas UDF) — GpuAggregateInPandasExec
    analog (python/GpuAggregateInPandasExec.scala, 270 LoC): groups are
    split host-side, each UDF receives its group's argument Series and
    returns one scalar per group."""

    def __init__(self, group_names: Sequence[str],
                 aggs: Sequence[tuple], child: TpuExec):
        """aggs: (out_name, fn, arg_name, return_dtype)."""
        super().__init__(child)
        self.group_names = list(group_names)
        self.aggs = list(aggs)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        child_schema = dict(self.child.schema)
        out = [(n, child_schema[n]) for n in self.group_names]
        out += [(name, dt) for name, _, _, dt in self.aggs]
        return out

    def describe(self):
        return (f"TpuAggregateInPandasExec[{[n for n, *_ in self.aggs]}]")

    def do_execute(self) -> Iterator[ColumnarBatch]:
        df = _child_pandas(self.child)
        if df.empty and self.group_names:
            return
        # keyless over empty input: Spark still applies the UDF once
        # (to empty Series) and returns one row
        sem = None
        from spark_rapids_tpu.api.session import TpuSession
        if TpuSession._active is not None:
            sem = TpuSession._active.semaphore
        if sem is not None:
            sem.release_if_held()
        rows = []
        if self.group_names:
            grouped = df.groupby(self.group_names, dropna=False,
                                 sort=False)
            for key, group in grouped:
                row = dict(zip(self.group_names, _norm_key(key)))
                for name, fn, arg, _ in self.aggs:
                    row[name] = fn(group[arg])
                rows.append(row)
        else:
            row = {}
            for name, fn, arg, _ in self.aggs:
                row[name] = fn(df[arg])
            rows.append(row)
        if sem is not None:
            sem.acquire_if_necessary()
        out = pd.DataFrame(rows, columns=[n for n, _ in self.schema])
        yield _batch_from_pandas_schema(out, self.schema)


class TpuWindowInPandasExec(TpuExec):
    """Pandas UDFs over window frames — GpuWindowInPandasExec analog
    (python/GpuWindowInPandasExec.scala, 430 LoC).  Per partition group
    the UDF sees its frame's argument Series and returns a scalar for
    the anchor row:

    * whole-partition frame: ONE call per group, broadcast (the
      reference's unbounded-window batching optimization);
    * running range frame: one call per peer group (ties share a frame
      end), broadcast across the tie run;
    * bounded rows frame: one call per row over the sliced Series.

    Original row order is restored on output (Spark windows are a
    projection, not a sort)."""

    def __init__(self, calls: Sequence[tuple], child: TpuExec):
        super().__init__(child)
        self.calls = list(calls)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return list(self.child.schema) + \
            [(name, dt) for name, _, _, dt, _ in self.calls]

    def describe(self):
        return f"TpuWindowInPandasExec[{[n for n, *_ in self.calls]}]"

    @staticmethod
    def _eval_one_group(g: pd.DataFrame, fn, arg: str, orders, frame
                        ) -> pd.Series:
        if orders:
            from spark_rapids_tpu.utils.hostsort import sort_per_key_nulls
            g = sort_per_key_nulls(
                g, [n for n, _, _ in orders],
                [not d for _, d, _ in orders],
                [nf for _, _, nf in orders], reset_index=False)
        s = g[arg].reset_index(drop=True)
        n = len(s)
        out = np.empty(n, dtype=object)
        whole = frame.lo is None and frame.hi is None
        if whole:
            out[:] = fn(s)
        elif frame.kind == "range":
            # running range frame: peers (tied order keys) share the
            # frame end — evaluate once per tie run
            keys = g[[n for n, _, _ in orders]].reset_index(drop=True)
            # NaN != NaN would split tied null keys into separate peer
            # runs; Spark treats nulls as peers of each other
            changed = keys.ne(keys.shift()) & \
                ~(keys.isna() & keys.shift().isna())
            run_id = changed.any(axis=1).cumsum()
            start = 0
            for _, idx in keys.groupby(run_id, sort=False).groups.items():
                e = idx[-1] + 1
                out[start:e] = fn(s.iloc[:e])
                start = e
        else:
            lo, hi = frame.lo, frame.hi
            for i in range(n):
                a = 0 if lo is None else max(0, i + lo)
                # clamp below at 0: a negative upper bound near the
                # partition start means an EMPTY frame, not a wrapped
                # negative iloc slice
                b = n if hi is None else min(n, max(0, i + hi + 1))
                out[i] = fn(s.iloc[a:b])
        res = pd.Series(out, index=g.index)
        return res

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.api.session import TpuSession
        df = _child_pandas(self.child)
        if df.empty:
            from spark_rapids_tpu.columnar.batch import empty_batch
            yield empty_batch(self.schema)
            return
        sem = None
        if TpuSession._active is not None:
            sem = TpuSession._active.semaphore
        if sem is not None:
            sem.release_if_held()
        for out_name, fn, arg, dt, (parts, orders, frame) in self.calls:
            if parts:
                pieces = [
                    self._eval_one_group(g, fn, arg, orders, frame)
                    for _, g in df.groupby(parts, dropna=False,
                                           sort=False)]
                df[out_name] = pd.concat(pieces).reindex(df.index)
            else:
                df[out_name] = self._eval_one_group(
                    df, fn, arg, orders, frame).reindex(df.index)
        if sem is not None:
            sem.acquire_if_necessary()
        yield _batch_from_pandas_schema(df[[n for n, _ in self.schema]],
                                        self.schema)


class TpuFlatMapCoGroupsInPandasExec(TpuExec):
    """cogroup().applyInPandas — GpuFlatMapCoGroupsInPandasExec analog
    (142 LoC; disabled by default in the reference,
    GpuOverrides.scala:3205): both sides grouped host-side, the user fn
    gets (left_group, right_group) per key in the union of keys."""

    def __init__(self, fn: Callable, out_schema: Schema,
                 left_names: Sequence[str], right_names: Sequence[str],
                 left: TpuExec, right: TpuExec):
        super().__init__(left, right)
        self.fn = fn
        self._schema = list(out_schema)
        self.left_names = list(left_names)
        self.right_names = list(right_names)

    @property
    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        return "TpuFlatMapCoGroupsInPandasExec"

    def do_execute(self) -> Iterator[ColumnarBatch]:
        left = _child_pandas(self.children[0])
        right = _child_pandas(self.children[1])
        lgroups = {_norm_key(k): g
                   for k, g in left.groupby(self.left_names, dropna=False,
                                            sort=False)}
        rgroups = {_norm_key(k): g
                   for k, g in right.groupby(self.right_names,
                                             dropna=False, sort=False)}
        keys = list(lgroups)
        keys += [k for k in rgroups if k not in lgroups]
        outs = []
        for k in keys:
            lg = lgroups.get(k, left.iloc[0:0])
            rg = rgroups.get(k, right.iloc[0:0])
            res = self.fn(lg.reset_index(drop=True),
                          rg.reset_index(drop=True))
            if len(res):
                outs.append(res[[n for n, _ in self._schema]])
        if not outs:
            from spark_rapids_tpu.columnar.batch import empty_batch
            yield empty_batch(self._schema)
            return
        yield _batch_from_pandas_schema(
            pd.concat(outs, ignore_index=True), self._schema)
