"""Python UDF integration: black-box UDF expression + pandas-UDF execs.

Counterpart of SURVEY.md section 2.7: `GpuUserDefinedFunction`/`RapidsUDF`
(compiled-else-blackbox dispatch), and the pandas exec family
(GpuArrowEvalPythonExec / GpuMapInPandasExec / GpuFlatMapGroupsInPandasExec).
The reference ships batches to external Python workers over Arrow IPC with
the semaphore released; this engine IS Python, so a "worker" is a host
function call on the arrow-converted batch — the device is released in the
same way (no TPU work while the UDF runs).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import pandas as pd

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.ops.expressions import Expression


class PythonUDF(Expression):
    """Uncompilable UDF: runs on the host (the reference's CPU fallback)."""

    def __init__(self, fn: Callable, return_type: DataType,
                 args: Sequence[Expression], name: str = ""):
        self.fn = fn
        self.return_type = return_type
        self.children = tuple(args)
        self._name = name or getattr(fn, "__name__", "udf")

    def with_children(self, children):
        return PythonUDF(self.fn, self.return_type, children, self._name)

    def bind(self, schema):
        return self.with_children([c.bind(schema) for c in self.children])

    @property
    def dtype(self) -> DataType:
        return self.return_type

    @property
    def name(self) -> str:
        return f"{self._name}(...)"

    def emit(self, ctx):
        raise RuntimeError("PythonUDF executes on the host, not the TPU")

    def cache_key(self):
        return ("PythonUDF", id(self.fn),
                tuple(c.cache_key() for c in self.children))


class TpuMapInPandasExec(TpuExec):
    """df.mapInPandas (GpuMapInPandasExec analog)."""

    def __init__(self, fn: Callable, out_schema: Schema, child: TpuExec):
        super().__init__(child)
        self.fn = fn
        self._schema = list(out_schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        for batch in self.children[0].execute():
            for out in self.fn(iter([batch.to_pandas()])):
                if len(out):
                    yield ColumnarBatch.from_pandas(
                        out[[n for n, _ in self._schema]])


class TpuFlatMapGroupsInPandasExec(TpuExec):
    """groupBy().applyInPandas (GpuFlatMapGroupsInPandasExec analog):
    groups are split with the engine's own machinery, the user fn runs per
    group on the host."""

    def __init__(self, fn: Callable, out_schema: Schema,
                 group_names: List[str], child: TpuExec):
        super().__init__(child)
        self.fn = fn
        self.group_names = group_names
        self._schema = list(out_schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        tables = [b.to_arrow() for b in self.children[0].execute()]
        if not tables:
            return
        df = pa.concat_tables(tables).to_pandas()
        for _, group in df.groupby(self.group_names, dropna=False,
                                   sort=False):
            out = self.fn(group)
            if len(out):
                yield ColumnarBatch.from_pandas(
                    out[[n for n, _ in self._schema]].reset_index(drop=True))
