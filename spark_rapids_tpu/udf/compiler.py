"""UDF compiler: CPython bytecode -> expression trees.

Counterpart of the reference's ``udf-compiler`` (SURVEY.md section 2.7:
LambdaReflection -> CFG -> abstract interpretation of JVM opcodes ->
Catalyst; ``Instruction.scala:198-928``), retargeted at CPython bytecode:
``dis`` supplies instructions, a symbolic evaluator executes them over a
stack/locals of *expression trees*, and conditional jumps fork the
evaluation — the two arms rejoin as an ``If`` expression (equivalent to the
reference's CFG condition folding for loop-free lambdas).  On any
unsupported opcode or call, compilation returns None and the UDF runs as a
host black box (the reference falls back to the original UDF the same way,
``Plugin.scala:39-89``).

Supported surface mirrors the reference's opcode tables: arithmetic,
comparison and boolean logic, conditional expressions, math builtins
(abs/min/max and ``math.*``), and common ``str`` methods (upper/lower/
strip/startswith/endswith/...).
"""

from __future__ import annotations

import dis
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from spark_rapids_tpu.ops import arithmetic as A
from spark_rapids_tpu.ops import predicates as P
from spark_rapids_tpu.ops import stringops as S
from spark_rapids_tpu.ops.expressions import Expression, Literal


class CompileError(Exception):
    pass


_MAX_INSTRUCTIONS = 4000  # path-explosion guard

# dis BINARY_OP argrepr -> builder
_BINARY_OPS: Dict[str, Callable] = {
    "+": A.Add, "-": A.Subtract, "*": A.Multiply, "/": A.Divide,
    "//": A.IntegralDivide, "%": A.Remainder, "**": A.Pow,
    "&": A.BitwiseAnd, "|": A.BitwiseOr, "^": A.BitwiseXor,
    "<<": A.ShiftLeft, ">>": A.ShiftRight,
}

_COMPARE_OPS: Dict[str, Callable] = {
    "<": P.LessThan, "<=": P.LessThanOrEqual, ">": P.GreaterThan,
    ">=": P.GreaterThanOrEqual, "==": P.EqualTo,
}

_MATH_FNS: Dict[str, Callable] = {
    "sqrt": A.Sqrt, "exp": A.Exp, "log": A.Log, "log2": A.Log2,
    "log10": A.Log10, "log1p": A.Log1p, "sin": A.Sin, "cos": A.Cos,
    "tan": A.Tan, "asin": A.Asin, "acos": A.Acos, "atan": A.Atan,
    "sinh": A.Sinh, "cosh": A.Cosh, "tanh": A.Tanh, "floor": A.Floor,
    "ceil": A.Ceil, "degrees": A.ToDegrees, "radians": A.ToRadians,
    "fabs": A.Abs,
}


def _expr_or_lit(v) -> Expression:
    if isinstance(v, Expression):
        return v
    return Literal(v)


class _Evaluator:
    def __init__(self, fn: Callable, args: Sequence[Expression]):
        self.code = fn.__code__
        self.fn = fn
        if self.code.co_argcount != len(args):
            raise CompileError("arity mismatch")
        self.instructions = list(dis.get_instructions(fn))
        self.by_offset = {ins.offset: idx
                          for idx, ins in enumerate(self.instructions)}
        self.globals = fn.__globals__
        self.closure = {}
        if fn.__closure__:
            for name, cell in zip(self.code.co_freevars, fn.__closure__):
                self.closure[name] = cell.cell_contents
        self.init_locals: Dict[str, Any] = {
            name: arg for name, arg in zip(self.code.co_varnames, args)}
        self.budget = _MAX_INSTRUCTIONS

    def run(self) -> Expression:
        out = self._exec(0, [], dict(self.init_locals))
        return _expr_or_lit(out)

    # ---- the symbolic interpreter -------------------------------------------
    def _exec(self, idx: int, stack: List, local_vars: Dict[str, Any]):
        stack = list(stack)
        local_vars = dict(local_vars)
        while True:
            self.budget -= 1
            if self.budget <= 0:
                raise CompileError("instruction budget exceeded (loop?)")
            if idx >= len(self.instructions):
                raise CompileError("fell off end of bytecode")
            ins = self.instructions[idx]
            op = ins.opname

            if op in ("RESUME", "NOP", "PRECALL", "CACHE", "PUSH_NULL",
                      "COPY_FREE_VARS", "MAKE_CELL", "NOT_TAKEN"):
                pass
            elif op == "LOAD_FAST" or op == "LOAD_FAST_CHECK" or \
                    op == "LOAD_FAST_BORROW":
                if ins.argval not in local_vars:
                    raise CompileError(f"unbound local {ins.argval}")
                stack.append(local_vars[ins.argval])
            elif op == "LOAD_FAST_LOAD_FAST" or \
                    op == "LOAD_FAST_BORROW_LOAD_FAST_BORROW":
                a, b = ins.argval
                stack.append(local_vars[a])
                stack.append(local_vars[b])
            elif op == "STORE_FAST":
                local_vars[ins.argval] = stack.pop()
            elif op == "STORE_FAST_STORE_FAST":
                a, b = ins.argval
                local_vars[a] = stack.pop()
                local_vars[b] = stack.pop()
            elif op == "LOAD_CONST" or op == "LOAD_SMALL_INT":
                stack.append(ins.argval)
            elif op == "LOAD_GLOBAL":
                name = ins.argval
                if name in self.globals:
                    stack.append(self.globals[name])
                elif name in __builtins__ if isinstance(__builtins__, dict) \
                        else hasattr(__builtins__, name):
                    b = __builtins__[name] if isinstance(__builtins__, dict) \
                        else getattr(__builtins__, name)
                    stack.append(b)
                else:
                    raise CompileError(f"unknown global {name}")
            elif op == "LOAD_DEREF":
                if ins.argval not in self.closure:
                    raise CompileError(f"unknown closure var {ins.argval}")
                stack.append(self.closure[ins.argval])
            elif op == "LOAD_ATTR" or op == "LOAD_METHOD":
                obj = stack.pop()
                stack.append(_Attr(obj, ins.argval))
            elif op == "BINARY_OP":
                r, l = stack.pop(), stack.pop()
                sym = ins.argrepr.rstrip("=")
                if sym not in _BINARY_OPS:
                    raise CompileError(f"binary op {ins.argrepr}")
                if isinstance(l, Expression) or isinstance(r, Expression):
                    if sym == "+" and _is_stringy(l, r):
                        stack.append(S.ConcatStrings(_expr_or_lit(l),
                                                     _expr_or_lit(r)))
                    else:
                        stack.append(_BINARY_OPS[sym](_expr_or_lit(l),
                                                      _expr_or_lit(r)))
                else:
                    stack.append(_const_binop(sym, l, r))
            elif op == "UNARY_NEGATIVE":
                v = stack.pop()
                stack.append(A.UnaryMinus(_expr_or_lit(v))
                             if isinstance(v, Expression) else -v)
            elif op == "UNARY_NOT":
                v = stack.pop()
                stack.append(P.Not(_expr_or_lit(v))
                             if isinstance(v, Expression) else (not v))
            elif op == "TO_BOOL":
                pass  # operand already usable as a predicate
            elif op == "COMPARE_OP":
                r, l = stack.pop(), stack.pop()
                sym = ins.argrepr.strip().split()[0]
                if sym == "!=":
                    e = P.Not(P.EqualTo(_expr_or_lit(l), _expr_or_lit(r)))
                elif sym in _COMPARE_OPS:
                    e = _COMPARE_OPS[sym](_expr_or_lit(l), _expr_or_lit(r))
                else:
                    raise CompileError(f"compare {ins.argrepr}")
                stack.append(e)
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE"):
                cond = stack.pop()
                target = self.by_offset[ins.argval]
                if not isinstance(cond, Expression):
                    taken = (not cond) if op == "POP_JUMP_IF_FALSE" else \
                        bool(cond)
                    idx = target if taken else idx + 1
                    continue
                if op == "POP_JUMP_IF_TRUE":
                    cond = P.Not(cond)
                t_val = self._exec(idx + 1, stack, local_vars)
                f_val = self._exec(target, stack, local_vars)
                return P.If(cond, _expr_or_lit(t_val), _expr_or_lit(f_val))
            elif op in ("JUMP_FORWARD", "JUMP_ABSOLUTE"):
                idx = self.by_offset[ins.argval]
                continue
            elif op == "JUMP_BACKWARD":
                raise CompileError("loops are not compilable")
            elif op == "POP_TOP":
                stack.pop()
            elif op == "COPY":
                stack.append(stack[-ins.arg])
            elif op == "SWAP":
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
            elif op == "RETURN_VALUE":
                return stack.pop()
            elif op == "RETURN_CONST":
                return ins.argval
            elif op == "CALL" or op == "CALL_FUNCTION" or \
                    op == "CALL_METHOD":
                argc = ins.arg
                args = [stack.pop() for _ in range(argc)][::-1]
                callee = stack.pop()
                # 3.11/3.12 push NULL under callee for non-method calls
                if stack and stack[-1] is None:
                    stack.pop()
                stack.append(self._call(callee, args))
            elif op == "KW_NAMES":
                raise CompileError("keyword arguments not supported")
            else:
                raise CompileError(f"unsupported opcode {op}")
            idx += 1

    # ---- known calls ---------------------------------------------------------
    def _call(self, callee, args):
        if isinstance(callee, _Attr):
            return self._method_call(callee.obj, callee.name, args)
        if callee is abs:
            return A.Abs(_expr_or_lit(args[0])) \
                if isinstance(args[0], Expression) else abs(args[0])
        if callee is min and len(args) == 2:
            return P.Least(*[_expr_or_lit(a) for a in args])
        if callee is max and len(args) == 2:
            return P.Greatest(*[_expr_or_lit(a) for a in args])
        if callee is len:
            return S.Length(_expr_or_lit(args[0]))
        if callee is round:
            scale = args[1] if len(args) > 1 else 0
            return A.Round(_expr_or_lit(args[0]), scale)
        if callee is float:
            from spark_rapids_tpu.columnar import dtypes as dts
            from spark_rapids_tpu.ops.cast import Cast
            return Cast(_expr_or_lit(args[0]), dts.FLOAT64)
        if callee is int:
            from spark_rapids_tpu.columnar import dtypes as dts
            from spark_rapids_tpu.ops.cast import Cast
            return Cast(_expr_or_lit(args[0]), dts.INT64)
        raise CompileError(f"call to {callee!r} not compilable")

    def _method_call(self, obj, name, args):
        if obj is math or (hasattr(obj, "__name__") and
                           getattr(obj, "__name__", "") == "math"):
            if name == "pow":
                return A.Pow(_expr_or_lit(args[0]), _expr_or_lit(args[1]))
            if name in _MATH_FNS:
                return _MATH_FNS[name](_expr_or_lit(args[0]))
            raise CompileError(f"math.{name} not compilable")
        e = _expr_or_lit(obj) if isinstance(obj, (Expression, str)) else None
        if e is None:
            raise CompileError(f"method {name} on {obj!r}")
        str_methods = {
            "upper": lambda: S.Upper(e),
            "lower": lambda: S.Lower(e),
            "strip": lambda: S.StringTrim(e),
            "lstrip": lambda: S.StringTrimLeft(e),
            "rstrip": lambda: S.StringTrimRight(e),
            "title": lambda: S.InitCap(e),
        }
        if name in str_methods and not args:
            return str_methods[name]()
        if name == "startswith" and isinstance(args[0], str):
            return S.StartsWith(e, args[0])
        if name == "endswith" and isinstance(args[0], str):
            return S.EndsWith(e, args[0])
        if name == "__contains__" and isinstance(args[0], str):
            return S.Contains(e, args[0])
        raise CompileError(f"str.{name} not compilable")


class _Attr:
    def __init__(self, obj, name):
        self.obj = obj
        self.name = name


def _is_stringy(l, r) -> bool:
    for v in (l, r):
        if isinstance(v, str):
            return True
        if isinstance(v, Expression):
            try:
                if v.dtype.is_string:
                    return True
            except Exception:
                pass
    return False


def _const_binop(sym, l, r):
    import operator
    return {"+": operator.add, "-": operator.sub, "*": operator.mul,
            "/": operator.truediv, "//": operator.floordiv,
            "%": operator.mod, "**": operator.pow, "&": operator.and_,
            "|": operator.or_, "^": operator.xor,
            "<<": operator.lshift, ">>": operator.rshift}[sym](l, r)


def compile_udf(fn: Callable,
                args: Sequence[Expression]) -> Optional[Expression]:
    """Compile fn(args) to an expression tree, or None if not compilable."""
    try:
        return _Evaluator(fn, list(args)).run()
    except CompileError:
        return None
    except Exception:
        return None
