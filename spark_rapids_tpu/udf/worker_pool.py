"""Persistent Python worker processes for UDF evaluation.

Counterpart of the reference's Python worker scheduling (its pandas UDF
execs reuse Spark's daemon-forked Python workers and gate them with
``concurrentPythonWorkers`` — python/GpuArrowEvalPythonExec.scala).
This engine is single-process, so black-box Python UDFs are GIL-bound:
the pool spreads row chunks across ``spawn``-started worker processes
(spawn, not fork — the parent holds initialized XLA state that must not
be forked) and reuses them across batches to amortize startup.

Off by default (``spark.rapids.sql.python.numWorkers = 0``): for cheap
UDFs the pickle/IPC overhead exceeds the GIL win.  Functions that
cannot pickle (lambdas, closures over open handles) fall back to inline
evaluation transparently.
"""

from __future__ import annotations

import pickle
from typing import List, Optional

_pool = None
_pool_size = 0


class WorkerUnpicklable(Exception):
    """The worker could not reconstruct the function (e.g. pickled by
    reference to a __main__ the spawn-started worker cannot import).
    Raised before any row evaluates, so inline fallback cannot
    duplicate side effects."""


def _eval_chunk(fn_bytes: bytes, rows: list) -> list:
    """Worker-side: unpickle the function once per chunk, evaluate
    row-wise with Spark null semantics (any NULL argument -> NULL)."""
    try:
        fn = pickle.loads(fn_bytes)
    except Exception as e:
        raise WorkerUnpicklable(repr(e))
    return [None if any(v is None for v in r) else fn(*r) for r in rows]


def get_pool(num_workers: int):
    """Process-wide pool, resized when the conf changes.  1 is a valid
    size (one reused isolated worker); 0 disables the pool."""
    global _pool, _pool_size
    if num_workers <= 0:
        return None
    if _pool is not None and _pool_size == num_workers:
        return _pool
    if _pool is not None:
        _pool.shutdown(wait=False, cancel_futures=True)
    import multiprocessing
    from concurrent.futures import ProcessPoolExecutor
    _pool = ProcessPoolExecutor(
        max_workers=num_workers,
        mp_context=multiprocessing.get_context("spawn"))
    _pool_size = num_workers
    return _pool


def shutdown_pool() -> None:
    global _pool, _pool_size
    if _pool is not None:
        _pool.shutdown(wait=False, cancel_futures=True)
        _pool = None
        _pool_size = 0


import weakref

# functions that failed to pickle; weak so a collected function can
# never alias a new one's address (id-reuse) and the set self-prunes
_unpicklable_fns: "weakref.WeakSet" = weakref.WeakSet()


def worth_trying(fn, nrows: int, num_workers: int,
                 min_rows_per_worker: int = 256) -> bool:
    """Cheap pre-checks so callers can avoid materializing row tuples
    for a pool path that would immediately decline."""
    if num_workers <= 0 or nrows < 2 * min_rows_per_worker:
        return False
    try:
        if fn in _unpicklable_fns:
            return False
    except TypeError:
        pass  # unhashable callables just retry the pickle probe
    return True


def _record_pool_degradation(error: str) -> None:
    """Surface the pool->inline degradation on the unified recovery
    trail (robustness/driver.py) — it is a recovery action even though
    no exception ever reaches the query driver."""
    from spark_rapids_tpu.robustness.driver import record_degradation
    try:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    except ImportError:  # torn-down interpreter only
        session = None
    record_degradation(session, "udf_worker", "inline_fallback", error)


def eval_rows(fn, rows: List[tuple], num_workers: int,
              min_rows_per_worker: int = 256) -> Optional[list]:
    """Evaluate ``fn`` over rows on the worker pool; None when the pool
    path does not apply (disabled, too few rows, unpicklable fn) and
    the caller should evaluate inline."""
    if not worth_trying(fn, len(rows), num_workers, min_rows_per_worker):
        return None
    # "udf.worker" models the pool dying before any row evaluates (the
    # spawn-broken / worker-killed class): degrade to inline evaluation
    # exactly like the real BrokenProcessPool handler below
    from spark_rapids_tpu.robustness.faults import InjectedWorkerFault
    from spark_rapids_tpu.robustness.inject import fire
    try:
        fire("udf.worker")
    except InjectedWorkerFault as e:
        shutdown_pool()
        _record_pool_degradation(f"{type(e).__name__}: {e}")
        return None
    try:
        fn_bytes = pickle.dumps(fn)
    except Exception:
        try:
            _unpicklable_fns.add(fn)
        except TypeError:
            pass
        return None
    pool = get_pool(num_workers)
    if pool is None:
        return None
    chunk = max(min_rows_per_worker, -(-len(rows) // num_workers))
    futures = [pool.submit(_eval_chunk, fn_bytes, rows[i:i + chunk])
               for i in range(0, len(rows), chunk)]
    import concurrent.futures as cf
    from concurrent.futures.process import BrokenProcessPool
    from spark_rapids_tpu.robustness import watchdog

    def _result(f):
        # poll instead of a bare f.result(): the result wait is the
        # driving thread's cancellation checkpoint, so a worker
        # process stuck in user code trips the deadline and the
        # TimeoutFault is actually deliverable HERE (a blocked
        # result() could never observe it).  cf.wait (not
        # result(timeout=...)) so a UDF that itself raised
        # TimeoutError is re-raised, not mistaken for "still running"
        # (on 3.11+ cf.TimeoutError IS the builtin TimeoutError)
        while not f.done():
            watchdog.checkpoint()
            cf.wait([f], timeout=0.05)
        return f.result()

    try:
        # "udf.worker" section: a stuck worker process (or a dead pool
        # that never errors) trips the deadline; the TimeoutFault
        # re-drives the query, whose retry re-evaluates — rows are
        # pure per the UDF contract used by the pool path.  Heartbeat
        # per completed chunk: the deadline measures silence, so a
        # merely SLOW multi-chunk stage that keeps finishing futures
        # never trips while a wedged worker does.
        with watchdog.section("udf.worker") as sect:
            out: list = []
            for f in futures:
                out.extend(_result(f))
                if sect is not None:
                    sect.beat()
        return out
    except WorkerUnpicklable:
        # pickled fine by reference but the worker cannot reconstruct
        # it (REPL __main__ fn); no row ran, inline fallback is safe
        try:
            _unpicklable_fns.add(fn)
        except TypeError:
            pass
        return None
    except BrokenProcessPool as e:
        # pool infrastructure failure (worker killed, spawn broken)
        # degrades to inline evaluation rather than failing the query
        shutdown_pool()
        _record_pool_degradation(f"{type(e).__name__}: {e}")
        return None
    # any other (user UDF) exception propagates — re-running inline
    # would duplicate side effects the completed rows already had
