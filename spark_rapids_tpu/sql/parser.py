"""Recursive-descent SQL parser: SELECT statements -> AST dataclasses.

Hand-rolled (no parser library in the image) with a conventional
precedence ladder: OR < AND < NOT < comparison/IS/IN/BETWEEN/LIKE <
additive < multiplicative < unary < primary.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------- AST --


@dataclasses.dataclass
class Lit:
    value: object
    kind: str = "plain"  # plain | date | timestamp


@dataclasses.dataclass
class ColRef:
    parts: Tuple[str, ...]  # ("t", "c") or ("c",)


@dataclasses.dataclass
class Star:
    table: Optional[str] = None


@dataclasses.dataclass
class BinOp:
    op: str
    left: object
    right: object


@dataclasses.dataclass
class UnOp:
    op: str  # '-', 'NOT'
    child: object


@dataclasses.dataclass
class IsNull:
    child: object
    negated: bool


@dataclasses.dataclass
class Between:
    child: object
    lo: object
    hi: object
    negated: bool


@dataclasses.dataclass
class InList:
    child: object
    items: List[object]
    negated: bool


@dataclasses.dataclass
class InSubquery:
    child: object
    query: "SelectStmt"
    negated: bool


@dataclasses.dataclass
class ScalarSubquery:
    query: "SelectStmt"


@dataclasses.dataclass
class LikeOp:
    child: object
    pattern: str
    negated: bool


@dataclasses.dataclass
class FuncCall:
    name: str
    args: List[object]
    distinct: bool = False
    window: Optional["WindowDef"] = None


@dataclasses.dataclass
class WindowDef:
    partition_by: List[object]
    order_by: List["OrderItem"]
    rows: Optional[Tuple[Optional[int], Optional[int]]] = None


@dataclasses.dataclass
class CaseExpr:
    whens: List[Tuple[object, object]]
    else_: Optional[object]


@dataclasses.dataclass
class CastExpr:
    child: object
    type_name: str


@dataclasses.dataclass
class Projection:
    expr: object
    alias: Optional[str]


@dataclasses.dataclass
class OrderItem:
    expr: object
    desc: bool = False
    nulls_first: Optional[bool] = None


@dataclasses.dataclass
class TableRef:
    name: str
    alias: Optional[str]


@dataclasses.dataclass
class SubqueryRef:
    query: "SelectStmt"
    alias: str


@dataclasses.dataclass
class JoinClause:
    how: str  # inner/left/right/full/semi/anti/cross
    right: object  # TableRef | SubqueryRef
    on: Optional[object] = None
    using: Optional[List[str]] = None


@dataclasses.dataclass
class SelectStmt:
    projections: List[Projection]
    from_: Optional[object]  # TableRef | SubqueryRef | None
    joins: List[JoinClause]
    where: Optional[object]
    group_by: List[object]
    having: Optional[object]
    order_by: List[OrderItem]
    limit: Optional[int]
    distinct: bool
    union_all: Optional["SelectStmt"] = None
    # ROLLUP/CUBE/GROUPING SETS: per output replica, the indices into
    # group_by that stay live (None = plain GROUP BY)
    group_sets: Optional[List[List[int]]] = None


# -------------------------------------------------------------------- lexer --

_TOKEN_RE = re.compile(r"""
    \s+
  | (?P<num>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][-+]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*|`[^`]+`)
  | (?P<op><>|!=|>=|<=|=|<|>|\|\||[-+*/%(),.])
""", re.VERBOSE)

KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having",
    "order", "limit", "as", "and", "or", "not", "in", "is", "null",
    "like", "between", "case", "when", "then", "else", "end", "cast",
    "join", "inner", "left", "right", "full", "outer", "semi", "anti",
    "cross", "on", "using", "union", "all", "true", "false", "asc",
    "desc", "nulls", "first", "last", "date", "timestamp", "interval",
    "over", "partition", "rows", "unbounded", "preceding", "following",
    "current", "row", "with",
}


class Token:
    __slots__ = ("kind", "value")

    def __init__(self, kind, value):
        self.kind = kind  # num | str | ident | kw | op | eof
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise ValueError(
                f"SQL syntax error at {text[pos:pos + 20]!r}")
        pos = m.end()
        if m.lastgroup is None:
            continue
        v = m.group(m.lastgroup)
        if m.lastgroup == "num":
            out.append(Token("num", v))
        elif m.lastgroup == "str":
            out.append(Token("str", v[1:-1].replace("''", "'")))
        elif m.lastgroup == "ident":
            if v.startswith("`"):
                out.append(Token("ident", v[1:-1]))
            elif v.lower() in KEYWORDS:
                out.append(Token("kw", v.lower()))
            else:
                out.append(Token("ident", v))
        else:
            out.append(Token("op", v))
    out.append(Token("eof", ""))
    return out


# ------------------------------------------------------------------- parser --


class Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    # -- token helpers -----------------------------------------------------
    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def advance(self) -> Token:
        t = self.cur
        self.i += 1
        return t

    def at_kw(self, *kws) -> bool:
        return self.cur.kind == "kw" and self.cur.value in kws

    def eat_kw(self, *kws) -> bool:
        if self.at_kw(*kws):
            self.advance()
            return True
        return False

    def expect_kw(self, kw):
        if not self.eat_kw(kw):
            raise ValueError(f"expected {kw.upper()}, got {self.cur}")

    def at_op(self, *ops) -> bool:
        return self.cur.kind == "op" and self.cur.value in ops

    def eat_op(self, *ops) -> bool:
        if self.at_op(*ops):
            self.advance()
            return True
        return False

    def expect_op(self, op):
        if not self.eat_op(op):
            raise ValueError(f"expected {op!r}, got {self.cur}")

    def ident(self) -> str:
        if self.cur.kind == "ident":
            return self.advance().value
        # non-reserved keywords usable as identifiers in practice
        if self.cur.kind == "kw" and self.cur.value in (
                "date", "timestamp", "first", "last", "left", "right",
                "row", "rows"):
            return self.advance().value
        raise ValueError(f"expected identifier, got {self.cur}")

    # -- statements --------------------------------------------------------
    def parse(self) -> SelectStmt:
        ctes: Dict[str, SelectStmt] = {}
        if self.eat_kw("with"):
            # non-recursive CTEs, substituted as derived tables at parse
            # time (each reference gets its own deep copy: the resolver
            # mutates ASTs in place when lifting aggregates)
            while True:
                name = self.ident().lower()
                self.expect_kw("as")
                self.expect_op("(")
                q = self.select_stmt()
                self.expect_op(")")
                if name in ctes:
                    raise ValueError(f"duplicate CTE name {name!r}")
                _substitute_ctes(q, ctes)  # earlier CTEs visible here
                ctes[name] = q
                if not self.eat_op(","):
                    break
        stmt = self.select_stmt()
        if self.cur.kind != "eof":
            raise ValueError(f"unexpected trailing input at {self.cur}")
        if ctes:
            _substitute_ctes(stmt, ctes)
        return stmt

    def select_stmt(self) -> SelectStmt:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        projections = [self.projection()]
        while self.eat_op(","):
            projections.append(self.projection())
        from_ = None
        joins: List[JoinClause] = []
        if self.eat_kw("from"):
            from_ = self.from_item()
            while True:
                j = self.join_clause()
                if j is None:
                    break
                joins.append(j)
        where = self.expr() if self.eat_kw("where") else None
        group_by: List[object] = []
        group_sets: Optional[List[List[int]]] = None
        if self.eat_kw("group"):
            self.expect_kw("by")
            # ROLLUP/CUBE/GROUPING are contextual (not reserved): they
            # only take effect as the head of the GROUP BY list followed
            # by "(", so columns named rollup/cube/grouping still work
            if (self.cur.kind == "ident"
                    and self.cur.value.lower() in ("rollup", "cube")
                    and self.i + 1 < len(self.toks)
                    and self.toks[self.i + 1].kind == "op"
                    and self.toks[self.i + 1].value == "("):
                kind = self.advance().value.lower()
                self.expect_op("(")
                group_by.append(self.expr())
                while self.eat_op(","):
                    group_by.append(self.expr())
                self.expect_op(")")
                n = len(group_by)
                if kind == "rollup":
                    group_sets = [list(range(k))
                                  for k in range(n, -1, -1)]
                else:
                    group_sets = [
                        [i for i in range(n) if m & (1 << (n - 1 - i))]
                        for m in range((1 << n) - 1, -1, -1)]
            elif (self.cur.kind == "ident"
                  and self.cur.value.lower() == "grouping"
                  and self.i + 1 < len(self.toks)
                  and self.toks[self.i + 1].kind == "ident"
                  and self.toks[self.i + 1].value.lower() == "sets"):
                self.advance()  # GROUPING (contextual, stays a valid
                self.advance()  # function name elsewhere) + SETS
                self.expect_op("(")
                group_sets = []
                key_reprs: List[str] = []
                while True:
                    self.expect_op("(")
                    one: List[int] = []
                    if not self.at_op(")"):
                        while True:
                            e = self.expr()
                            r = repr(e)
                            if r not in key_reprs:
                                key_reprs.append(r)
                                group_by.append(e)
                            one.append(key_reprs.index(r))
                            if not self.eat_op(","):
                                break
                    self.expect_op(")")
                    group_sets.append(one)
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
            else:
                group_by.append(self.expr())
                while self.eat_op(","):
                    group_by.append(self.expr())
        having = self.expr() if self.eat_kw("having") else None
        order_by: List[OrderItem] = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by.append(self.order_item())
            while self.eat_op(","):
                order_by.append(self.order_item())
        limit = None
        if self.eat_kw("limit"):
            limit = int(self.advance().value)
        union_all = None
        if self.eat_kw("union"):
            self.expect_kw("all")
            union_all = self.select_stmt()
        return SelectStmt(projections, from_, joins, where, group_by,
                          having, order_by, limit, distinct, union_all,
                          group_sets=group_sets)

    def projection(self) -> Projection:
        if self.at_op("*"):
            self.advance()
            return Projection(Star(), None)
        e = self.expr()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.cur.kind == "ident":
            alias = self.advance().value
        return Projection(e, alias)

    def from_item(self):
        if self.eat_op("("):
            q = self.select_stmt()
            self.expect_op(")")
            self.eat_kw("as")
            return SubqueryRef(q, self.ident())
        name = self.ident()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.cur.kind == "ident":
            alias = self.advance().value
        return TableRef(name, alias)

    def join_clause(self) -> Optional[JoinClause]:
        how = None
        if self.eat_kw("join"):
            how = "inner"
        elif self.at_kw("inner", "left", "right", "full", "cross"):
            kw = self.advance().value
            if kw == "left" and self.at_kw("semi", "anti"):
                kw = self.advance().value
            elif kw in ("left", "right", "full"):
                self.eat_kw("outer")
            self.expect_kw("join")
            how = {"inner": "inner", "left": "left", "right": "right",
                   "full": "full", "semi": "semi", "anti": "anti",
                   "cross": "cross"}[kw]
        else:
            return None
        right = self.from_item()
        on = None
        using = None
        if self.eat_kw("on"):
            on = self.expr()
        elif self.eat_kw("using"):
            self.expect_op("(")
            using = [self.ident()]
            while self.eat_op(","):
                using.append(self.ident())
            self.expect_op(")")
        return JoinClause(how, right, on, using)

    def order_item(self) -> OrderItem:
        e = self.expr()
        desc = False
        if self.eat_kw("asc"):
            pass
        elif self.eat_kw("desc"):
            desc = True
        nulls_first = None
        if self.eat_kw("nulls"):
            if self.eat_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return OrderItem(e, desc, nulls_first)

    # -- expressions -------------------------------------------------------
    def expr(self):
        return self.or_expr()

    def or_expr(self):
        e = self.and_expr()
        while self.eat_kw("or"):
            e = BinOp("or", e, self.and_expr())
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.eat_kw("and"):
            e = BinOp("and", e, self.not_expr())
        return e

    def not_expr(self):
        if self.eat_kw("not"):
            return UnOp("NOT", self.not_expr())
        return self.predicate()

    def predicate(self):
        e = self.additive()
        while True:
            if self.cur.kind == "op" and self.cur.value in (
                    "=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.advance().value
                e = BinOp(op, e, self.additive())
                continue
            if self.at_kw("is"):
                self.advance()
                negated = self.eat_kw("not")
                self.expect_kw("null")
                e = IsNull(e, negated)
                continue
            negated = False
            if self.at_kw("not") and self.toks[self.i + 1].kind == "kw" \
                    and self.toks[self.i + 1].value in (
                        "between", "in", "like"):
                self.advance()
                negated = True
            if self.eat_kw("between"):
                lo = self.additive()
                self.expect_kw("and")
                hi = self.additive()
                e = Between(e, lo, hi, negated)
                continue
            if self.eat_kw("in"):
                self.expect_op("(")
                if self.at_kw("select"):
                    q = self.select_stmt()
                    self.expect_op(")")
                    e = InSubquery(e, q, negated)
                    continue
                items = [self.expr()]
                while self.eat_op(","):
                    items.append(self.expr())
                self.expect_op(")")
                e = InList(e, items, negated)
                continue
            if self.eat_kw("like"):
                pat = self.advance()
                if pat.kind != "str":
                    raise ValueError("LIKE needs a string literal")
                e = LikeOp(e, pat.value, negated)
                continue
            if negated:
                raise ValueError(f"unexpected NOT at {self.cur}")
            return e

    def additive(self):
        e = self.multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.advance().value
                e = BinOp(op, e, self.multiplicative())
            elif self.at_op("||"):
                self.advance()
                e = FuncCall("concat", [e, self.multiplicative()])
            else:
                return e

    def multiplicative(self):
        e = self.unary()
        while self.at_op("*", "/", "%"):
            op = self.advance().value
            e = BinOp(op, e, self.unary())
        return e

    def unary(self):
        if self.eat_op("-"):
            return UnOp("-", self.unary())
        if self.eat_op("+"):
            return self.unary()
        return self.primary()

    def primary(self):
        t = self.cur
        if t.kind == "num":
            self.advance()
            is_float = "." in t.value or "e" in t.value.lower()
            return Lit(float(t.value) if is_float else int(t.value))
        if t.kind == "str":
            self.advance()
            return Lit(t.value)
        if self.at_kw("true"):
            self.advance()
            return Lit(True)
        if self.at_kw("false"):
            self.advance()
            return Lit(False)
        if self.at_kw("null"):
            self.advance()
            return Lit(None)
        if self.at_kw("date"):
            # DATE 'yyyy-mm-dd'
            if self.toks[self.i + 1].kind == "str":
                self.advance()
                return Lit(self.advance().value, kind="date")
            return ColRef((self.ident(),))
        if self.at_kw("timestamp"):
            if self.toks[self.i + 1].kind == "str":
                self.advance()
                return Lit(self.advance().value, kind="timestamp")
            return ColRef((self.ident(),))
        if self.at_kw("interval"):
            raise ValueError("INTERVAL literals are not supported; "
                             "use date_add/date_sub")
        if self.at_kw("case"):
            return self.case_expr()
        if self.at_kw("cast"):
            self.advance()
            self.expect_op("(")
            child = self.expr()
            self.expect_kw("as")
            tname = self.type_name()
            self.expect_op(")")
            return CastExpr(child, tname)
        if self.eat_op("("):
            if self.at_kw("select"):
                # uncorrelated scalar subquery: one row, one column
                q = self.select_stmt()
                self.expect_op(")")
                return ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind in ("ident", "kw"):
            name = self.ident()
            if self.at_op("("):
                return self.func_call(name)
            parts = [name]
            while self.at_op(".") and (
                    self.toks[self.i + 1].kind in ("ident", "kw")
                    or self.toks[self.i + 1].value == "*"):
                self.advance()
                if self.at_op("*"):
                    self.advance()
                    return Star(table=parts[0])
                parts.append(self.ident())
            return ColRef(tuple(parts))
        raise ValueError(f"unexpected token {t}")

    def type_name(self) -> str:
        base = self.ident().lower()
        if self.eat_op("("):
            args = [self.advance().value]
            while self.eat_op(","):
                args.append(self.advance().value)
            self.expect_op(")")
            return f"{base}({','.join(args)})"
        return base

    def case_expr(self) -> CaseExpr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()  # CASE x WHEN v THEN ...
        whens = []
        while self.eat_kw("when"):
            cond = self.expr()
            if operand is not None:
                cond = BinOp("=", operand, cond)
            self.expect_kw("then")
            whens.append((cond, self.expr()))
        else_ = self.expr() if self.eat_kw("else") else None
        self.expect_kw("end")
        return CaseExpr(whens, else_)

    def func_call(self, name: str) -> FuncCall:
        self.expect_op("(")
        distinct = False
        args: List[object] = []
        if self.at_op("*"):
            self.advance()
            args.append(Star())
        elif not self.at_op(")"):
            distinct = self.eat_kw("distinct")
            args.append(self.expr())
            while self.eat_op(","):
                args.append(self.expr())
        self.expect_op(")")
        window = None
        if self.eat_kw("over"):
            window = self.window_def()
        return FuncCall(name.lower(), args, distinct, window)

    def window_def(self) -> WindowDef:
        self.expect_op("(")
        partition: List[object] = []
        orders: List[OrderItem] = []
        rows = None
        if self.eat_kw("partition"):
            self.expect_kw("by")
            partition.append(self.expr())
            while self.eat_op(","):
                partition.append(self.expr())
        if self.eat_kw("order"):
            self.expect_kw("by")
            orders.append(self.order_item())
            while self.eat_op(","):
                orders.append(self.order_item())
        if self.eat_kw("rows"):
            self.expect_kw("between")
            rows = (self.frame_bound(), None)
            self.expect_kw("and")
            rows = (rows[0], self.frame_bound())
        self.expect_op(")")
        return WindowDef(partition, orders, rows)

    def frame_bound(self) -> Optional[int]:
        if self.eat_kw("unbounded"):
            if not self.eat_kw("preceding"):
                self.expect_kw("following")
            return None
        if self.eat_kw("current"):
            self.expect_kw("row")
            return 0
        n = int(self.advance().value)
        if self.eat_kw("preceding"):
            return -n
        self.expect_kw("following")
        return n


def _substitute_ctes(node, ctes: Dict[str, SelectStmt]) -> None:
    """Replace TableRefs naming a CTE with SubqueryRef copies, walking
    every nested SelectStmt (joins, derived tables, IN/scalar
    subqueries, UNION ALL branches)."""
    import copy

    def sub_table(ref):
        if isinstance(ref, TableRef) and ref.name.lower() in ctes:
            return SubqueryRef(copy.deepcopy(ctes[ref.name.lower()]),
                               ref.alias or ref.name)
        if isinstance(ref, SubqueryRef):
            _substitute_ctes(ref.query, ctes)
        return ref

    def walk_expr(e):
        if isinstance(e, (InSubquery,)):
            _substitute_ctes(e.query, ctes)
        elif isinstance(e, ScalarSubquery):
            _substitute_ctes(e.query, ctes)
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, list):
                for x in v:
                    if hasattr(x, "__dataclass_fields__"):
                        walk_expr(x)
            elif hasattr(v, "__dataclass_fields__") and \
                    not isinstance(v, SelectStmt):
                walk_expr(v)

    stmt = node
    while stmt is not None:
        stmt.from_ = sub_table(stmt.from_) if stmt.from_ is not None \
            else None
        for j in stmt.joins:
            j.right = sub_table(j.right)
            if j.on is not None:
                walk_expr(j.on)
        for p in stmt.projections:
            if hasattr(p.expr, "__dataclass_fields__"):
                walk_expr(p.expr)
        if stmt.where is not None:
            walk_expr(stmt.where)
        if stmt.having is not None:
            walk_expr(stmt.having)
        stmt = stmt.union_all


def parse(text: str) -> SelectStmt:
    return Parser(tokenize(text)).parse()
