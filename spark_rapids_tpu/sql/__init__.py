"""SQL string frontend: parse -> resolve onto the DataFrame algebra.

Counterpart of the reference's Spark SQL entry point (SURVEY.md section
2.1 "plugin entry"): the reference rides Spark's parser/analyzer and
replaces the physical plan; this engine has no host Spark, so a compact
recursive-descent parser (``parser.py``) produces an AST that
``resolver.py`` lowers onto the existing DataFrame/functions API — every
downstream stage (planner meta/tagging, fused XLA stages, spill, AQE) is
shared with the programmatic API.

Surface: SELECT [DISTINCT] ... FROM (tables, subqueries, JOINs with
ON/USING), WHERE, GROUP BY/HAVING, ORDER BY, LIMIT, UNION ALL, CASE,
CAST, BETWEEN/IN/LIKE/IS NULL, window functions with OVER, and the
function library mapped 1:1 onto ``api.functions``.
"""

from spark_rapids_tpu.sql.parser import parse
from spark_rapids_tpu.sql.resolver import resolve

__all__ = ["parse", "resolve"]
