"""Lower the SQL AST onto the DataFrame algebra.

Every SQL construct becomes the equivalent programmatic call, so the
planner's meta/tagging, fused stages, spill, and AQE all apply
identically to SQL and DataFrame queries.

Aggregation lowering: each aggregate subtree in the projection/HAVING
gets a hidden name, the query groups by its keys with those aggregates,
and the outer expressions re-project against the hidden columns — which
is how ``sum(x) + 1`` or HAVING conditions compose without special
cases.
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.sql import parser as A

AGG_FNS = {"sum", "count", "avg", "mean", "min", "max", "first", "last",
           "collect_list", "collect_set", "stddev", "stddev_samp",
           "stddev_pop", "variance", "var_samp", "var_pop"}

WINDOW_RANK_FNS = {"row_number", "rank", "dense_rank", "percent_rank"}


class Scope:
    """Name resolution for one FROM clause.

    Each source maps its ORIGINAL (SQL-visible) column names to the
    flat engine names (which differ after join-deduplication renames).
    A bare name appearing in two sources is ambiguous even when one
    side was renamed — matching Spark's analyzer."""

    def __init__(self):
        self.sources: List[Tuple[Optional[str], Dict[str, str]]] = []

    def add(self, alias: Optional[str], columns,
            renames: Optional[Dict[str, str]] = None):
        renames = renames or {}
        self.sources.append(
            (alias, {c: renames.get(c, c) for c in columns}))

    def all_columns(self) -> List[str]:
        out = []
        for _, m in self.sources:
            out.extend(v for v in m.values() if v not in out)
        return out

    def mapping_of(self, alias: str) -> Optional[Dict[str, str]]:
        for a, m in self.sources:
            if a == alias:
                return m
        return None

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[str, Tuple[str, ...]]:
        """(qualified) name -> (flat column name, remaining struct path)."""
        if len(parts) >= 2 and self.mapping_of(parts[0]) is not None:
            alias, name, rest = parts[0], parts[1], parts[2:]
            m = self.mapping_of(alias)
            if name not in m:
                raise KeyError(
                    f"column {name!r} not found in {alias!r} "
                    f"(has {sorted(m)})")
            return m[name], rest
        name, rest = parts[0], parts[1:]
        hits = [(a, m[name]) for a, m in self.sources if name in m]
        flats = {f for _, f in hits}
        if len(hits) > 1 and len(flats) > 1:
            raise ValueError(
                f"column {name!r} is ambiguous "
                f"(in {[a for a, _ in hits]}); qualify it")
        if hits:
            return hits[0][1], rest
        if self.sources:
            all_cols = self.all_columns()
            if name not in all_cols:
                raise KeyError(
                    f"column {name!r} not found; available: {all_cols}")
        return name, rest


class Resolver:
    def __init__(self, session):
        self.session = session
        from spark_rapids_tpu.api import functions as F
        self.F = F

    # ------------------------------------------------------------ entry --
    def run(self, stmt: A.SelectStmt):
        df = self._select(stmt)
        while stmt.union_all is not None:
            stmt = stmt.union_all
            df = df.union(self._select(stmt))
        return df

    # ----------------------------------------------------------- select --
    def _select(self, stmt: A.SelectStmt):
        F = self.F
        scope = Scope()
        if stmt.from_ is None:
            df = self.session.range(1)
            scope.add(None, ["id"])
        else:
            df = self._from_item(stmt.from_, scope)
        for j in stmt.joins:
            df = self._join(df, j, scope)
        if stmt.where is not None:
            # top-level conjuncts that are IN (subquery) become
            # semi/anti joins (Spark's RewritePredicateSubquery); the
            # rest filter normally
            residual = None
            for conj in self._split_conjuncts(stmt.where):
                if isinstance(conj, A.InSubquery):
                    df = self._in_subquery_join(df, conj, scope)
                    continue
                c = self._expr(conj, scope)
                residual = c if residual is None else (residual & c)
            if residual is not None:
                df = df.filter(residual)

        aggs: Dict[str, object] = {}   # hidden name -> Col aggregate
        agg_keys: Dict[str, str] = {}  # structural key -> hidden name

        def lift_aggs(node):
            """Replace aggregate subtrees with hidden column refs.
            Under ROLLUP/CUBE/GROUPING SETS, grouping()/grouping_id()
            calls lift the same way — GroupedData.agg resolves their
            markers against the Expand-produced grouping-id column."""
            if isinstance(node, A.ScalarSubquery):
                return node  # opaque: its aggregates are its own
            if isinstance(node, A.FuncCall) and node.window is None \
                    and stmt.group_sets is not None \
                    and node.name in ("grouping", "grouping_id"):
                key = repr(node)
                if key not in agg_keys:
                    hidden = f"__a{len(aggs)}"
                    agg_keys[key] = hidden
                    if node.name == "grouping_id":
                        aggs[hidden] = self.F.grouping_id().alias(hidden)
                    else:
                        aggs[hidden] = self.F.grouping(
                            self._expr(node.args[0], scope)).alias(hidden)
                return A.ColRef((agg_keys[key],))
            if isinstance(node, A.FuncCall) and node.window is None \
                    and node.name in AGG_FNS:
                key = repr(node)
                if key not in agg_keys:
                    hidden = f"__a{len(aggs)}"
                    agg_keys[key] = hidden
                    aggs[hidden] = self._agg_call(node, scope).alias(hidden)
                return A.ColRef((agg_keys[key],))
            for f in getattr(node, "__dataclass_fields__", {}):
                v = getattr(node, f)
                if isinstance(v, list):
                    setattr(node, f, [lift_aggs(x) if hasattr(
                        x, "__dataclass_fields__") else x for x in v])
                elif hasattr(v, "__dataclass_fields__"):
                    setattr(node, f, lift_aggs(v))
            return node

        projections = self._expand_stars(stmt.projections, scope)
        has_aggs = stmt.group_by or any(
            self._contains_agg(p.expr) for p in projections) or (
            stmt.having is not None and self._contains_agg(stmt.having))
        group_alias: Dict[str, str] = {}  # flat group key -> out alias

        if has_aggs:
            # group keys: plain column refs group directly; computed
            # keys materialize as hidden columns first
            key_cols: List[str] = []
            pre_exprs = []
            for i, g in enumerate(stmt.group_by):
                if isinstance(g, A.ColRef):
                    name, rest = scope.resolve(g.parts)
                    if rest:
                        raise ValueError(
                            "GROUP BY struct fields: alias the field in "
                            "a subquery first")
                    key_cols.append(name)
                else:
                    hidden = f"__g{i}"
                    pre_exprs.append(
                        self._expr(g, scope).alias(hidden))
                    key_cols.append(hidden)
            if pre_exprs:
                df = df.select(*[F.col(c) for c in scope.all_columns()],
                               *pre_exprs)
                scope.add(None, [k for k in key_cols
                                 if k.startswith("__g")])
            # re-projected GROUP BY expressions (SELECT cust/2 ... GROUP
            # BY cust/2) resolve to the materialized key column by
            # structural match, before aggregate lifting
            gmap = {repr(g): k for g, k in zip(stmt.group_by, key_cols)}

            def replace_group_exprs(node):
                if isinstance(node, A.ScalarSubquery):
                    return node  # opaque: its expressions are its own
                if hasattr(node, "__dataclass_fields__"):
                    if repr(node) in gmap:
                        return A.ColRef((gmap[repr(node)],))
                    for f in node.__dataclass_fields__:
                        v = getattr(node, f)
                        if isinstance(v, list):
                            setattr(node, f, [
                                replace_group_exprs(x) if hasattr(
                                    x, "__dataclass_fields__") else x
                                for x in v])
                        elif hasattr(v, "__dataclass_fields__"):
                            setattr(node, f, replace_group_exprs(v))
                return node

            proj_asts = [lift_aggs(replace_group_exprs(p.expr))
                         for p in projections]
            having_ast = lift_aggs(replace_group_exprs(stmt.having)) \
                if stmt.having is not None else None
            if not aggs and not key_cols:
                raise ValueError("grouped query with no aggregates")
            if stmt.group_sets is not None:
                df = df.groupingSets(
                    [[key_cols[i] for i in s] for s in stmt.group_sets],
                    *key_cols).agg(*aggs.values())
            else:
                df = df.group_by(*key_cols).agg(*aggs.values())
            # post-agg scope: original aliases keep their surviving
            # group keys so qualified refs (c.name) still resolve; the
            # anonymous source holds only the hidden names
            post_scope = Scope()
            key_set = set(key_cols)
            for alias, m in scope.sources:
                kept = {o: f for o, f in m.items() if f in key_set}
                if kept:
                    post_scope.sources.append((alias, kept))
            post_scope.add(None, [k for k in key_cols
                                  if k.startswith("__g")]
                           + list(aggs.keys()))
            if having_ast is not None:
                df = df.filter(self._expr(having_ast, post_scope))
            out_cols = []
            out_names = []
            for p, ast in zip(projections, proj_asts):
                name = p.alias or self._default_name(p.expr)
                out_cols.append(self._expr(ast, post_scope).alias(name))
                out_names.append(name)
                # a bare projection of a group key under an alias
                # (SELECT ca.ca_state state ... GROUP BY ca.ca_state):
                # remember flat-key -> output-alias so a qualified
                # ORDER BY ref to the key can find its output column
                if isinstance(ast, A.ColRef) and len(ast.parts) == 1 \
                        and ast.parts[0] in key_cols:
                    group_alias.setdefault(ast.parts[0], name)
            df = df.select(*out_cols)
        else:
            if stmt.having is not None:
                raise ValueError("HAVING requires GROUP BY/aggregates")
            raw_cols = []
            out_names = []
            for p in projections:
                name = p.alias or self._default_name(p.expr)
                raw_cols.append(self._expr(p.expr, scope))
                out_names.append(name)
            # ORDER BY may mix output aliases with input columns the
            # projection drops (Spark allows both): materialize the
            # outputs alongside the inputs, sort once, then project
            if stmt.order_by and not stmt.distinct and any(
                    self._order_name(o, out_names) is None
                    for o in stmt.order_by):
                F = self.F
                # outputs materialize under hidden names so input
                # columns stay addressable for the sort (an alias may
                # shadow the input name it sorts by)
                prefix = "__o"
                in_names = scope.all_columns()
                while any(n.startswith(prefix) for n in in_names):
                    prefix += "_"
                hidden = [f"{prefix}{i}" for i in range(len(raw_cols))]
                ext = df.select(
                    *[F.col(c) for c in in_names],
                    *[c.alias(h) for c, h in zip(raw_cols, hidden)])
                keys = []
                for o in stmt.order_by:
                    name = self._order_name(o, out_names)
                    if name is not None:
                        keys.append(self._sortkey_for(
                            F.col(hidden[out_names.index(name)]), o))
                    else:
                        keys.append(self._order_sortkey(o, scope))
                df = ext.orderBy(*keys).select(
                    *[F.col(h).alias(n)
                      for h, n in zip(hidden, out_names)])
                stmt = dataclasses.replace(stmt, order_by=[])
            else:
                df = df.select(*[c.alias(n) for c, n in
                                 zip(raw_cols, out_names)])

        if stmt.distinct:
            df = df.distinct()
        if stmt.order_by:
            # DISTINCT also lacks a pre-projection fallback, so
            # qualified refs may match outputs there too — but only
            # when the qualifier really owns the named column
            df = df.orderBy(*[
                self._order_key(o, out_names,
                                grouped=has_aggs or stmt.distinct,
                                scope=scope, key_alias=group_alias)
                for o in stmt.order_by])
        if stmt.limit is not None:
            df = df.limit(stmt.limit)
        return df

    @staticmethod
    def _split_conjuncts(node):
        if isinstance(node, A.BinOp) and node.op == "and":
            yield from Resolver._split_conjuncts(node.left)
            yield from Resolver._split_conjuncts(node.right)
        else:
            yield node

    def _in_subquery_join(self, df, node: A.InSubquery, scope: Scope):
        """x IN (SELECT k FROM ...) -> semi join; NOT IN -> null-aware
        anti (SQL three-valued semantics: a NULL anywhere in the
        subquery makes NOT IN unknown for every row)."""
        F = self.F
        sub = self._select(node.query)
        sub_cols = [n for n, _ in sub.schema]
        if len(sub_cols) != 1:
            raise ValueError(
                "IN (subquery) must select exactly one column")
        key = self._expr(node.child, scope)
        rname = sub_cols[0]
        if rname in {n for n, _ in df.schema}:
            new = "__in_sub"
            sub = sub.withColumnRenamed(rname, new)
            rname = new
        if node.negated:
            # one aggregate pass answers both probes: count(*) for
            # emptiness, count(col) for null presence
            n_all, n_nonnull = sub.agg(
                F.count("*").alias("n"),
                F.count(F.col(rname)).alias("nn")).collect()[0]
            if n_all == 0:
                return df  # empty list: NOT IN is true for every row
            if n_nonnull < n_all:
                return df.limit(0)  # NULL present: never true
            return df.filter(key.isNotNull()).join(
                sub, on=key == F.col(rname), how="anti")
        return df.join(sub, on=key == F.col(rname), how="semi")

    # ------------------------------------------------------------- from --
    def _from_item(self, item, scope: Scope):
        if isinstance(item, A.SubqueryRef):
            sub = self._select(item.query)
            scope.add(item.alias, [n for n, _ in sub.schema])
            return sub
        df = self.session.table(item.name)
        cols = [n for n, _ in df.schema]
        scope.add(item.alias or item.name, cols)
        return df

    def _join(self, left, j: A.JoinClause, scope: Scope):
        right_scope = Scope()
        right = self._from_item(j.right, right_scope)
        ralias, rmap = right_scope.sources[0]
        rcols = list(rmap)
        if j.how == "cross":
            scope.add(ralias, rcols)
            out = left.join(right, on=None, how="cross")
            return out if j.on is None else out.filter(
                self._expr(j.on, scope))
        if j.using is not None:
            if j.how in ("semi", "anti"):
                # output is left-only; right columns leave scope
                scope.add(ralias, [])
                return left.join(right, on=j.using, how=j.how)
            # rename right-side non-key duplicates so qualified refs
            # (tb.v) resolve to the RIGHT side's values, not the left's
            lcols = set(scope.all_columns())
            dup = [c for c in rcols
                   if c not in j.using and c in lcols]
            renames = {}
            if dup:
                prefix = ralias or "r"
                renames = {c: f"{prefix}__{c}" for c in dup}
                for old, new in renames.items():
                    right = right.withColumnRenamed(old, new)
            scope.add(ralias, [c for c in rcols if c not in j.using],
                      renames=renames)
            return left.join(right, on=j.using, how=j.how)
        if j.on is None:
            raise ValueError("JOIN requires ON or USING")
        # deduplicate overlapping column names so the flat engine can
        # hold both sides; qualified refs resolve through the rename map
        lcols = set(scope.all_columns())
        dup = [c for c in rcols if c in lcols]
        keep_right = j.how not in ("semi", "anti")
        renames = {}
        if dup:
            prefix = ralias or "r"
            renames = {c: f"{prefix}__{c}" for c in dup}
            for old, new in renames.items():
                right = right.withColumnRenamed(old, new)
        if keep_right:
            scope.add(ralias, rcols, renames=renames)
            return left.join(right, on=self._expr(j.on, scope),
                             how=j.how)
        # semi/anti resolve the ON condition over both sides before
        # the scope narrows back to the left
        cond_scope = Scope()
        cond_scope.sources = list(scope.sources)
        cond_scope.add(ralias, rcols, renames=renames)
        return left.join(right, on=self._expr(j.on, cond_scope),
                         how=j.how)

    # ------------------------------------------------------ expressions --
    def _expand_stars(self, projections, scope: Scope):
        out = []
        for p in projections:
            if isinstance(p.expr, A.Star):
                if p.expr.table is None:
                    cols = scope.all_columns()
                else:
                    m = scope.mapping_of(p.expr.table)
                    if m is None:
                        raise KeyError(f"unknown table {p.expr.table!r}")
                    cols = list(m.values())
                out.extend(A.Projection(A.ColRef((c,)), None)
                           for c in cols)
            else:
                out.append(p)
        return out

    def _contains_agg(self, node) -> bool:
        if isinstance(node, A.ScalarSubquery):
            return False  # opaque: its aggregates are its own
        if isinstance(node, A.FuncCall) and node.window is None and \
                node.name in AGG_FNS:
            return True
        for f in getattr(node, "__dataclass_fields__", {}):
            v = getattr(node, f)
            if isinstance(v, list):
                if any(self._contains_agg(x) for x in v
                       if hasattr(x, "__dataclass_fields__")):
                    return True
            elif hasattr(v, "__dataclass_fields__") and \
                    self._contains_agg(v):
                return True
        return False

    def _default_name(self, ast) -> str:
        if isinstance(ast, A.ColRef):
            return ast.parts[-1]
        if isinstance(ast, A.FuncCall):
            return ast.name
        return "col"

    def _order_name(self, o: A.OrderItem, out_names: List[str],
                    allow_qualified: bool = False,
                    scope: Optional[Scope] = None,
                    key_alias: Optional[Dict[str, str]] = None
                    ) -> Optional[str]:
        """Output-column name an ORDER BY item refers to, or None when
        it must resolve against the pre-projection input.  In grouped/
        DISTINCT queries (``allow_qualified``) there is no input to
        fall back to, so a qualified ref (c.name) matches the output
        column its last part named — after validating the qualifier
        actually owns that column in ``scope``.  ``key_alias`` maps a
        GROUP BY key's flat column to the alias its projection gave it
        (SELECT ca.ca_state state ... ORDER BY ca.ca_state — Spark
        resolves the qualified ref against the grouping expression)."""
        if isinstance(o.expr, A.Lit) and isinstance(o.expr.value, int):
            pos = o.expr.value
            if not 1 <= pos <= len(out_names):
                raise ValueError(
                    f"ORDER BY position {pos} out of range "
                    f"(1..{len(out_names)})")
            return out_names[pos - 1]
        if isinstance(o.expr, A.ColRef):
            if len(o.expr.parts) == 1:
                # bare names resolve against the output; QUALIFIED refs
                # (t.c) name the input relation and fall through to
                # pre-projection resolution (Spark's behavior)
                if o.expr.parts[0] in out_names:
                    return o.expr.parts[0]
            elif allow_qualified:
                parts = o.expr.parts
                if len(parts) != 2:
                    raise ValueError(
                        f"ORDER BY {'.'.join(parts)}: multi-part "
                        "references are not supported in grouped/"
                        "DISTINCT queries; alias the expression")
                if scope is not None:
                    m = scope.mapping_of(parts[0])
                    if m is None:
                        raise KeyError(
                            f"unknown relation {parts[0]!r} in "
                            "ORDER BY")
                    flat = m.get(parts[1])
                    if flat is None:
                        raise KeyError(
                            f"column {parts[1]!r} not in relation "
                            f"{parts[0]!r}")
                    # provenance check: the qualifier's FLAT column
                    # (post join-dedup rename) must itself be the
                    # output — b.v must not silently sort by a's v
                    if flat in out_names:
                        return flat
                    # ... or a GROUP BY key whose aliased output is
                    # projected (Spark resolves a qualified ORDER BY
                    # ref against the grouping expressions)
                    if key_alias and flat in key_alias:
                        return key_alias[flat]
                    raise KeyError(
                        f"ORDER BY {parts[0]}.{parts[1]}: that "
                        "relation's column is not among the outputs")
                if parts[-1] in out_names:
                    return parts[-1]
        return None

    def _order_key(self, o: A.OrderItem, out_names: List[str],
                   grouped: bool = False,
                   scope: Optional[Scope] = None,
                   key_alias: Optional[Dict[str, str]] = None):
        """Post-projection sort key.  Qualified refs (t.c) may match
        output columns by last part only in GROUPED/DISTINCT queries,
        where no input relation survives to resolve them against."""
        F = self.F
        name = self._order_name(o, out_names, allow_qualified=grouped,
                                scope=scope, key_alias=key_alias)
        if name is None:
            raise ValueError(
                "ORDER BY supports output columns/aliases/positions "
                "(or input columns for non-aggregate queries)")
        c = F.col(name)
        if o.desc:
            return c.desc_nulls_first() if o.nulls_first else c.desc()
        if o.nulls_first is False:
            return c.asc_nulls_last()
        return c.asc()

    def _agg_call(self, node: A.FuncCall, scope: Scope):
        F = self.F
        if node.distinct:
            raise ValueError(f"{node.name.upper()}(DISTINCT ...) is not "
                             "supported; use a subquery with DISTINCT")
        fn = {"sum": F.sum, "count": F.count, "avg": F.avg,
              "mean": F.avg, "min": F.min, "max": F.max,
              "first": F.first, "last": F.last,
              "collect_list": F.collect_list,
              "collect_set": F.collect_set,
              "stddev": F.stddev, "stddev_samp": F.stddev_samp,
              "stddev_pop": F.stddev_pop, "variance": F.variance,
              "var_samp": F.var_samp, "var_pop": F.var_pop}[node.name]
        if node.name == "count" and (not node.args or
                                     isinstance(node.args[0], A.Star)):
            return F.count("*")
        return fn(self._expr(node.args[0], scope))

    def _window_call(self, node: A.FuncCall, scope: Scope):
        F = self.F
        w = node.window
        win = F.Window.partitionBy(
            *[self._expr(e, scope) for e in w.partition_by])
        if w.order_by:
            win = win.orderBy(*[self._order_sortkey(o, scope)
                                for o in w.order_by])
        if w.rows is not None:
            win = win.rowsBetween(w.rows[0], w.rows[1])
        if node.name in WINDOW_RANK_FNS:
            return getattr(F, node.name)().over(win)
        if node.name in ("lead", "lag"):
            off = node.args[1].value if len(node.args) > 1 else 1
            default = node.args[2].value if len(node.args) > 2 else None
            return getattr(F, node.name)(
                self._expr(node.args[0], scope), off, default).over(win)
        wfn = {"sum": F.window_sum, "count": F.window_count,
               "min": F.window_min, "max": F.window_max,
               "avg": F.window_avg, "mean": F.window_avg}.get(node.name)
        if wfn is None:
            raise ValueError(
                f"window function {node.name!r} not supported")
        if node.name == "count" and (not node.args or
                                     isinstance(node.args[0], A.Star)):
            return wfn("*").over(win)
        return wfn(self._expr(node.args[0], scope)).over(win)

    def _order_sortkey(self, o: A.OrderItem, scope: Scope):
        return self._sortkey_for(self._expr(o.expr, scope), o)

    @staticmethod
    def _sortkey_for(c, o: A.OrderItem):
        if o.desc:
            return c.desc_nulls_first() if o.nulls_first else c.desc()
        if o.nulls_first is False:
            return c.asc_nulls_last()
        return c.asc()

    def _func(self, node: A.FuncCall, scope: Scope):
        F = self.F
        if node.window is not None:
            return self._window_call(node, scope)
        if node.name in AGG_FNS:
            return self._agg_call(node, scope)
        args = [self._expr(a, scope) for a in node.args]
        n = node.name

        def lit_arg(i):
            a = node.args[i]
            if not isinstance(a, A.Lit):
                raise ValueError(f"{n}: argument {i + 1} must be a "
                                 "literal")
            return a.value

        simple = {
            "exp": F.exp, "expm1": F.expm1, "ln": F.log,
            "asinh": F.asinh, "acosh": F.acosh, "atanh": F.atanh,
            "log2": F.log2, "log10": F.log10, "log1p": F.log1p,
            "sin": F.sin, "cos": F.cos, "tan": F.tan, "cot": F.cot,
            "asin": F.asin, "acos": F.acos, "atan": F.atan,
            "atan2": F.atan2, "sinh": F.sinh, "cosh": F.cosh,
            "tanh": F.tanh, "degrees": F.degrees, "radians": F.radians,
            "rint": F.rint, "signum": F.signum, "sign": F.signum,
            "cbrt": F.cbrt, "floor": F.floor, "ceil": F.ceil,
            "ceiling": F.ceil, "pmod": F.pmod,
            "abs": F.abs, "sqrt": F.sqrt, "coalesce": F.coalesce,
            "isnan": F.isnan, "greatest": F.greatest, "least": F.least,
            "length": F.length, "upper": F.upper, "lower": F.lower,
            "initcap": F.initcap, "concat": F.concat, "trim": F.trim,
            "ltrim": F.ltrim, "rtrim": F.rtrim, "year": F.year,
            "month": F.month, "day": F.dayofmonth,
            "dayofmonth": F.dayofmonth, "dayofweek": F.dayofweek,
            "weekday": F.weekday, "dayofyear": F.dayofyear,
            "quarter": F.quarter, "hour": F.hour, "minute": F.minute,
            "second": F.second, "last_day": F.last_day,
            "unix_timestamp": F.unix_timestamp,
            "from_unixtime": F.from_unixtime, "size": F.size,
            "array": F.array, "datediff": F.datediff,
            "months_between": F.months_between, "pow": F.pow,
            "power": F.pow, "element_at": F.element_at,
            "map_keys": F.map_keys, "map_values": F.map_values,
            "hypot": F.hypot, "ascii": F.ascii, "char": F.chr,
            "chr": F.chr, "array_min": F.array_min,
            "array_max": F.array_max, "reverse": F.reverse,
        }
        if n in simple:
            return simple[n](*args)
        if n == "round":
            return F.round(args[0], int(lit_arg(1)) if len(args) > 1
                           else 0)
        if n == "bround":
            return F.bround(args[0], int(lit_arg(1)) if len(args) > 1
                            else 0)
        if n == "slice":
            return F.slice(args[0], int(lit_arg(1)), int(lit_arg(2)))
        if n == "array_repeat":
            return F.array_repeat(args[0], int(lit_arg(1)))
        if n == "next_day":
            return F.next_day(args[0], str(lit_arg(1)))
        if n == "shiftleft":
            return F.shiftleft(args[0], int(lit_arg(1)))
        if n == "shiftright":
            return F.shiftright(args[0], int(lit_arg(1)))
        if n == "shiftrightunsigned":
            return F.shiftrightunsigned(args[0], int(lit_arg(1)))
        if n == "log":
            # 1-arg = natural log; 2-arg = log(base, expr) (Spark)
            if len(args) == 1:
                return F.log(args[0])
            from spark_rapids_tpu.ops import arithmetic as arith
            from spark_rapids_tpu.api.functions import Col, _expr
            return Col(arith.Logarithm(_expr(args[0]), _expr(args[1])))
        if n in ("substring", "substr"):
            return F.substring(args[0], int(lit_arg(1)),
                               int(lit_arg(2)) if len(args) > 2
                               else 2 ** 31 - 1)
        if n == "get_json_object":
            return F.get_json_object(args[0], lit_arg(1))
        if n == "split":
            return F.split(args[0], lit_arg(1),
                           int(lit_arg(2)) if len(args) > 2 else -1)
        if n == "date_format":
            return F.date_format(args[0], lit_arg(1))
        if n == "to_unix_timestamp":
            return F.to_unix_timestamp(args[0])
        if n == "window":
            return F.window(args[0], lit_arg(1),
                            lit_arg(2) if len(args) > 2 else None)
        if n == "concat_ws":
            return F.concat_ws(lit_arg(0), *args[1:])
        if n in ("lpad", "rpad"):
            fn = F.lpad if n == "lpad" else F.rpad
            return fn(args[0], int(lit_arg(1)), lit_arg(2)
                      if len(args) > 2 else " ")
        if n == "locate":
            return F.locate(lit_arg(0), args[1])
        if n == "repeat":
            return F.repeat(args[0], int(lit_arg(1)))
        if n == "substring_index":
            return F.substring_index(args[0], lit_arg(1),
                                     int(lit_arg(2)))
        if n == "regexp_replace":
            return F.regexp_replace(args[0], lit_arg(1), lit_arg(2))
        if n == "replace":
            return F.replace(args[0], lit_arg(1), lit_arg(2))
        if n == "translate":
            return F.translate(args[0], lit_arg(1), lit_arg(2))
        if n == "split":
            return F.split(args[0], lit_arg(1))
        if n == "date_add":
            return F.date_add(args[0], int(lit_arg(1)))
        if n == "date_sub":
            return F.date_sub(args[0], int(lit_arg(1)))
        if n == "add_months":
            return F.add_months(args[0], int(lit_arg(1)))
        if n == "trunc":
            return F.trunc(args[0], lit_arg(1))
        if n == "struct":
            return F.struct(*args)
        if n == "md5":
            return F.md5(args[0])
        if n == "hash":
            return F.hash(*args) if hasattr(F, "hash") else \
                F.murmur3(*args)
        raise ValueError(f"unknown SQL function {n!r}")

    def _expr(self, node, scope: Scope):
        F = self.F
        if isinstance(node, A.Lit):
            if node.kind == "date":
                return F.lit(datetime.date.fromisoformat(node.value))
            if node.kind == "timestamp":
                import pandas as pd
                return F.lit(pd.Timestamp(node.value, tz="UTC")
                             .to_pydatetime())
            return F.lit(node.value)
        if isinstance(node, A.ColRef):
            name, rest = scope.resolve(node.parts)
            c = F.col(name)
            for field in rest:
                c = c.getField(field)
            return c
        if isinstance(node, A.BinOp):
            left = self._expr(node.left, scope)
            right = self._expr(node.right, scope)
            op = node.op
            if op == "and":
                return left & right
            if op == "or":
                return left | right
            if op == "=":
                return left == right
            if op in ("<>", "!="):
                return left != right
            if op == "<":
                return left < right
            if op == "<=":
                return left <= right
            if op == ">":
                return left > right
            if op == ">=":
                return left >= right
            if op == "+":
                return left + right
            if op == "-":
                return left - right
            if op == "*":
                return left * right
            if op == "/":
                return left / right
            if op == "%":
                return left % right
            raise ValueError(f"unknown operator {op!r}")
        if isinstance(node, A.UnOp):
            c = self._expr(node.child, scope)
            return ~c if node.op == "NOT" else -c
        if isinstance(node, A.IsNull):
            c = self._expr(node.child, scope)
            return c.isNotNull() if node.negated else c.isNull()
        if isinstance(node, A.Between):
            c = self._expr(node.child, scope)
            e = c.between(self._expr(node.lo, scope),
                          self._expr(node.hi, scope))
            return ~e if node.negated else e
        if isinstance(node, A.InList):
            c = self._expr(node.child, scope)
            vals = []
            for it in node.items:
                if not isinstance(it, A.Lit):
                    raise ValueError("IN list items must be literals")
                vals.append(it.value)
            e = c.isin(*vals)
            return ~e if node.negated else e
        if isinstance(node, A.LikeOp):
            c = self._expr(node.child, scope)
            e = c.like(node.pattern)
            return ~e if node.negated else e
        if isinstance(node, A.CaseExpr):
            if not node.whens:
                raise ValueError("CASE needs at least one WHEN")
            b = F.when(self._expr(node.whens[0][0], scope),
                       self._expr(node.whens[0][1], scope))
            for cond, val in node.whens[1:]:
                b = b.when(self._expr(cond, scope),
                           self._expr(val, scope))
            if node.else_ is not None and not (
                    isinstance(node.else_, A.Lit)
                    and node.else_.value is None):
                return b.otherwise(self._expr(node.else_, scope))
            # ELSE NULL == no else: CaseWhen emits a typed null from the
            # first branch's dtype post-bind
            return b
        if isinstance(node, A.CastExpr):
            return self._expr(node.child, scope).cast(node.type_name)
        if isinstance(node, A.FuncCall):
            return self._func(node, scope)
        if isinstance(node, A.ScalarSubquery):
            # uncorrelated: runs once at resolve time, inlines the value
            # (Spark executes uncorrelated scalar subqueries the same
            # way — once, before the main query)
            sub = self._select(node.query)
            rows = sub.collect()
            if len(sub.schema) != 1 or len(rows) > 1:
                raise ValueError(
                    "scalar subquery must return at most one row, one "
                    f"column (got {len(rows)} rows x "
                    f"{len(sub.schema)} cols)")
            if not rows:
                # empty scalar subquery yields NULL (SQL semantics)
                from spark_rapids_tpu.ops.expressions import Literal
                return self.F.Col(Literal(None, sub.schema[0][1]))
            return F.lit(rows[0][0])
        if isinstance(node, A.InSubquery):
            raise ValueError(
                "IN (subquery) is only supported as a top-level WHERE "
                "conjunct")
        if isinstance(node, A.Star):
            raise ValueError("* is only valid as a projection or in "
                             "count(*)")
        raise ValueError(f"cannot resolve {node!r}")


def resolve(session, stmt: A.SelectStmt):
    return Resolver(session).run(stmt)
