"""Watchdog: deadlines over named engine sections, hang detection, and
cooperative cancellation.

The recovery ladder (driver.py) only fires when a fault surfaces as an
*exception*, but the failure modes that dominate distributed TPU runs
are hangs: a collective stuck on ICI/DCN, a wedged pipeline worker, a
stalled reader.  The reference's UCX transport carried heartbeats and
request timeouts for exactly this class (SURVEY.md section 2.5); the
collective-based shuffle dropped them.  This module restores them as a
*host-side* facility the ladder can consume:

- ``section(point, deadline_ms=...)`` wraps a monitored region.  The
  deadline comes from the explicit argument, the per-point conf key
  ``spark.rapids.tpu.watchdog.deadline.<point>``, or
  ``spark.rapids.tpu.watchdog.defaultDeadlineMs``.
- a single daemon **monitor thread** polls active sections; an overrun
  becomes a classified :class:`~.faults.TimeoutFault` (RETRYABLE — the
  ladder's retry/demote rungs absorb it) parked on the owning thread's
  **cancellation token**.
- the fault is *raised at the next cooperative checkpoint* on the
  driving thread: every ``inject.fire`` site, every host sync
  (utils/hostsync.py), the pipeline consumer's queue wait
  (exec/pipeline.py), and section entry/exit.  A monitor thread cannot
  safely interrupt arbitrary Python/XLA frames, so cancellation is
  cooperative — the checkpoints are the places the engine already
  touches the host between device work.
- long-lived sections (the pipeline worker) call ``Section.beat()``
  on progress: the deadline then measures *silence since the last
  beat*, not total elapsed time, so a worker that is making progress
  never trips while a wedged one does.

Worker threads adopt their driving thread's identity
(``adopt_thread``/``release_thread``, wired through
``exec/pipeline.worker_attribution``) so a section opened on the
worker cancels the *query's* token, and either thread — whichever
checkpoints first — delivers the fault to the recovery ladder.

Every trip and every delivered cancellation is counted in
``watchdog_metrics`` and emitted as a ``WatchdogTrip`` /
``WatchdogCancel`` event on the session event log (stamped with the
in-flight query id), feeding ``tools/profiling`` health checks.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from spark_rapids_tpu.robustness import faults as F
from spark_rapids_tpu.utils import tracing

# monitor cadence bounds: never poll faster than 2ms (a busy loop) or
# slower than 100ms (a 150ms test deadline must still detect promptly)
_POLL_MIN_S = 0.002
_POLL_MAX_S = 0.1
_IDLE_SLEEP_S = 0.2


class WatchdogMetrics:
    """Process-wide trip/cancel counters, surfaced by tools/profiling
    alongside the OOM-retry and recovery counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self.trips: Dict[str, int] = {}
        self.cancels: Dict[str, int] = {}
        self.max_overrun_ms = 0.0

    def trip(self, point: str, overrun_ms: float) -> None:
        with self._lock:
            self.trips[point] = self.trips.get(point, 0) + 1
            self.max_overrun_ms = max(self.max_overrun_ms, overrun_ms)

    def cancel(self, point: str) -> None:
        with self._lock:
            self.cancels[point] = self.cancels.get(point, 0) + 1

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {"trips": dict(self.trips),
                    "cancels": dict(self.cancels),
                    "max_overrun_ms": self.max_overrun_ms}

    def reset(self) -> None:
        with self._lock:
            self.trips.clear()
            self.cancels.clear()
            self.max_overrun_ms = 0.0


watchdog_metrics = WatchdogMetrics()


class Section:
    """One active monitored region."""

    __slots__ = ("id", "point", "deadline_s", "owner", "opener",
                 "session", "started", "deadline_at", "tripped")
    _ids = itertools.count(1)

    def __init__(self, point: str, deadline_s: float, owner: int,
                 session):
        self.id = next(Section._ids)
        self.point = point
        self.deadline_s = deadline_s
        self.owner = owner
        # the physical thread that opened the section (== owner unless
        # adopted); disown() evicts by opener when a wedged worker is
        # abandoned
        self.opener = threading.get_ident()
        self.session = session
        self.started = time.monotonic()
        self.deadline_at = self.started + deadline_s
        self.tripped = False

    def beat(self) -> None:
        """Heartbeat: push the deadline out from *now*.  A hang is
        silence longer than the deadline, not total elapsed time."""
        self.deadline_at = time.monotonic() + self.deadline_s


_lock = threading.Lock()
_sections: Dict[int, Section] = {}
# owning (driving) thread ident -> the pending TimeoutFault the next
# checkpoint on that thread (or a worker adopted into it) must raise
_pending: Dict[int, F.TimeoutFault] = {}
# worker thread ident -> driving thread it acts for (same discipline
# as inject._adopted: int-keyed dict ops are atomic under the GIL)
_adopted: Dict[int, int] = {}
_monitor: Optional[threading.Thread] = None
# set on section registration so an idle monitor re-evaluates its
# cadence immediately instead of finishing an idle sleep first
_monitor_wake = threading.Event()
# hot-path guard: checkpoint() costs one global read when nothing is
# pending (it is threaded through per-batch loops via inject.fire and
# utils/hostsync)
_any_pending = False
# target poll cadence (spark.rapids.tpu.watchdog.pollMs, refreshed at
# section registration); the monitor also adapts to the shortest
# active deadline so short test deadlines detect promptly
_poll_target_s = 0.025


def adopt_thread(owner_ident: int) -> None:
    """Sections opened and checkpoints hit on the calling thread act
    for ``owner_ident`` (the pipeline worker adopts its driver)."""
    _adopted[threading.get_ident()] = owner_ident


def release_thread() -> None:
    _adopted.pop(threading.get_ident(), None)


def disown(ident: int) -> None:
    """Sever ``ident``'s adoption from the outside — used when a
    driver abandons a wedged worker: the zombie must not consume the
    driver's NEXT attempt's one-shot cancellation token when it
    eventually unwedges and checkpoints, and its still-open sections
    must not trip spurious faults onto that attempt either."""
    _adopted.pop(ident, None)
    with _lock:
        stale = [sid for sid, s in _sections.items()
                 if s.opener == ident]
        for sid in stale:
            del _sections[sid]


def purge_owner(owner_ident: int) -> None:
    """Drop every adoption mapping TO ``owner_ident`` plus any token
    still parked for it — the query-exit counterpart of
    :func:`disown` (serving/context.QueryContext.__exit__).  The OS
    reuses thread idents: a stale worker adoption would deliver a NEW
    query's cancellation to this dead query's token, and a stale
    parked token would cancel whatever unrelated query next runs on a
    recycled owner ident."""
    global _any_pending
    from spark_rapids_tpu.robustness.inject import purge_adoptions
    purge_adoptions(_adopted, owner_ident)
    with _lock:
        _pending.pop(owner_ident, None)
        _any_pending = bool(_pending)


def _effective_ident() -> int:
    ident = threading.get_ident()
    return _adopted.get(ident, ident)


def checkpoint() -> None:
    """Cooperative cancellation point: raise the pending TimeoutFault
    for this thread's query, if the monitor parked one.  One-shot —
    delivery clears the token so the ladder's next attempt starts
    clean."""
    global _any_pending
    if not _any_pending:
        return
    ident = _effective_ident()
    with _lock:
        fault = _pending.pop(ident, None)
        _any_pending = bool(_pending)
    if fault is None:
        return
    watchdog_metrics.cancel(fault.point)
    try:
        _emit(None, "WatchdogCancel", point=fault.point,
              deadlineMs=fault.deadline_ms, elapsedMs=fault.elapsed_ms)
    except Exception:
        pass  # a log-write failure must not mask the TimeoutFault
    raise fault


def clear_thread() -> None:
    """Drop any pending cancellation for this thread's query.  Called
    at each query attempt boundary so a token left behind by an
    attempt that died of a *different* exception cannot leak into the
    retry."""
    global _any_pending
    with _lock:
        _pending.pop(_effective_ident(), None)
        _any_pending = bool(_pending)


def _emit(session, event: str, **fields) -> None:
    from spark_rapids_tpu.utils.events import emit_on_session
    emit_on_session(event, session=session, **fields)


def _active_session():
    try:
        from spark_rapids_tpu.api.session import TpuSession
        return TpuSession._active
    except ImportError:  # torn-down interpreter only
        return None


def _resolve_deadline_ms(point: str, deadline_ms, session) -> float:
    """Explicit arg > per-point conf > calibrated p99 > default conf;
    0/None disables.  Returns 0.0 when the section should not be
    monitored.  The calibrated tier (robustness/grayfailure.py
    DeadlineCalibrator, armed by fleet.grayFailure.enabled) replaces
    only the implicit DEFAULT: an explicit argument or a per-point conf
    keeps operator control."""
    global _poll_target_s
    conf = getattr(session, "conf", None) if session is not None else None
    if conf is not None:
        from spark_rapids_tpu.config import rapids_conf as rc
        if not conf.get(rc.WATCHDOG_ENABLED):
            return 0.0
        if deadline_ms is None:
            raw = conf.settings.get(rc._WATCHDOG_DEADLINE_PREFIX + point)
            if raw is not None:
                deadline_ms = int(raw)  # explicit per-point conf wins
            else:
                cal = getattr(session, "gray_deadlines", None)
                if cal is not None:
                    deadline_ms = cal.deadline_ms(point)
                if deadline_ms is None:
                    deadline_ms = conf.watchdog_deadline_ms(point)
        _poll_target_s = conf.get(rc.WATCHDOG_POLL_MS) / 1e3
    return float(deadline_ms or 0)


def _ensure_monitor() -> None:
    global _monitor
    if _monitor is not None and _monitor.is_alive():
        return
    with _lock:
        if _monitor is not None and _monitor.is_alive():
            return
        _monitor = threading.Thread(
            target=_monitor_loop, name="tpu-watchdog", daemon=True)
        _monitor.start()


def _monitor_loop() -> None:
    global _any_pending
    while True:
        with _lock:
            active = list(_sections.values())
        now = time.monotonic()
        min_deadline = None
        for s in active:
            if s.tripped:
                continue
            if now >= s.deadline_at:
                s.tripped = True
                elapsed_ms = (now - s.started) * 1e3
                overrun_ms = (now - s.deadline_at) * 1e3
                fault = F.TimeoutFault(s.point, s.deadline_s * 1e3,
                                       elapsed_ms)
                with _lock:
                    # never overwrite an earlier pending fault — the
                    # first overrun is the root cause
                    _pending.setdefault(s.owner, fault)
                    _any_pending = True
                watchdog_metrics.trip(s.point, overrun_ms)
                try:
                    # stamp the OWNING query's id: the monitor thread
                    # has no query of its own, and under concurrent
                    # queries a session-global "current qid" would
                    # attribute this trip to whichever query last
                    # started (serving/context resolves by owner)
                    from spark_rapids_tpu.serving import context as qc
                    _emit(s.session, "WatchdogTrip", point=s.point,
                          queryId=qc.qid_for_ident(s.owner, s.session),
                          deadlineMs=s.deadline_s * 1e3,
                          elapsedMs=round(elapsed_ms, 3),
                          overrunMs=round(overrun_ms, 3))
                except Exception:
                    # an event-log write failure (disk full — exactly
                    # the degraded world this thread exists for) must
                    # never kill the singleton monitor: the token was
                    # already parked, detection keeps working
                    pass
            else:
                min_deadline = s.deadline_s if min_deadline is None \
                    else min(min_deadline, s.deadline_s)
        _reap_dead_owners()
        if min_deadline is None:
            _monitor_wake.wait(_IDLE_SLEEP_S)
        else:
            _monitor_wake.wait(
                min(max(min(min_deadline / 5, _poll_target_s),
                        _POLL_MIN_S), _POLL_MAX_S))
        _monitor_wake.clear()


def _reap_dead_owners() -> None:
    """Drop pending faults whose owning thread is gone: a token the
    owner can never consume (the thread died without a final
    checkpoint) would pin ``_any_pending`` — a per-checkpoint lock for
    the process's life — and could be mis-delivered to an unrelated
    thread that recycles the ident."""
    global _any_pending
    if not _pending:
        return
    live = {t.ident for t in threading.enumerate()}
    with _lock:
        for ident in [i for i in _pending if i not in live]:
            del _pending[ident]
        _any_pending = bool(_pending)


@contextmanager
def section(point: str, deadline_ms: Optional[float] = None,
            session=None):
    """Monitor the enclosed region: if it runs past its deadline the
    watchdog parks a TimeoutFault on the owning thread's token.  Yields
    the :class:`Section`, or None when monitoring is disabled for this
    point.  Long-lived sections become heartbeat-style by calling
    ``.beat()`` on progress — the deadline then measures silence, not
    total elapsed time (exec/pipeline.py's worker does this).

    Entry and (clean) exit are checkpoints: a region that finishes
    *after* its trip still surfaces the fault at the boundary —
    deadlines are a contract, and recovery re-runs with correct
    results either way."""
    checkpoint()
    if session is None:
        session = _active_session()
    ms = _resolve_deadline_ms(point, deadline_ms, session)
    # every monitored section doubles as a tracing span (the section
    # taxonomy IS most of the span taxonomy: reader pulls, exchange
    # launches, host syncs, UDF/pipeline waits, checkpoint writes).
    # "query" is excluded — it stays open across the QueryEnd drain,
    # whose wall clock already covers it.
    sp = tracing.span(point) if point != "query" else None
    # self-calibration: clean section exits feed the per-point wall
    # evidence the DeadlineCalibrator derives future deadlines from
    # (None unless fleet.grayFailure.enabled — a single getattr here)
    cal = getattr(session, "gray_deadlines", None) \
        if point != "query" else None
    if ms <= 0:
        t0 = time.monotonic() if cal is not None else 0.0
        try:
            if sp is None:
                yield None
            else:
                with sp:
                    yield None
        finally:
            if cal is not None:
                cal.observe(point, (time.monotonic() - t0) * 1e3)
        return
    s = Section(point, ms / 1e3, _effective_ident(), session)
    with _lock:
        _sections[s.id] = s
    _monitor_wake.set()
    _ensure_monitor()
    try:
        if sp is None:
            yield s
        else:
            with sp:
                yield s
    finally:
        with _lock:
            _sections.pop(s.id, None)
        if cal is not None and not s.tripped:
            # tripped sections are excluded: a wedge's wall is not
            # evidence of the point's healthy latency
            cal.observe(point, (time.monotonic() - s.started) * 1e3)
    checkpoint()  # after finally: never masks an in-flight exception


@contextmanager
def query_scope(session, deadline_ms: Optional[float] = None):
    """One query attempt's watchdog envelope: clears any stale token
    left by a previous attempt, then monitors whole-query wall time
    under ``spark.rapids.tpu.watchdog.queryDeadlineMs`` (0 = off)."""
    clear_thread()
    if deadline_ms is None:
        conf = getattr(session, "conf", None)
        if conf is not None:
            from spark_rapids_tpu.config import rapids_conf as rc
            deadline_ms = conf.get(rc.WATCHDOG_QUERY_DEADLINE_MS)
    with section("query", deadline_ms=deadline_ms or 0,
                 session=session):
        yield
