"""Fault taxonomy: classify every failure the engine can see.

The reference keeps failure handling scattered — RMM alloc callbacks
decide what an OOM means, the UCX transport decides what a peer death
means, each operator decides what its own retry means.  This module is
the single classification authority for the TPU port: ``classify``
turns any exception into a ``Fault`` with a *kind* (what broke) and a
*severity* (what recovery is allowed to do about it):

- ``RETRYABLE``  — transient; re-running the same work can succeed
  (device OOM after a spill, a reader hiccup, a preempted step).
- ``DEGRADABLE`` — deterministic at this plan shape; only *changing*
  the plan can succeed (demote the distributed plan to one device,
  fall back to CPU, evaluate a UDF inline).
- ``FATAL``      — a real error (user bug, corrupted input, host
  memory exhaustion); recovery must re-raise, never mask it.

Anything unrecognized is FATAL by default — the ladder never eats an
exception it cannot name.
"""

from __future__ import annotations

from dataclasses import dataclass

# severities (ordered by how much the recovery path may change the plan)
RETRYABLE = "RETRYABLE"
DEGRADABLE = "DEGRADABLE"
FATAL = "FATAL"

# markers jax/XLA use for preemption-style runtime failures that are
# worth re-driving (TPU maintenance events, donated-buffer races after
# an aborted step, transport resets) — deliberately NOT including
# RESOURCE_EXHAUSTED, which is_oom owns
_PREEMPTION_MARKERS = ("UNAVAILABLE", "ABORTED", "DATA_LOSS",
                       "DEADLINE_EXCEEDED", "preempted",
                       "Socket closed", "Connection reset")


@dataclass(frozen=True)
class Fault:
    """One classified failure: ``kind`` names the subsystem/failure
    mode, ``severity`` bounds what recovery may do."""

    kind: str
    severity: str

    @property
    def retryable(self) -> bool:
        return self.severity == RETRYABLE

    @property
    def fatal(self) -> bool:
        return self.severity == FATAL


# ---------------------------------------------------------- fault types --
class InjectedFault(Exception):
    """Base for faults raised by the injection registry (inject.py).
    Subclasses pin the kind/severity the real failure would have, so
    the recovery path under test is exactly the production one."""

    kind = "injected"
    severity = RETRYABLE

    def __init__(self, point: str, note: str = ""):
        super().__init__(f"injected fault at {point!r}"
                         + (f": {note}" if note else ""))
        self.point = point


class InjectedReaderFault(InjectedFault, OSError):
    """Synthetic transient I/O error in a file scan."""
    kind = "io_read"


class InjectedShuffleFault(InjectedFault):
    """Synthetic failure inside the all-to-all shuffle exchange."""
    kind = "shuffle"


class InjectedHostSyncFault(InjectedFault):
    """Synthetic multi-host phase-boundary sync failure."""
    kind = "host_sync"


class InjectedSpillFault(InjectedFault, OSError):
    """Synthetic disk-tier spill I/O error."""
    kind = "spill_io"


class InjectedWorkerFault(InjectedFault):
    """Synthetic UDF worker-pool death (BrokenProcessPool analog)."""
    kind = "udf_worker"
    severity = DEGRADABLE


class HostLossFault(InjectedFault):
    """A fleet peer host went silent: its heartbeat record aged past
    ``heartbeatMs * missedBeatsFatal`` in the HostMembership registry
    (parallel/mesh.py), or the membership layer was told of the loss
    directly (a coordinator-level eviction, an injected kill).
    RETRYABLE — but unlike a transient retry, identical re-execution
    on the SAME mesh cannot succeed while the host stays dark, so the
    recovery ladder enters at its ``shrink`` rung: rebuild the mesh
    over the surviving hosts, clear layout-keyed lineage, re-drive.
    Subclasses InjectedFault so the chaos registry can raise it at
    the ``fleet.heartbeat`` point with production classification."""

    kind = "host_loss"
    severity = RETRYABLE

    def __init__(self, point: str = "fleet.heartbeat", note: str = "",
                 host: int = -1):
        super().__init__(point, note or (f"host {host} silent"
                                         if host >= 0 else ""))
        self.host = host


class TimeoutFault(Exception):
    """A watchdog deadline fired: a monitored section (reader decode,
    shuffle launch, the pipeline worker heartbeat, whole-query wall
    time) overran its budget and the overrun was delivered at the
    driving thread's next cooperative checkpoint
    (robustness/watchdog.py).  Retryable — a hang is the transport/
    preemption failure mode that doesn't bother to raise, and
    re-driving the query re-establishes the stuck collective/reader
    exactly like a preemption retry does."""

    kind = "timeout"
    severity = RETRYABLE

    def __init__(self, point: str, deadline_ms: float,
                 elapsed_ms: float):
        super().__init__(
            f"watchdog deadline exceeded at {point!r}: "
            f"{elapsed_ms:.0f}ms elapsed > {deadline_ms:.0f}ms deadline")
        self.point = point
        self.deadline_ms = deadline_ms
        self.elapsed_ms = elapsed_ms


class CorruptionFault(Exception):
    """A spill payload failed checksum verification on restore (or its
    disk frame no longer decodes).  The corrupt batch is dropped by the
    raising site before this propagates — wrong bytes must never reach
    an operator.  Degradable: the stored replica is gone, so only
    re-running from source (a re-planned attempt re-reads inputs) can
    produce the data again; retrying the same restore would re-read
    the same rot."""

    kind = "spill_corruption"
    severity = DEGRADABLE

    def __init__(self, tier: str, detail: str = ""):
        super().__init__(
            f"spill payload corruption detected at {tier} tier"
            + (f": {detail}" if detail else ""))
        self.tier = tier


class ShuffleSlotOverflow(Exception):
    """A speculative (EMA-predicted) all-to-all slot was smaller than
    the launch's true max slice — rows would have been dropped.  The
    exchange site handles this LOCALLY: it re-runs the launch at full
    capacity (never wrong bytes) and records the fault on the recovery
    trail as a degradable action (the plan was fine; only the slot
    prediction was stale, and the planner has already grown/reset it).
    Degradable if it ever escapes: identical re-execution with the same
    stale slot cannot succeed, a re-planned attempt re-sizes."""

    kind = "shuffle_slot"
    severity = DEGRADABLE

    def __init__(self, site: str, slot: int, capacity: int):
        super().__init__(
            f"speculative shuffle slot overflow at {site}: slot {slot} "
            f"< true max slice (capacity {capacity}); re-running at "
            "full capacity")
        self.site = site
        self.slot = slot
        self.capacity = capacity


class AsyncExchangeOverflow(ShuffleSlotOverflow):
    """A DEFERRED slot verification (async exchange window,
    parallel/exchange_async.py) found the speculative slot too small
    AFTER downstream compute already consumed the truncated frame — the
    local full-capacity re-run is no longer enough, the whole attempt
    must re-drive.  RETRYABLE, not degradable: the slot planner latched
    the site off speculation when the flag came back, and the planner
    runs recovery re-attempts on the synchronous stats-sized path, so
    the re-driven attempt is NOT identical re-execution and succeeds on
    the mesh."""

    severity = RETRYABLE

    def __init__(self, site: str, slot: int, capacity: int):
        super().__init__(site, slot, capacity)


class EncodingOverflowFault(Exception):
    """An encoded-execution dictionary outgrew
    ``spark.rapids.tpu.encoding.execution.maxDictSize`` mid-query.
    Codes already issued are stable and correct, but the operator
    cannot un-encode batches it has processed, so the raising site
    LATCHES encoded execution off for the session before raising.
    RETRYABLE, not degradable: every attempt re-plans from the logical
    plan, and with the latch set the re-planned attempt takes the
    decoded host-dictionary path — not identical re-execution, exact
    results."""

    kind = "encoding_overflow"
    severity = RETRYABLE

    def __init__(self, site: str, size: int, limit: int):
        super().__init__(
            f"encoded-execution dictionary at {site} grew to {size} "
            f"distinct values > maxDictSize {limit}; encoded execution "
            "latched off, re-planning on the decoded path")
        self.site = site
        self.size = size
        self.limit = limit


class ReplanRequested(Exception):
    """The cost model (plan/costmodel.py) observed launch statistics
    that contradict its plan-time decision past the hysteresis band —
    e.g. the measured per-destination histogram says a ragged exchange
    would beat the uniform slot the plan chose by >= hysteresis x.
    The fresh evidence was folded into the observation store BEFORE
    raising, so the re-planned attempt decides the measured-optimal
    strategy.  RETRYABLE, not degradable: the ladder's retry rung
    keeps the mesh layout, completed stages splice from the
    stage-checkpoint lineage, and only the contradicted subtree
    re-plans — a non-failure entry point into the recovery re-drive.
    The model arms at most ONE replan per query, so a borderline
    workload cannot oscillate."""

    kind = "replan"
    severity = RETRYABLE

    def __init__(self, site: str, planned: str, better: str,
                 ratio: float):
        super().__init__(
            f"cost-model replan requested at {site}: measured stats "
            f"say {better!r} beats the planned {planned!r} by "
            f"{ratio:.1f}x (>= hysteresis); re-driving with fresh "
            "evidence")
        self.site = site
        self.planned = planned
        self.better = better
        self.ratio = ratio


class AdmissionFault(Exception):
    """The serving layer rejected this query at (or after) admission:
    the fair admission queue timed out / overflowed, or the query blew
    through a per-query budget after the in-query degradations (queue,
    then spill) were exhausted.  FATAL *for this query* by design — a
    rejection is a typed answer the client must see, and re-driving it
    down the ladder would re-consume the very capacity the admission
    layer is protecting.  Other queries on the session are untouched:
    that containment is the whole point (serving/admission.py)."""

    kind = "admission"
    severity = FATAL

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(
            f"query rejected by admission control ({reason})"
            + (f": {detail}" if detail else ""))
        self.reason = reason


class BudgetExhaustedFault(AdmissionFault):
    """A per-query budget (memory bytes, host syncs, deadline) ran out
    and the degradation ladder for budgets — queue, spill own batches,
    reject — reached its last rung.  Carries which budget died so the
    BudgetExhausted event and the client error are actionable."""

    kind = "budget"

    def __init__(self, budget: str, used, limit):
        super().__init__(
            "budget", f"{budget} budget exhausted ({used} > {limit})")
        self.budget = budget
        self.used = used
        self.limit = limit


class HostSyncError(RuntimeError):
    """Multi-host phase boundary failed: the cross-process stats
    all-gather timed out or the controllers diverged.  Retryable — the
    SPMD contract re-establishes on the next collective."""


class SpillIOError(OSError):
    """Disk-tier spill I/O failed (write or read-back).  Retryable:
    the batch is still resident at its previous tier, nothing is
    lost, and the disk may only be transiently full/unreachable."""


def _is_xla_runtime_error(exc: BaseException) -> bool:
    # by name, not import: jaxlib moves this class between releases,
    # and classification must not hard-depend on jaxlib internals
    return any(c.__name__ in ("XlaRuntimeError", "JaxRuntimeError")
               for c in type(exc).__mro__)


def classify(exc: BaseException) -> Fault:
    """Map an exception to the taxonomy.  Precedence: injected faults
    declare themselves; device OOM (via ``memory/retry.is_oom``) next;
    then the engine's own typed failures; unknown -> FATAL."""
    if isinstance(exc, InjectedFault):
        return Fault(exc.kind, exc.severity)
    if isinstance(exc, TimeoutFault):
        return Fault(exc.kind, exc.severity)
    if isinstance(exc, AdmissionFault):
        # covers BudgetExhaustedFault too: a typed per-query rejection
        # the ladder must hand back, never absorb
        return Fault(exc.kind, exc.severity)
    if isinstance(exc, CorruptionFault):
        return Fault(exc.kind, exc.severity)
    if isinstance(exc, ShuffleSlotOverflow):
        return Fault(exc.kind, exc.severity)
    if isinstance(exc, EncodingOverflowFault):
        return Fault(exc.kind, exc.severity)
    if isinstance(exc, ReplanRequested):
        return Fault(exc.kind, exc.severity)
    from spark_rapids_tpu.memory.retry import SplitAndRetryOOM, is_oom
    if isinstance(exc, SplitAndRetryOOM):
        # operator-level split already bottomed out at the 1-row floor;
        # only a plan change (smaller scan batches, CPU) can help
        return Fault("device_oom", DEGRADABLE)
    if is_oom(exc):
        return Fault("device_oom", RETRYABLE)
    if isinstance(exc, HostSyncError):
        return Fault("host_sync", RETRYABLE)
    if isinstance(exc, SpillIOError):
        return Fault("spill_io", RETRYABLE)
    try:
        from concurrent.futures.process import BrokenProcessPool
        if isinstance(exc, BrokenProcessPool):
            # pool infrastructure death; the worker pool usually
            # degrades inline before this escapes to a query
            return Fault("udf_worker", DEGRADABLE)
    except ImportError:  # torn-down interpreter only
        pass
    if _is_xla_runtime_error(exc):
        text = str(exc)
        if any(m in text for m in _PREEMPTION_MARKERS):
            return Fault("preemption", RETRYABLE)
        return Fault("xla_runtime", FATAL)
    return Fault("unknown", FATAL)
