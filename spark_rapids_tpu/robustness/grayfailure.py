"""Gray-failure resilience: fail-slow detection, hedged execution,
quarantine/rejoin tracking, and self-calibrating deadlines.

PR 18's membership layer answers fail-STOP — a silent host is declared
lost and the shrink rung rebuilds the mesh.  The dominant real-world
failure mode is fail-SLOW: a thermally-throttled chip, a degraded DCN
link, a noisy co-tenant.  A fail-slow host never trips the heartbeat
loss judgment; it just stalls every DCN-spanning collective at its own
pace.  This module treats asymmetric slowness as a first-class fault
with its own detection, mitigation, and recovery rungs:

- :class:`HostHealthTracker` folds a per-host health score from
  heartbeat-interval jitter (gossiped through the membership beat
  records) and per-host wall observations at the evidence points
  (``dist.host_sync``, ``exchange.host_staging``).  A host
  persistently slower than the fleet median by
  ``fleet.suspectFactor`` over a rolling window becomes SUSPECT — a
  typed ``HostSuspect`` event, never a hard fault on its own.
- :func:`hedged_call` re-dispatches a SUSPECT host's host-side shard
  work (host staging, per-member replay) on a healthy path when it
  overruns an adaptive percentile deadline.  First result wins; the
  loser is discarded with ``hedgesFired``/``hedgesWon``/
  ``duplicatesSuppressed`` pinned.  Only *pure host-side* work may
  hedge — a collective is a fleet-wide rendezvous and re-entering one
  concurrently would wedge or corrupt the SPMD program, so collectives
  never hedge (docs/robustness.md "hedge eligibility").
- quarantine/rejoin bookkeeping: SUSPECT past
  ``fleet.quarantineAfterMs`` requests a soft-shrink drain (the
  session applies it at a safe query boundary); a quarantined host
  whose score recovers for ``fleet.rejoinAfterMs`` requests a rejoin.
- :class:`DeadlineCalibrator` derives watchdog per-point deadlines
  from observed p99 walls (floor/ceiling confs retain operator
  control) instead of hand-tuned static confs.

Everything hangs off ``session.gray_health`` / ``session.gray_deadlines``
— both None unless ``spark.rapids.tpu.fleet.grayFailure.enabled``, so
the default engine stays bit-identical (every hook is a None check).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

# evidence pseudo-point for heartbeat-interval jitter (the walls of the
# other evidence points are real observed durations; this one is the
# gap between a peer's successive beat records)
HEARTBEAT_POINT = "fleet.heartbeat"
# points whose walls feed the per-host health score
EVIDENCE_POINTS = (HEARTBEAT_POINT, "dist.host_sync",
                   "exchange.host_staging")

# host health states
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"

# thread-local hedge context: the re-dispatched (hedge) leg of a
# hedged_call runs with this set so the work body routes through the
# ``<point>.hedge`` injection/watchdog point — the simulated analog of
# dispatching on a DIFFERENT (healthy) host, where the sick host's
# armed delay rules do not apply
_tls = threading.local()


def in_hedge() -> bool:
    return getattr(_tls, "depth", 0) > 0


def hedge_point(point: str) -> str:
    """Effective injection/watchdog point name for the current leg:
    the hedge leg fires ``<point>.hedge`` (registered alongside the
    primary point) so chaos rules wedging the sick host's path do not
    wedge the healthy re-dispatch."""
    return point + ".hedge" if in_hedge() else point


def _percentile(vals: List[float], p: float) -> float:
    """Nearest-rank percentile of an unsorted sample (no numpy on the
    hot path)."""
    s = sorted(vals)
    if not s:
        return 0.0
    k = max(0, min(len(s) - 1, int(round(p * len(s) + 0.5)) - 1))
    return s[k]


def _median(vals) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class HostHealthTracker:
    """Per-host health scoring plus hedge/quarantine bookkeeping.

    Evidence arrives from three producers: the membership layer feeds
    peers' heartbeat intervals and gossiped per-point walls
    (``observe_beat``/``observe_peer_walls``, read from the beat
    records every ``check()``), and the engine's own host-side work
    feeds local walls (``observe_wall``).  ``poll()`` folds the
    evidence into per-host states and emits the typed transition
    events; the session applies quarantine/rejoin requests at safe
    query boundaries (``quarantine_due``/``rejoin_due``).

    The health score of a host is the worst (max) ratio, over evidence
    points with at least ``min_samples`` observations, of the host's
    median wall to the fleet's median-of-host-medians at that point —
    robust to one outlier observation AND to one outlier host
    dragging the fleet baseline."""

    def __init__(self, session=None, host_id: int = 0, n_hosts: int = 1,
                 suspect_factor: float = 3.0, window: int = 32,
                 min_samples: int = 3, quarantine_after_ms: int = 60_000,
                 rejoin_after_ms: int = 30_000,
                 hedge_percentile: float = 0.95,
                 hedge_margin: float = 2.0, hedge_floor_ms: int = 25):
        self._session = session
        self.host = int(host_id)
        self.n_hosts = int(n_hosts)
        self.suspect_factor = float(suspect_factor)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.quarantine_after_ms = int(quarantine_after_ms)
        self.rejoin_after_ms = int(rejoin_after_ms)
        self.hedge_percentile = float(hedge_percentile)
        self.hedge_margin = float(hedge_margin)
        self.hedge_floor_ms = int(hedge_floor_ms)
        self._lock = threading.Lock()
        # (host, point) -> rolling wall observations [ms]
        self._walls: Dict[Tuple[int, str], deque] = {}
        # host -> last beat ts seen (for interval derivation)
        self._last_beat_ts: Dict[int, float] = {}
        self.state: Dict[int, str] = {}
        self.scores: Dict[int, float] = {}
        # host -> monotonic time it entered SUSPECT / recovered while
        # quarantined (the quarantine / rejoin clocks)
        self._suspect_since: Dict[int, float] = {}
        self._recovered_since: Dict[int, float] = {}
        # score timeline for the profiling "Fleet health" section:
        # emitted on the event log, mirrored here for tests
        self.transitions: List[Dict[str, object]] = []
        self.counters: Dict[str, int] = {
            "hedgesFired": 0, "hedgesWon": 0, "duplicatesSuppressed": 0,
            "suspects": 0, "recoveries": 0, "quarantines": 0,
            "rejoins": 0}

    # ------------------------------------------------------- evidence --
    def observe_wall(self, host: int, point: str, wall_ms: float
                     ) -> None:
        """One wall observation for ``host`` at an evidence point.
        Local work attributes to the local host; membership gossip
        attributes to peers.  Also persisted on the ObservationStore's
        per-host axis (``host<h>@<point>`` sites) so evidence survives
        process restarts alongside the per-site records."""
        host = int(host)
        if host < 0:
            return
        with self._lock:
            dq = self._walls.setdefault((host, point),
                                        deque(maxlen=self.window))
            dq.append(float(wall_ms))
        from spark_rapids_tpu.utils import tracing
        tracing.observe_host(host, point, wall_ms=float(wall_ms))

    def observe_beat(self, host: int, beat_ts: float) -> None:
        """Derive the heartbeat-interval evidence from a peer's beat
        record: the gap between successive ``ts`` stamps IS the
        interval the peer achieved (a wedged writer shows up as a
        stretched interval long before the fatal silence window)."""
        prev = self._last_beat_ts.get(host)
        self._last_beat_ts[host] = beat_ts
        if prev is not None and beat_ts > prev:
            self.observe_wall(host, HEARTBEAT_POINT,
                              (beat_ts - prev) * 1000.0)

    def observe_peer_walls(self, host: int,
                           walls: Dict[str, float]) -> None:
        """Fold a peer's gossiped per-point EMA walls (carried on its
        beat record) into its evidence."""
        for point, ms in (walls or {}).items():
            if point in EVIDENCE_POINTS:
                self.observe_wall(host, point, float(ms))

    def local_walls(self) -> Dict[str, float]:
        """This host's latest per-point walls — the gossip payload its
        next beat record carries."""
        with self._lock:
            out = {}
            for (h, point), dq in self._walls.items():
                if h == self.host and dq and point != HEARTBEAT_POINT:
                    out[point] = round(_median(dq), 3)
            return out

    # -------------------------------------------------------- scoring --
    def score(self, host: int) -> float:
        """Worst per-point slowness ratio vs the fleet baseline (1.0 =
        at the fleet median; below min_samples everywhere = 1.0).  The
        baseline is the median of the OTHER hosts' medians — in a
        small fleet the scored host's own evidence would drag the
        baseline toward itself and mask the asymmetry."""
        with self._lock:
            worst = 1.0
            for point in EVIDENCE_POINTS:
                mine = self._walls.get((int(host), point))
                if not mine or len(mine) < self.min_samples:
                    continue
                peers = [
                    _median(dq) for (h, p), dq in self._walls.items()
                    if p == point and h != int(host)
                    and len(dq) >= self.min_samples]
                if not peers:
                    continue  # no fleet baseline to compare against
                fleet = _median(peers)
                if fleet <= 0:
                    continue
                worst = max(worst, _median(mine) / fleet)
            return worst

    def _emit(self, event: str, **fields) -> None:
        try:
            from spark_rapids_tpu.utils.events import emit_on_session
            emit_on_session(event, self._session, **fields)
        except Exception:
            pass  # health tracking must work without an event log

    def poll(self) -> Dict[int, str]:
        """Recompute every known host's state and emit transition
        events.  Never touches the mesh — mitigation is the session's
        (safe-boundary) job; detection alone is side-effect free."""
        now = time.monotonic()
        with self._lock:
            hosts = sorted({h for h, _ in self._walls})
        for h in hosts:
            if h == self.host:
                continue
            sc = self.score(h)
            self.scores[h] = round(sc, 3)
            state = self.state.get(h, HEALTHY)
            if state == QUARANTINED:
                # recovery clock: score back under the threshold and
                # staying there arms the rejoin request
                if sc < self.suspect_factor:
                    self._recovered_since.setdefault(h, now)
                else:
                    self._recovered_since.pop(h, None)
                continue
            if sc >= self.suspect_factor and state != SUSPECT:
                self.state[h] = SUSPECT
                self._suspect_since[h] = now
                self.counters["suspects"] += 1
                rec = {"kind": "suspect", "host": h, "score": sc}
                self.transitions.append(rec)
                self._emit("HostSuspect", host=h, score=round(sc, 3),
                           factor=self.suspect_factor)
            elif sc < self.suspect_factor and state == SUSPECT:
                self.state[h] = HEALTHY
                self._suspect_since.pop(h, None)
                self.counters["recoveries"] += 1
                self.transitions.append(
                    {"kind": "recovered", "host": h, "score": sc})
                self._emit("HostRecovered", host=h,
                           score=round(sc, 3))
        return dict(self.state)

    # ------------------------------------------------------- requests --
    def is_suspect(self, host: int) -> bool:
        return self.state.get(int(host)) in (SUSPECT, QUARANTINED)

    def suspect_hosts(self) -> List[int]:
        return sorted(h for h, s in self.state.items() if s == SUSPECT)

    def quarantine_due(self) -> List[int]:
        """SUSPECT hosts whose degradation outlasted the quarantine
        window — the session drains these through the soft-shrink
        path at the next safe boundary."""
        if self.quarantine_after_ms <= 0:
            return []
        now = time.monotonic()
        return sorted(
            h for h, s in self.state.items()
            if s == SUSPECT and
            (now - self._suspect_since.get(h, now)) * 1000.0
            >= self.quarantine_after_ms)

    def rejoin_due(self) -> List[int]:
        """Quarantined hosts whose recovery outlasted the rejoin
        window — the session restores these at the next safe
        boundary."""
        now = time.monotonic()
        return sorted(
            h for h, s in self.state.items()
            if s == QUARANTINED and h in self._recovered_since and
            (now - self._recovered_since[h]) * 1000.0
            >= self.rejoin_after_ms)

    def mark_quarantined(self, host: int) -> None:
        self.state[int(host)] = QUARANTINED
        self._suspect_since.pop(int(host), None)
        self._recovered_since.pop(int(host), None)
        self.counters["quarantines"] += 1
        self.transitions.append({"kind": "quarantine", "host": host,
                                 "score": self.scores.get(host, 0.0)})

    def mark_rejoined(self, host: int) -> None:
        self.state[int(host)] = HEALTHY
        self._recovered_since.pop(int(host), None)
        self.counters["rejoins"] += 1
        self.transitions.append({"kind": "rejoin", "host": host,
                                 "score": self.scores.get(host, 0.0)})
        # a rejoined host starts with a clean slate: its quarantine-era
        # evidence (stale, observed while it did no fleet work) must
        # not re-trip SUSPECT on the first post-rejoin poll
        with self._lock:
            for key in [k for k in self._walls if k[0] == int(host)]:
                del self._walls[key]
        self.scores.pop(int(host), None)

    # -------------------------------------------------------- hedging --
    def hedge_deadline_ms(self, point: str) -> float:
        """Adaptive hedge deadline for ``point``: the configured
        percentile of the recent healthy-host walls, scaled by the
        hedge margin and floored — a freshly-started fleet with no
        evidence hedges at the floor."""
        with self._lock:
            healthy: List[float] = []
            for (h, p), dq in self._walls.items():
                if p == point and \
                        self.state.get(h, HEALTHY) == HEALTHY:
                    healthy.extend(dq)
        if not healthy:
            return float(self.hedge_floor_ms)
        return max(float(self.hedge_floor_ms),
                   _percentile(healthy, self.hedge_percentile)
                   * self.hedge_margin)

    def query_counters(self) -> Dict[str, int]:
        """Cumulative counter snapshot (QueryEnd computes per-query
        deltas against this)."""
        with self._lock:
            return dict(self.counters)

    @staticmethod
    def counters_delta(after: Dict[str, int], before: Dict[str, int]
                       ) -> Dict[str, int]:
        return {k: after.get(k, 0) - before.get(k, 0) for k in after}


class DeadlineCalibrator:
    """Self-calibrating watchdog deadlines (tentpole layer 4).

    The watchdog's section exits feed per-point wall observations;
    once a point has ``minSamples`` the resolved deadline becomes
    ``clamp(p99 * marginFactor, floorMs, ceilingMs)`` instead of the
    static conf value — detection tightens as evidence accumulates
    instead of being hand-tuned per topology (the dcnDeadlineScale
    knob keeps applying to the static path for points still below
    minSamples).  Explicit ``deadline_ms`` arguments and per-point
    conf overrides always win: calibration replaces only the implicit
    default."""

    def __init__(self, floor_ms: int = 50, ceiling_ms: int = 600_000,
                 margin: float = 4.0, min_samples: int = 8,
                 window: int = 128):
        self.floor_ms = float(floor_ms)
        self.ceiling_ms = float(ceiling_ms)
        self.margin = float(margin)
        self.min_samples = int(min_samples)
        self._lock = threading.Lock()
        self._walls: Dict[str, deque] = {}

    def observe(self, point: str, wall_ms: float) -> None:
        with self._lock:
            self._walls.setdefault(
                point, deque(maxlen=128)).append(float(wall_ms))

    def deadline_ms(self, point: str) -> Optional[float]:
        """Calibrated deadline for ``point``; None below minSamples
        (the caller falls back to the static conf chain)."""
        with self._lock:
            dq = self._walls.get(point)
            if not dq or len(dq) < self.min_samples:
                return None
            vals = list(dq)
        p99 = _percentile(vals, 0.99)
        return min(self.ceiling_ms, max(self.floor_ms,
                                        p99 * self.margin))

    def snapshot(self) -> Dict[str, float]:
        out = {}
        with self._lock:
            points = list(self._walls)
        for p in points:
            d = self.deadline_ms(p)
            if d is not None:
                out[p] = round(d, 1)
        return out


# ------------------------------------------------------- session hooks --

def tracker_for(session) -> Optional[HostHealthTracker]:
    return getattr(session, "gray_health", None) \
        if session is not None else None


def note_wall(session, point: str, wall_ms: float,
              host: Optional[int] = None) -> None:
    """Attribute one local wall observation; no-op without a tracker.
    ``host`` defaults to the session's own fleet host."""
    tracker = tracker_for(session)
    if tracker is None:
        return
    tracker.observe_wall(tracker.host if host is None else host,
                         point, wall_ms)


def suspect_host_in(session, mesh) -> int:
    """A SUSPECT host participating in ``mesh``, or -1.  The hedge
    eligibility gate: host-side shard work only hedges when the
    exchange actually spans a host the tracker distrusts."""
    tracker = tracker_for(session)
    if tracker is None or mesh is None:
        return -1
    suspects = {h for h, s in tracker.state.items()
                if s == SUSPECT}
    if not suspects:
        return -1
    try:
        from spark_rapids_tpu.parallel.mesh import mesh_hosts
        hosts = set(mesh_hosts(mesh))
    except Exception:
        return -1
    hit = sorted(suspects & hosts)
    return hit[0] if hit else -1


def hedged_call(session, point: str, host: int,
                fn: Callable[[], object]):
    """Run ``fn`` with hedged re-dispatch when ``host`` is SUSPECT.

    The primary leg runs on a worker thread adopted into the driving
    thread's identity (chaos rules modeling the sick host fire there,
    exactly as on the real dispatch).  If it has not produced within
    the adaptive hedge deadline, the hedge leg re-runs ``fn`` inline
    under the hedge context (``<point>.hedge`` — the healthy-survivor
    path) and the first completed leg wins.  The loser's result is
    discarded (``duplicatesSuppressed``) and the abandoned worker is
    disowned from every attribution registry so its eventual
    completion cannot consume the query's next cancellation token or
    rule budget.  Exactly-once holds structurally: exactly ONE result
    object is returned to the caller, so sinks emit once and the
    lineage log records once.

    ``fn`` MUST be pure host-side work (staging repartitions, member
    replays) — never a collective: both legs may run concurrently.

    Without a tracker, with a healthy host, or in a nested hedge, this
    is exactly ``fn()`` — the default path stays bit-identical."""
    tracker = tracker_for(session)
    if tracker is None or host < 0 or not tracker.is_suspect(host) \
            or in_hedge():
        return fn()
    deadline_ms = tracker.hedge_deadline_ms(point)

    lock = threading.Lock()
    done = threading.Event()
    box: Dict[str, object] = {}  # "value" | "error", set by the winner

    def _claim(key, val) -> bool:
        with lock:
            if "value" in box or "error" in box:
                tracker.counters["duplicatesSuppressed"] += 1
                return False
            box[key] = val
            done.set()
            return True

    from spark_rapids_tpu.exec import pipeline
    from spark_rapids_tpu.serving import context as qc
    # adopt the EFFECTIVE ident: when the caller is itself an adopted
    # pipeline worker, the primary leg must chain to the driving
    # query's identity or thread-scoped chaos rules / cancellation
    # tokens would miss it
    owner = qc.effective_ident()

    def _primary():
        with pipeline.worker_attribution(owner):
            # watchdog identity stays LOCAL: a wedged primary's
            # section trip must park on THIS thread, not on the
            # driving query — the hedge leg (which runs on the
            # driver) would inherit the fault at its first checkpoint
            # and the hedge could never win
            from spark_rapids_tpu.robustness import watchdog
            watchdog.release_thread()
            try:
                val = fn()
            except BaseException as exc:  # noqa: BLE001 — relayed
                _claim("error", exc)
                return
            _claim("value", val)

    t = threading.Thread(target=_primary, daemon=True,
                         name=f"tpu-hedge-primary-{point}")
    t.start()
    if done.wait(deadline_ms / 1000.0):
        if "error" in box:
            raise box["error"]
        return box["value"]

    # primary overran the hedge deadline: re-dispatch on the healthy
    # path, first result wins
    tracker.counters["hedgesFired"] += 1
    tracker._emit("HedgeFired", point=point, host=host,
                  deadlineMs=round(deadline_ms, 1))
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        try:
            hedge_val = fn()
        except BaseException as exc:  # noqa: BLE001
            # hedge leg failed; the primary may still land — give it
            # one more deadline before surfacing the hedge's fault
            if done.wait(deadline_ms / 1000.0):
                if "value" in box:
                    return box["value"]
                raise box["error"]
            raise exc
    finally:
        _tls.depth -= 1
    if _claim("value", hedge_val):
        tracker.counters["hedgesWon"] += 1
        tracker._emit("HedgeWon", point=point, host=host)
        # abandon the wedged primary: sever its adopted identity so
        # its dying fire()/checkpoint() calls cannot consume the
        # query's rule budgets or cancellation token
        if t.ident is not None:
            pipeline.disown_worker(t.ident)
        return hedge_val
    # the primary finished in the hedge's shadow (photo finish): its
    # result was claimed first, ours is the suppressed duplicate
    if "error" in box:
        raise box["error"]
    return box["value"]


def register_hedge_points() -> None:
    """Declare the ``<point>.hedge`` injection points beside their
    primaries so chaos rules can target (or deliberately spare) the
    healthy-survivor leg."""
    from spark_rapids_tpu.robustness import inject
    from spark_rapids_tpu.robustness.faults import InjectedShuffleFault
    inject.register_point("exchange.host_staging.hedge",
                          InjectedShuffleFault)
    inject.register_point("dist.member_replay",
                          InjectedShuffleFault)
    inject.register_point("dist.member_replay.hedge",
                          InjectedShuffleFault)


register_hedge_points()
