"""Continuous micro-batch ingest on checkpoint lineage: crash-consistent
incremental state with epoch semantics.

PR5 made completed exchange stages durable *within* one query (the
per-query :class:`~spark_rapids_tpu.robustness.checkpoint.CheckpointManager`);
this module promotes that log into a **session-persistent
IncrementalStateStore** and turns the checkpoint subsystem from a
failure feature into a latency feature (ROADMAP item 5): a standing
query over an append-only input re-executes only what the appended
files can change, and resumes everything else from state.

The unit of standing work is a :class:`MicroBatchRunner`
(``session.incremental(df)``); each ``runner.tick(new_paths)`` is one
micro-batch with **epoch semantics**:

- the tick executes against the last *committed* epoch; everything it
  writes — the new partial-aggregate state, fresh stage checkpoints —
  lands in a *provisional* epoch;
- the provisional epoch **commits atomically only when the tick
  completes**; any fault mid-tick (chaos-injected or real: reader
  fault, shuffle wedge, spill corruption, watchdog timeout, admission
  reject) **rolls back** to the committed epoch and the tick degrades
  to a full recompute — standing state is never half-updated, a
  degraded tick answers with recomputed (correct) bytes, never wrong
  ones;
- the full robustness stack is live the whole time: every execution
  inside a tick runs through ``DataFrame._execute_batches`` — admission
  control, per-query budgets, the recovery ladder, watchdog deadlines,
  spill integrity and per-query stage checkpoints all apply unchanged.

Two reuse mechanisms compose:

1. **Delta decomposition** (the streaming workload classes): plans of
   shape ``[Sort|Limit|Filter]* <- Aggregate <- [Filter|Project]* <-
   source`` decompose into mergeable partials (sum→sum, count→sum,
   min→min, max→max, avg→(sum,count)).  The tick aggregates ONLY the
   appended files and merges (old-state ⊕ delta) through the engine's
   own aggregate merge discipline — zero re-pulls of already-ingested
   source files.  ``source`` may be the appended fact scan itself, or
   a **delta-join**: ``Join(fact chain, dim subtree)`` where the join
   type preserves per-fact-row locality (inner always; left/semi/anti
   with the fact on the left; right with the fact on the right) — the
   tick joins only the NEW fact batches against the unchanged
   dimension state, whose completed subtrees splice from the lineage
   store, and a dim-side input-fingerprint drift drops the state and
   degrades the tick to full recompute.  Two refinements bound state:
   **windowed aggregation** (group keys from ``functions.window``)
   under ``incremental.watermarkDelayMs`` advances an event-time
   watermark at every commit and evicts expired window buckets
   atomically with it (rollback restores data AND watermark — no
   resurrection of evicted windows, no premature eviction from a
   rolled-back tick); **mergeable top-N**
   (``orderBy(group keys).limit(n)``) trims state and delta partials
   to the top-n rows whenever the sort key set provably makes the
   merge reproduce the one-shot answer bit-for-bit (bare group-key
   sort columns covering every key; value sorts refuse).
2. **Lineage splice** for everything else: the store subclasses the
   PR5 CheckpointManager with ``always_resume`` — stage ids now fold in
   an **input fingerprint** (file list + sizes + mtimes,
   ``checkpoint.input_fingerprint``), so appending files invalidates
   exactly the scan-adjacent subtrees and a full-recompute tick still
   splices unchanged subtrees (a static dimension side of a join, a
   pre-aggregated reference table) via the existing
   ``try_distributed(resume=True)`` machinery.

State lives in the spill catalog at ``INCREMENTAL_STATE_PRIORITY``
(colder than per-query checkpoints — standing state never competes
with live queries for HBM) under its own budget/tier confs
(``spark.rapids.tpu.incremental.enabled`` / ``.maxStateBytes`` /
``.tiers``); eviction or CRC failure of a state entry degrades the
next tick to recompute — it never fails a tick and never returns wrong
bytes.  Observable end to end: ``StateCommit`` / ``StateRollback`` /
``StateEvict`` / ``IncrementalResume`` events → eventlog
``QueryInfo.incremental`` → profiling "Continuous ingest" section and
health checks.
"""

from __future__ import annotations

import copy
import hashlib
import itertools
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.robustness.checkpoint import (CheckpointManager,
                                                    CheckpointMetrics)
from spark_rapids_tpu.robustness.inject import (fire, fire_mutate,
                                                register_point)

# chaos surface: a raise/delay rule on the write covers a wedged state
# commit; a corrupt rule on the restore flips state bytes so the CRC
# gate has real rot to catch (fire_mutate site); the sink point sits
# in the emission hand-off between compute and commit — a kill there
# is the crash window exactly-once emission must survive, a corrupt
# rule rots the staged sink payload so the promote-time CRC gate has
# real rot to catch
register_point("incremental.state.write")
register_point("incremental.state.restore")
register_point("incremental.sink.commit")

# Tick markers (thread-local: ticks serialize per runner and every
# execution inside a tick starts on the tick thread).  TWO distinct
# facts live here, split deliberately:
#
# - ``depth``  — "inside MicroBatchRunner.tick()" (in_tick): scope
#   bookkeeping, spans, and user code a tick invokes (an on_commit
#   sink callback) all run under it;
# - ``exec_depth`` — "running one of the RUNNER'S OWN executions"
#   (in_tick_execution): delta partial, merge, watermark evict,
#   finalize, degraded recompute, and the fleet shared-ingest read.
#
# Only the second gates the serving reuse stores
# (DataFrame._execute_batches): the runner's plans over transient
# state relations must bypass the result cache and shared-stage
# registration — their crash-consistency contract rests on the epoch
# store alone, and their id()-keyed in-memory fingerprints die with
# the epoch.  An ORDINARY query issued from within a tick callback
# (e.g. a sink-side lookup) carries depth but not exec_depth and
# caches normally — one coarse marker for both facts silently
# uncached every such query.
_TICK_TLS = threading.local()


def in_tick() -> bool:
    """True while the calling thread is inside MicroBatchRunner.tick()
    (any runner, incremental.enabled on or off) — including user code
    the tick invokes, e.g. an on_commit sink callback."""
    return getattr(_TICK_TLS, "depth", 0) > 0


def in_tick_execution() -> bool:
    """True only while the calling thread is running one of a tick's
    OWN plan executions (or a fleet shared-ingest read) — the marker
    the serving reuse stores gate on; see the module comment above."""
    return getattr(_TICK_TLS, "exec_depth", 0) > 0


class tick_execution_scope:
    """Mark the calling thread as running a tick-owned execution for
    the duration of the ``with`` block (see in_tick_execution)."""

    def __enter__(self) -> "tick_execution_scope":
        _TICK_TLS.exec_depth = getattr(_TICK_TLS, "exec_depth", 0) + 1
        return self

    def __exit__(self, *exc) -> bool:
        _TICK_TLS.exec_depth -= 1
        return False


class IncrementalMetrics(CheckpointMetrics):
    """Process-wide continuous-ingest counters (bench.py --ingest-ticks
    and the profiling tool read these alongside the checkpoint/recovery
    counters).  Same lock/bump/snapshot discipline as the checkpoint
    counters, wider field set; ``stateBytes`` is a gauge (last
    committed epoch's size), everything else is a counter."""

    FIELDS = ("ticks", "incrementalTicks", "fullRecomputes", "commits",
              "rollbacks", "writes", "bytesWritten", "resumes",
              "stagesSkipped", "evictions", "invalid", "stateBytes",
              "stateBytesRaw", "joinTicks", "windowTicks", "topnTicks",
              "watermarkEvictedBuckets", "watermarkEvictedBytes",
              "sinkCommits", "sinkReplays")

    def set(self, field: str, value: int) -> None:
        with self._lock:
            self.counters[field] = int(value)


incremental_metrics = IncrementalMetrics()


def _batch_payload(batch) -> dict:
    """Canonical host payload of a ColumnarBatch (the spill module's
    key layout) for the store's own checksum — a DEVICE-resident state
    batch is verified on restore even though the catalog's CRC only
    stamps at tier crossings.  Host-backed buffers are used bit-exact;
    every still-on-device buffer comes down in ONE budgeted transfer
    (utils/hostsync.fetch_all — syncs are a counted resource, and a
    per-buffer ``np.asarray`` would pay a tunnel round trip per column
    on real hardware, the checkpoint._frame_payload discipline)."""
    payload = {}
    pending = []  # (payload key, device buffer)
    for name, col in batch.columns.items():
        for suffix, np_buf, jax_buf in (
                ("data", col._np_data, col._jax_data),
                ("validity", col._np_validity, col._jax_validity),
                ("offsets", col._np_offsets, col._jax_offsets)):
            if np_buf is not None:
                payload[f"{name}.{suffix}"] = \
                    np.ascontiguousarray(np_buf)
            elif jax_buf is not None:
                pending.append((f"{name}.{suffix}", jax_buf))
    if pending:
        from spark_rapids_tpu.utils.hostsync import fetch_all
        fetched = fetch_all([b for _, b in pending])
        for (key, _), host in zip(pending, fetched):
            payload[key] = np.ascontiguousarray(np.asarray(host))
    return payload


class AggState:
    """One epoch's partial-aggregate state: the spill-catalog handle
    holding the merged partial batch plus the input fingerprint it was
    computed from.  ``watermark`` is the epoch's event-time watermark
    (microseconds; None for non-windowed shapes) — it lives WITH the
    state so commit promotes and rollback discards them together:
    a rolled-back tick can neither advance the watermark nor
    resurrect a bucket the committed epoch already evicted."""

    __slots__ = ("handle", "nrows", "crc", "size_bytes", "fingerprint",
                 "epoch", "watermark")

    def __init__(self, handle, nrows: int, crc: int, size_bytes: int,
                 fingerprint: str, epoch: int,
                 watermark: Optional[int] = None):
        self.handle = handle
        self.nrows = nrows
        self.crc = crc
        self.size_bytes = size_bytes
        self.fingerprint = fingerprint
        self.epoch = epoch
        self.watermark = watermark


class _SinkRecord:
    """One COMMITTED (or staged-provisional) emission's identity:
    epoch + payload CRC + row/byte counts.  Metadata only — the
    payload itself is the tick's result (bit-identical on recompute by
    the epoch contract), so idempotent re-emission needs the identity,
    not a copy of the bytes."""

    __slots__ = ("epoch", "crc", "rows", "size_bytes")

    def __init__(self, epoch: int, crc: int, rows: int,
                 size_bytes: int):
        self.epoch = epoch
        self.crc = crc
        self.rows = rows
        self.size_bytes = size_bytes


class SinkCommit:
    """What ``runner.tick()`` hands a downstream sink: exactly-once
    emission metadata that rode the atomic epoch commit.  ``epoch`` is
    the emission's COMMITTED epoch — a replayed tick (no new data, or
    a retried delivery) re-surfaces the SAME epoch with
    ``replayed=True`` and an identical ``crc``, so a sink that
    dedupes on (store, epoch) gets every answer exactly once across
    crash/rollback/replay.  ``df`` is the tick's result DataFrame
    (attached by the runner after commit)."""

    __slots__ = ("store", "epoch", "crc", "rows", "size_bytes",
                 "replayed", "df")

    def __init__(self, store: int, epoch: int, crc: int, rows: int,
                 size_bytes: int, replayed: bool):
        self.store = store
        self.epoch = epoch
        self.crc = crc
        self.rows = rows
        self.size_bytes = size_bytes
        self.replayed = replayed
        self.df = None

    def __repr__(self) -> str:
        return (f"SinkCommit(store={self.store}, epoch={self.epoch}, "
                f"crc={self.crc:#010x}, rows={self.rows}, "
                f"replayed={self.replayed})")


class SharedIngest:
    """One fleet round's single source pull, fanned out to every
    subscriber: the delta file list, its PRE-READ ``scan_input_meta``
    stat triples (the stat-before-read rule — a file mutating after
    the stat leaves the committed fingerprint describing pre-mutation
    bytes, so the next staleness check catches it), the materialized
    batches, and the full scan schema the batches carry (subscribers
    whose fact scan reads a different shape fall back to their own
    pull — correct, just unshared)."""

    __slots__ = ("paths", "meta", "batches", "schema_names")

    def __init__(self, paths, meta, batches, schema_names):
        self.paths = list(paths)
        self.meta = list(meta)
        self.batches = list(batches)
        self.schema_names = list(schema_names)  # [(name, dtype.name)]


class IncrementalStateStore(CheckpointManager):
    """Session-persistent lineage + aggregate state with epochs.

    The PR5 CheckpointManager, promoted: entries outlive a query, stage
    ids are input-fingerprinted (safe to splice across queries —
    ``always_resume``), and every mutation lands provisionally until
    :meth:`commit` — :meth:`rollback` restores the committed epoch
    exactly.  Committed entries are only ever *dropped* outside the
    epoch discipline (CRC failure, eviction) — a drop degrades a future
    tick to recompute, which is always correct."""

    always_resume = True

    # per-process store sequence: stamps StateWatermark with a stable
    # per-standing-query discriminator so app-level consumers (the
    # watermark-stall health check) can group one runner's trail —
    # without it, one advancing windowed query masks a stalled one
    _STORE_SEQ = itertools.count(1)

    def __init__(self, session):
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.memory.spill import (
            INCREMENTAL_STATE_PRIORITY)
        # base wiring (session/catalog/entry log/counters) is the
        # manager's; only the governing confs and the priority differ
        super().__init__(session)
        self.store_id = next(IncrementalStateStore._STORE_SEQ)
        conf = session.conf
        self.enabled = bool(conf.get(rc.INCREMENTAL_ENABLED))
        self.max_bytes = int(conf.get(rc.INCREMENTAL_MAX_STATE_BYTES))
        self.tiers = tuple(
            t.strip().upper()
            for t in conf.get(rc.INCREMENTAL_TIERS).split(",")
            if t.strip())
        self.priority = INCREMENTAL_STATE_PRIORITY
        self.epoch = 0
        self._agg: Optional[AggState] = None
        self._agg_prov: Optional[AggState] = None
        self._provisional: set = set()
        self._touched: set = set()
        # exactly-once sink log: committed emission records (epoch →
        # identity, insertion-ordered, trimmed to sink_max) plus the
        # one staged-provisional record that rides the next commit
        self.sink_max = int(conf.get(rc.FLEET_SINK_MAX_RECORDS))
        self._sink: Dict[int, _SinkRecord] = {}
        self._sink_prov: Optional[_SinkRecord] = None
        self.last_sink: Optional[SinkCommit] = None
        # epoch-aware sharing: sids whose input fingerprint is purely
        # file-backed (no in-memory batch identities — the planner's
        # shareable hint) are safe to splice ACROSS standing queries;
        # commit publishes the committed subset to the session
        # SharedStageCache's epoch tier
        self.share_epoch = bool(
            conf.get(rc.FLEET_EPOCH_SHARED_STAGE_ENABLED))
        self._shareable: set = set()
        self._splice_active = False
        # True only when a splice execution ran DISTRIBUTED end to end
        # — the precondition for stale-entry pruning at commit: an
        # attempt that fell off the mesh (ladder demotion, fallback)
        # touched nothing, and "untouched" must not read as "stale"
        self._splice_complete = False

    # ------------------------------------------------------- metric/event taps --
    # the base class's save/restore/drop machinery is reused verbatim;
    # only where its counters and events land changes
    _EVENT_MAP = {"CheckpointWrite": None,  # commit carries the bytes
                  "CheckpointResume": "IncrementalResume",
                  "CheckpointEvict": "StateEvict",
                  "CheckpointInvalid": "StateEvict"}

    def _bump(self, field: str, by: int = 1) -> None:
        incremental_metrics.bump(field, by)
        if field in self.local:
            self.local[field] += int(by)

    def _emit(self, event: str, **fields) -> None:
        mapped = self._EVENT_MAP.get(event, event)
        if mapped is None:
            return
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session(mapped, session=self.session, **fields)

    # ------------------------------------------------------------ stage lineage --
    def save(self, sid: str, frame, stages: int = 1,
             shareable: bool = False) -> None:
        known = sid in self._entries
        super().save(sid, frame, stages)
        if not known and sid in self._entries:
            self._provisional.add(sid)
            if shareable:
                # the planner vouched: this sid's fingerprint is
                # purely file-backed, so another standing query whose
                # plan contains the identical subtree derives the
                # identical sid — publishable at commit
                self._shareable.add(sid)
        self._touched.add(sid)

    def restore(self, sid: str, mesh):
        frame = super().restore(sid, mesh)
        if frame is not None:
            self._touched.add(sid)
            return frame
        # local miss: try a co-subscriber's COMMITTED epoch via the
        # session shared-stage cache's epoch tier.  The hit is not
        # adopted (not _touched, not ours): the owner store's epoch
        # discipline governs its lifetime, and this store's pruning
        # must not treat a borrowed entry as its own lineage.
        if self.share_epoch:
            shared = getattr(self.session, "shared_stages", None)
            if shared is not None and getattr(shared, "enabled", False):
                er = getattr(shared, "epoch_restore", None)
                if er is not None:
                    return er(sid, mesh, exclude=self)
        return None

    def drop(self, sid: str, reason: str, evict: bool = False) -> None:
        self._provisional.discard(sid)
        self._shareable.discard(sid)
        super().drop(sid, reason, evict=evict)

    def note_distributed_complete(self) -> None:
        """The planner's on-thread completion signal: the final
        attempt of a splice execution really ran distributed, so
        untouched entries are provably stale at commit.  clear() (a
        layout rung) can only be followed by off-mesh attempts, which
        never reach this hook — the veto sticks."""
        if self._splice_active:
            self._splice_complete = True

    def clear(self, reason: str) -> None:
        """A layout-changing ladder rung inside one tick invalidates
        only that tick's PROVISIONAL work: committed entries are keyed
        to (subtree, mesh layout, input fingerprint), all of which
        survive the rung — the next tick runs on the mesh again and
        they splice correctly.  (The per-query manager clears its whole
        log here; a persistent store that did the same would throw away
        every standing epoch on one transient demotion.)"""
        self._splice_complete = False  # a layout rung ran: this tick
        # can no longer vouch for which committed entries are stale
        self._sink_prov = None  # metadata only; nothing to release
        for sid in list(self._provisional):
            entry = self._entries.pop(sid, None)
            self._provisional.discard(sid)
            self._shareable.discard(sid)
            if entry is not None:
                try:
                    entry.handle.close()
                except Exception:
                    pass
        if self._agg_prov is not None:
            try:
                self._agg_prov.handle.close()
            except Exception:
                pass
            self._agg_prov = None

    # ------------------------------------------------------------ agg state I/O --
    def put_state(self, batch, fingerprint: str,
                  watermark: Optional[int] = None) -> None:
        """Register the tick's merged partial-aggregate batch as the
        PROVISIONAL epoch's state (replacing any earlier provisional
        from the same tick — a degraded tick overwrites its own
        half-built state, never the committed epoch).  For windowed
        shapes the batch arrives already watermark-evicted and
        ``watermark`` is the epoch it was evicted against — the two
        are one provisional unit, promoted or discarded together."""
        from spark_rapids_tpu.memory.spill import _payload_checksum
        fire("incremental.state.write")
        if self._agg_prov is not None:
            try:
                self._agg_prov.handle.close()
            except Exception:
                pass
            self._agg_prov = None
        payload = _batch_payload(batch)
        crc = _payload_checksum(payload, batch.nrows)
        # put_state runs BETWEEN a tick's query executions (no
        # QueryContext to auto-tag from), but the standing state must
        # still bill its tenant: the tick thread's ident is the same
        # owner ident every QueryContext of this tick registers its
        # budgets under, so per-owner accounting and the eviction
        # floor see the state as the standing query's, not nobody's
        handle = self.catalog.register(batch, priority=self.priority,
                                       owner=threading.get_ident())
        if "DEVICE" not in self.tiers:
            self.catalog.demote(
                handle, self.tiers[0] if self.tiers else "HOST")
        self._agg_prov = AggState(handle, batch.nrows, crc,
                                  handle.size_bytes, fingerprint,
                                  self.epoch + 1, watermark=watermark)
        self._bump("writes")
        self._bump("bytesWritten", handle.size_bytes)
        self._evict_over_budget()

    def get_state(self):
        """The COMMITTED epoch's state batch, or None when the next
        tick must full-recompute (no state, evicted, CRC mismatch,
        undecodable spill frame).  Wrong bytes are never returned: any
        verification failure drops the state and lands a StateEvict on
        the trail."""
        from spark_rapids_tpu.memory.spill import _payload_checksum
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        st = self._agg
        if st is None:
            return None
        try:
            batch = st.handle.materialize()
        except (CorruptionFault, OSError, ValueError) as e:
            self.drop_state(f"{type(e).__name__}: {e}")
            return None
        payload = _batch_payload(batch)
        key = next((k for k in sorted(payload)
                    if payload[k].size > 0), None)
        if key is not None:
            mutated = fire_mutate("incremental.state.restore",
                                  payload[key])
            if mutated is not payload[key]:
                payload = dict(payload)
                payload[key] = mutated
        got = _payload_checksum(payload, st.nrows)
        if got != st.crc:
            self.drop_state(f"crc {got:#010x} != stored {st.crc:#010x}")
            return None
        return batch

    def drop_state(self, reason: str, evict: bool = False,
                   provisional: bool = False) -> None:
        """Release one aggregate-state slot (committed by default, the
        in-flight provisional one under budget pressure) with the
        shared eviction accounting — both paths must emit the same
        StateEvict shape."""
        if provisional:
            st, self._agg_prov = self._agg_prov, None
        else:
            st, self._agg = self._agg, None
        if st is None:
            return
        try:
            st.handle.close()
        except Exception:
            pass
        self._bump("evictions" if evict else "invalid")
        self._emit("StateEvict", kind="aggState", reason=reason,
                   bytes=st.size_bytes, epoch=st.epoch)

    # ---------------------------------------------------------------- sink log --
    def sink_prepare(self, batches) -> None:
        """Stage this tick's emission as the PROVISIONAL sink record
        (CRC + rows + bytes over the result batches).  This is the
        hand-off between compute and commit — the chaos point here IS
        the crash window exactly-once emission must survive: a kill
        raises before anything is staged (rollback discards, the
        degraded recompute stages afresh, one commit → one emission),
        and a corrupt rule rots the staged payload so the CRC gate
        below catches real bit rot before it can ride a commit."""
        from spark_rapids_tpu.memory.spill import _payload_checksum
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        fire("incremental.sink.commit")
        crc, rows, size = 0, 0, 0
        probed = False
        for b in batches:
            payload = _batch_payload(b)
            c = _payload_checksum(payload, b.nrows)
            if not probed:
                key = next((k for k in sorted(payload)
                            if payload[k].size > 0), None)
                if key is not None:
                    probed = True
                    mut = fire_mutate("incremental.sink.commit",
                                      payload[key])
                    if mut is not payload[key]:
                        staged = dict(payload)
                        staged[key] = mut
                        got = _payload_checksum(staged, b.nrows)
                        if got != c:
                            raise CorruptionFault(
                                "sink payload rot between compute and"
                                f" commit: crc {got:#010x} != "
                                f"computed {c:#010x}")
            crc = (crc * 1000003 + c) & 0xFFFFFFFF
            rows += int(b.nrows)
            size += sum(a.nbytes for a in payload.values())
        self._sink_prov = _SinkRecord(self.epoch + 1, crc, rows, size)

    @property
    def state_fingerprint(self) -> Optional[str]:
        return self._agg.fingerprint if self._agg is not None else None

    @property
    def state_watermark(self) -> Optional[int]:
        """The COMMITTED epoch's event-time watermark (us) — the floor
        every later advance builds on; None for non-windowed state or
        after a state drop (a recompute then re-derives an equal-or-
        later watermark from the data, monotone by construction)."""
        return self._agg.watermark if self._agg is not None else None

    @property
    def state_bytes(self) -> int:
        """STORED bytes of all standing state — compressed host/disk
        frames meter their encoded size, so maxStateBytes holds
        proportionally more state when the storage codec is on."""
        n = self.live_bytes
        for st in (self._agg, self._agg_prov):
            if st is not None:
                n += self._entry_bytes(st)
        return n

    @property
    def state_bytes_raw(self) -> int:
        n = self.live_bytes_raw
        for st in (self._agg, self._agg_prov):
            if st is not None:
                n += st.size_bytes
        return n

    # -------------------------------------------------------------------- epochs --
    def commit(self, mode: str, delta_files: int, reused: bool,
               evicted_buckets: int = 0, evicted_rows: int = 0,
               evicted_bytes: int = 0) -> int:
        """Atomically promote the provisional epoch: the new aggregate
        state replaces the old (whose payload is released), provisional
        stage entries become committed, and — when this tick spliced —
        committed entries the tick never touched are pruned (their
        input fingerprints have moved on; they can never match again).
        The commit is the LAST step of a tick: everything before it is
        invisible to the next tick until this returns.  For windowed
        shapes the commit doubles as the watermark advance — the
        provisional state was built already-evicted against its
        watermark, so promoting it IS the atomic
        eviction+advance (``evicted_buckets``/``evicted_bytes`` are
        the counts that eviction removed, stamped on the
        ``StateWatermark`` event this emits)."""
        self.epoch += 1
        if self._agg_prov is not None:
            old, self._agg = self._agg, self._agg_prov
            self._agg_prov = None
            if old is not None:
                try:
                    old.handle.close()
                except Exception:
                    pass
        if self._splice_active and self._splice_complete:
            # lifecycle GC, not pressure: a DISTRIBUTED splice tick
            # that completed on the mesh and never touched an entry
            # proves its input fingerprint moved on — the key can
            # never match again.  Removed silently (no StateEvict, no
            # eviction counter): routine pruning on a healthy standing
            # query must not trip the eviction-thrash health check.
            # Guarded by _splice_complete: a tick whose final attempt
            # left the mesh (layout rung, planner fallback) touched
            # nothing, and pruning then would wipe still-valid lineage
            for sid in [s for s in self._entries
                        if s not in self._touched]:
                entry = self._entries.pop(sid)
                self._provisional.discard(sid)
                self._shareable.discard(sid)
                try:
                    entry.handle.close()
                except Exception:
                    pass
        self._provisional.clear()
        self._touched.clear()
        self._splice_active = False
        self._splice_complete = False
        self._evict_over_budget()
        # promote the staged sink record — the emission rides THIS
        # commit.  An identical payload to the latest committed record
        # is a REPLAY: the same committed epoch re-emits idempotently
        # (retried tick, zero-delta round) and no new record lands —
        # a (store, epoch)-deduping sink sees every answer exactly once
        sink = None
        if self._sink_prov is not None:
            prov, self._sink_prov = self._sink_prov, None
            last = (self._sink[next(reversed(self._sink))]
                    if self._sink else None)
            if last is not None and (last.crc, last.rows) == \
                    (prov.crc, prov.rows):
                self._bump("sinkReplays")
                sink = SinkCommit(self.store_id, last.epoch, last.crc,
                                  last.rows, last.size_bytes, True)
            else:
                self._sink[self.epoch] = _SinkRecord(
                    self.epoch, prov.crc, prov.rows, prov.size_bytes)
                while len(self._sink) > self.sink_max:
                    self._sink.pop(next(iter(self._sink)))
                self._bump("sinkCommits")
                sink = SinkCommit(self.store_id, self.epoch, prov.crc,
                                  prov.rows, prov.size_bytes, False)
            self._emit("SinkCommit", epoch=sink.epoch, crc=sink.crc,
                       rows=sink.rows, bytes=sink.size_bytes,
                       replayed=bool(sink.replayed),
                       store=self.store_id)
        self.last_sink = sink
        # publish the committed epoch's shareable sids to the session
        # shared-stage cache — ONLY here, never from provisional state
        # (rollback publishes nothing, so the snapshot other standing
        # queries splice from is always a committed epoch's); an empty
        # set still publishes, replacing a stale snapshot
        self._publish_epoch()
        incremental_metrics.bump("commits")
        incremental_metrics.set("stateBytes", self.state_bytes)
        incremental_metrics.set("stateBytesRaw", self.state_bytes_raw)
        self._emit("StateCommit", epoch=self.epoch,
                   stateBytes=self.state_bytes,
                   entries=len(self._entries), mode=mode,
                   deltaFiles=delta_files, reusedState=bool(reused))
        if self._agg is not None and self._agg.watermark is not None:
            # the windowed shape's commit fact: where the watermark
            # landed and what its eviction removed — the profiling
            # "Continuous ingest" watermark line and the
            # watermark-stalled-growth health check read these
            incremental_metrics.bump("watermarkEvictedBuckets",
                                     evicted_buckets)
            incremental_metrics.bump("watermarkEvictedBytes",
                                     evicted_bytes)
            self._emit("StateWatermark", epoch=self.epoch,
                       store=self.store_id,
                       watermark=int(self._agg.watermark),
                       evictedBuckets=int(evicted_buckets),
                       evictedRows=int(evicted_rows),
                       evictedBytes=int(evicted_bytes),
                       stateRows=int(self._agg.nrows),
                       stateBytes=self.state_bytes)
        return self.epoch

    def _publish_epoch(self) -> None:
        """Hand the session SharedStageCache a by-reference snapshot of
        this store's committed, cross-query-safe stage entries (called
        from commit ONLY)."""
        if not self.share_epoch:
            return
        shared = getattr(self.session, "shared_stages", None)
        if shared is None or not getattr(shared, "enabled", False):
            return
        pub = getattr(shared, "publish_epoch", None)
        if pub is not None:
            pub(self, frozenset(s for s in self._entries
                                if s in self._shareable))

    def rollback(self, reason: str) -> None:
        """Discard every provisional write; the committed epoch is
        untouched — a chaos-killed tick leaves the standing state
        exactly as the last commit left it (including the sink log and
        the published shared-epoch snapshot: neither is touched here,
        both only ever move at commit)."""
        self.clear(reason)
        self._touched.clear()
        self._splice_active = False
        self._splice_complete = False
        incremental_metrics.bump("rollbacks")
        self._emit("StateRollback", epoch=self.epoch, reason=reason)

    def _evict_over_budget(self) -> None:
        """maxStateBytes over ALL state: oldest stage entries evict
        first (stale lineage is the cheapest loss), then the committed
        aggregate state (superseded at the next commit anyway), and
        only then the provisional one — each eviction degrades a
        future tick to recompute, never fails one."""
        while self.state_bytes > self.max_bytes and self._entries:
            victim = min(self._entries.values(), key=lambda e: e.seq)
            self.drop(victim.stage_id, reason="max-state-bytes",
                      evict=True)
        if self.state_bytes > self.max_bytes and self._agg is not None:
            self.drop_state("max-state-bytes", evict=True)
        if self.state_bytes > self.max_bytes and \
                self._agg_prov is not None:
            self.drop_state("max-state-bytes", evict=True,
                            provisional=True)

    def close(self) -> None:
        """Release every payload (runner teardown / session stop)."""
        shared = getattr(self.session, "shared_stages", None)
        if shared is not None and \
                hasattr(shared, "retract_epoch"):
            try:
                shared.retract_epoch(self)
            except Exception:
                pass
        self._sink_prov = None
        self._sink.clear()
        self.last_sink = None
        self.clear("store-closed")
        for sid in list(self._entries):
            entry = self._entries.pop(sid)
            try:
                entry.handle.close()
            except Exception:
                pass
        if self._agg is not None:
            try:
                self._agg.handle.close()
            except Exception:
                pass
            self._agg = None


# ------------------------------------------------------------- plan analysis --

def _file_scans(plan) -> list:
    """Every FileRelation leaf of a plan, in pre-order."""
    from spark_rapids_tpu.plan import logical as L
    scans = []

    def walk(node):
        if isinstance(node, L.FileRelation):
            scans.append(node)
        for c in node.children:
            walk(c)

    walk(plan)
    return scans


def _find_fact_scan(plan, fact=None):
    """The FileRelation leaf tick() appends to: the plan's unique scan,
    or — for multi-scan plans like a fact⋈dim join over two parquet
    tables — the one designated by ``fact`` (a path already in its
    file list).  None when no unambiguous choice exists (the runner
    then has no append target; plans without one still tick as full
    re-executions with lineage splice)."""
    scans = _file_scans(plan)
    if fact is not None:
        hits = [s for s in scans if fact in s.paths]
        return hits[0] if len(hits) == 1 else None
    return scans[0] if len(scans) == 1 else None


def _replace_scan(plan, scan, paths, replacement=None):
    """Clone ``plan`` with ``scan``'s path list swapped for ``paths``
    — or, with ``replacement``, with the scan node swapped for that
    relation outright (the fleet shared-ingest form: an
    InMemoryRelation holding the already-pulled delta batches).
    Expressions stay shared (they are bound by ordinal and the delta
    scan exposes the identical schema), and subtrees that do not
    contain ``scan`` are shared UNTOUCHED — the dimension side of a
    delta-join keeps node identity across ticks, so its
    InMemoryRelation batch ids (and therefore its input fingerprints
    and spliceable stage ids) stay stable; only the spine from the
    root down to the scan is copied."""
    if plan is scan:
        if replacement is not None:
            return replacement
        new = copy.copy(plan)
        new.paths = list(paths)
        new.pushed_filters = list(plan.pushed_filters)
        new.file_meta = set(plan.file_meta)
        return new
    if not plan.children:
        return plan
    new_children = tuple(_replace_scan(c, scan, paths, replacement)
                         for c in plan.children)
    if all(nc is c for nc, c in zip(new_children, plan.children)):
        return plan
    new = copy.copy(plan)
    new.children = new_children
    return new


class _AggSpec:
    """Decomposition prover: certify a standing plan's delta form, or
    refuse it (``None`` — ticks then full-recompute with lineage
    splice, which is always correct).

    ``[Sort|Limit|Filter]* <- Aggregate <- [Filter|Project]* <-
    source`` splits into: a partial-aggregate plan template (run over
    the delta files only), a merge aggregate (re-reduce (old-state ⊕
    delta) partial rows — the same update/merge split the engine's
    chunked and distributed aggregates use, ops/aggregates.merge_kind),
    a finalize projection (avg = sum/count), and the post-aggregate
    operator chain re-applied on top.  Three admitted source/refinement
    shapes beyond the plain scan:

    - **delta-join** — ``source`` is ``Join(fact chain, dim subtree)``
      where the fact scan sits under its own [Filter|Project]* chain
      and the join type makes output rows a per-fact-row function
      (inner always; left/semi/anti only with the fact on the left;
      right only with the fact on the right — every other type scopes
      output to DIM rows, where a new fact batch can flip matched-ness
      and no per-delta decomposition is sound).  Dim subtrees routing
      through arbitrary Python (UDF/pandas) refuse: the delta merge
      re-executes the dim side and non-determinism would diverge from
      the one-shot oracle.
    - **windowed aggregation** — a group key pair built by
      ``functions.window`` (tumbling only; sliding lowers through
      Expand and never reaches this prover).  With
      ``incremental.watermarkDelayMs`` set, ``window_end`` names the
      bucket-end key the watermark advances on and eviction filters.
    - **mergeable top-N** — post chain exactly ``Limit <- Sort`` whose
      sort keys are bare group-key references covering EVERY group key
      (the ordering over output rows is then total, and for append-only
      ingest a group trimmed from the top-n can never re-enter: the n
      better-keyed groups that displaced it persist — so merging
      trimmed partials provably reproduces the one-shot answer
      bit-for-bit).  Sort keys touching aggregated values refuse the
      trim (a value can move a group back into the top-n after its
      partial was discarded); limits above
      ``incremental.topn.maxStateRows`` keep full-group state.
    """

    def __init__(self, agg, pre_chain_root, post_ops, partial_aggs,
                 merge_keys, merge_aggs, final_exprs, partial_schema,
                 join_type=None, dim_plan=None, window_end=None,
                 delay_us=None, trim_n=None, trim_sort=None):
        self.agg = agg
        self.pre_root = pre_chain_root  # plan node directly above scan
        self.post_ops = post_ops        # outermost-first [Sort|Limit|Filter]
        self.partial_aggs = partial_aggs
        self.merge_keys = merge_keys
        self.merge_aggs = merge_aggs
        self.final_exprs = final_exprs
        self.partial_schema = partial_schema
        self.join_type = join_type      # admitted delta-join type
        self.dim_plan = dim_plan        # static dimension subtree
        self.window_end = window_end    # bucket-end key (eviction on)
        self.delay_us = delay_us        # watermark delay (us)
        self.trim_n = trim_n            # proven top-N state bound
        self.trim_sort = trim_sort      # the Sort node the trim applies

    @property
    def shape(self) -> str:
        """Primary shape label (spans, last_tick_info, bench)."""
        if self.join_type is not None:
            return "join"
        if self.window_end is not None:
            return "window"
        if self.trim_n is not None:
            return "topn"
        return "agg"

    @staticmethod
    def _fact_side(join, scan):
        """Which join child reaches ``scan`` through a pure
        [Filter|Project]* chain (0=left, 1=right), or None.  Chain
        purity is what lets ``_replace_scan`` build the delta fact
        side; the other child is the dimension subtree and must not
        contain the fact scan anywhere (a self-join over the appended
        table has no per-delta form — delta×delta pairs would be
        lost)."""
        from spark_rapids_tpu.plan import logical as L
        side = None
        for i, child in enumerate(join.children):
            c = child
            while isinstance(c, (L.Filter, L.Project)):
                c = c.children[0]
            if c is scan:
                side = i if side is None else None
        if side is None:
            return None

        def contains(node):
            if node is scan:
                return True
            return any(contains(ch) for ch in node.children)

        return None if contains(join.children[1 - side]) else side

    @classmethod
    def analyze(cls, plan, scan, watermark_delay_us=None, topn_cap=0):
        from spark_rapids_tpu.columnar import dtypes as dts
        from spark_rapids_tpu.ops import aggregates as ag
        from spark_rapids_tpu.ops.arithmetic import Divide
        from spark_rapids_tpu.ops.cast import Cast
        from spark_rapids_tpu.ops.expressions import (Alias,
                                                      UnresolvedColumn)
        from spark_rapids_tpu.plan import logical as L
        from spark_rapids_tpu.plan.logical import AggregateExpression
        if scan is None:
            return None
        post, node = [], plan
        while isinstance(node, (L.Sort, L.Limit, L.Filter)):
            post.append(node)
            node = node.children[0]
        if not isinstance(node, L.Aggregate):
            return None
        agg = node
        pre = agg.child
        c = pre
        while isinstance(c, (L.Filter, L.Project)):
            c = c.children[0]
        join_type = dim_plan = None
        if isinstance(c, L.Join):
            side = cls._fact_side(c, scan)
            if side is None:
                return None
            jt = c.join_type
            if not (jt == "inner"
                    or (side == 0 and jt in ("left", "semi", "anti"))
                    or (side == 1 and jt == "right")):
                return None  # output scoped to dim rows: a new fact
                #               batch can flip a dim row's matched-ness,
                #               so no per-delta decomposition is sound
            dim_plan = c.children[1 - side]
            dtext = dim_plan.tree_string()
            if "UDF" in dtext or "InPandas" in dtext or \
                    "ArrowEval" in dtext:
                return None  # dim re-executes per delta; arbitrary
                #               Python is not provably deterministic
            join_type = jt
        elif c is not scan:
            return None

        keys = [(ge.name, ge.dtype) for ge in agg.group_exprs]
        if len({n for n, _ in keys}) != len(keys):
            return None  # duplicate key names would mis-merge
        if any(n.startswith("__p") or n == "__wm" for n, _ in keys):
            return None  # reserved partial/watermark column names
        partial_aggs: List = []   # Alias(AggregateExpression, pname)
        merge_aggs: List = []
        final_tail: List = []
        partial_cols: List[Tuple[str, object]] = []

        def add(pname, update_func, merge_cls):
            ae = AggregateExpression(update_func)
            partial_aggs.append(Alias(ae, pname))
            partial_cols.append((pname, ae.dtype))
            merge_aggs.append(Alias(AggregateExpression(
                merge_cls(UnresolvedColumn(pname))), pname))

        for i, e in enumerate(agg.agg_exprs):
            name = e.name
            inner = e.children[0] if isinstance(e, Alias) else e
            if not isinstance(inner, AggregateExpression):
                return None
            func = inner.func
            child = func.child
            if child is not None and child.dtype.is_decimal:
                return None  # sum(decimal) widens per level; no merge form
            if isinstance(func, ag.Average):
                sname, cname = f"__p{i}s", f"__p{i}c"
                add(sname, ag.Sum(Cast(child, dts.FLOAT64)), ag.Sum)
                add(cname, ag.Count(child), ag.Sum)
                final_tail.append(Alias(
                    Divide(UnresolvedColumn(sname),
                           UnresolvedColumn(cname)), name))
            elif isinstance(func, ag.Sum):
                add(f"__p{i}", ag.Sum(child), ag.Sum)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            elif isinstance(func, ag.Count):
                add(f"__p{i}", ag.Count(child), ag.Sum)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            elif isinstance(func, ag.Min):
                add(f"__p{i}", ag.Min(child), ag.Min)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            elif isinstance(func, ag.Max):
                add(f"__p{i}", ag.Max(child), ag.Max)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            else:
                return None  # first/last/collect/moments: order- or
                #               shape-dependent; no safe delta merge yet

        partial_schema = keys + partial_cols
        merge_keys = [Alias(UnresolvedColumn(n), n) for n, _ in keys]
        final_exprs = [UnresolvedColumn(n) for n, _ in keys] + final_tail

        # windowed shape: a tumbling functions.window bucket pair among
        # the group keys — eviction arms only when the watermark delay
        # conf is set and exactly ONE end edge exists (two different
        # windows in one key set have no single watermark)
        window_end = delay_us = None
        if watermark_delay_us is not None and watermark_delay_us >= 0:
            from spark_rapids_tpu.ops.datetime_ops import TimeWindow
            ends = []
            for ge in agg.group_exprs:
                inner = ge.children[0] if isinstance(ge, Alias) else ge
                if isinstance(inner, TimeWindow) and \
                        inner.field == "end" and \
                        inner.slide_us >= inner.window_us:
                    ends.append(ge.name)
            if len(ends) == 1:
                window_end = ends[0]
                delay_us = int(watermark_delay_us)

        # mergeable top-N: post chain exactly Limit <- Sort, sort keys
        # bare group-key references covering every key (total order
        # over output rows -> trimmed merges provably reproduce the
        # one-shot answer; see class docstring).  Never combined with
        # watermark eviction: trimming to n keys BEFORE eviction could
        # under-fill the limit the one-shot answer fills after its
        # filter — eviction already bounds windowed state anyway.
        trim_n = trim_sort = None
        if window_end is None and len(post) == 2 and keys and \
                isinstance(post[0], L.Limit) and \
                isinstance(post[1], L.Sort) and \
                0 < post[0].n <= int(topn_cap):
            from spark_rapids_tpu.ops.expressions import BoundReference
            n_keys = len(keys)
            ords = []
            for oe, _, _ in post[1].orders:
                if isinstance(oe, BoundReference) and \
                        oe.ordinal < n_keys:
                    ords.append(oe.ordinal)
                else:
                    ords = None
                    break
            if ords is not None and set(ords) == set(range(n_keys)):
                trim_n = post[0].n
                trim_sort = post[1]

        spec = cls(agg, pre, post, partial_aggs, merge_keys, merge_aggs,
                   final_exprs, partial_schema, join_type=join_type,
                   dim_plan=dim_plan, window_end=window_end,
                   delay_us=delay_us, trim_n=trim_n,
                   trim_sort=trim_sort)
        # the decomposition must reproduce the original output schema
        # exactly — name or dtype drift means the merge form is not the
        # same query, so refuse it rather than answer differently
        try:
            probe = spec.result_plan([])
        except Exception:
            return None
        if [(n, dt.name) for n, dt in probe.schema] != \
                [(n, dt.name) for n, dt in plan.schema]:
            return None
        return spec

    # -- plan builders ----------------------------------------------------
    def _trimmed(self, node):
        """The proven top-N state bound applied to a partial plan: the
        group keys lead the partial schema at the same ordinals as the
        aggregate output, so the post chain's bound sort keys transfer
        verbatim.  Identity when the trim was refused."""
        from spark_rapids_tpu.plan import logical as L
        if self.trim_n is None:
            return node
        return L.Limit(self.trim_n,
                       L.Sort(list(self.trim_sort.orders), node))

    def partial_plan(self, scan, paths, batches=None):
        """Partial aggregate over ONLY ``paths`` (the delta).  For a
        delta-join the cloned spine keeps the dimension subtree SHARED
        (node identity — see ``_replace_scan``), so its stage ids stay
        spliceable and its in-memory batch ids stay fingerprintable.
        With ``batches`` (a fleet round's shared-ingest pull of those
        same paths) the scan is replaced by an InMemoryRelation over
        them — same schema, zero additional source pulls."""
        from spark_rapids_tpu.plan import logical as L
        rel = None
        if batches is not None:
            rel = L.InMemoryRelation(list(batches), list(scan.schema))
        child = _replace_scan(self.pre_root, scan, paths,
                              replacement=rel)
        return self._trimmed(L.Aggregate(list(self.agg.group_exprs),
                                         list(self.partial_aggs),
                                         child))

    def merge_plan(self, batches):
        """Re-aggregate (old-state ⊕ delta) partial rows into the next
        epoch's state — the aggregate merge discipline over an
        in-memory union of partial batches."""
        from spark_rapids_tpu.plan import logical as L
        rel = L.InMemoryRelation(batches, self.partial_schema)
        return self._trimmed(
            L.Aggregate(list(self.merge_keys), list(self.merge_aggs),
                        rel))

    def evict_plan(self, state_batches, watermark: int):
        """Watermark eviction as an engine plan: keep only buckets
        whose window end is strictly AFTER the watermark.  Runs
        through the full exec path (string keys, validity, the mesh
        when one is up) instead of a hand-rolled host row filter.
        The watermark rides as a DATA column (``__wm``), not a
        literal: a literal would bake each tick's watermark into the
        jit signature and recompile the evict stage every tick —
        column-vs-column keeps one stable compiled program for the
        life of the standing query."""
        from spark_rapids_tpu.columnar import dtypes as dts
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.column import Column
        from spark_rapids_tpu.ops.expressions import UnresolvedColumn
        from spark_rapids_tpu.ops.predicates import (GreaterThan,
                                                     IsNull, Or)
        from spark_rapids_tpu.plan import logical as L
        aug = []
        for b in state_batches:
            cols = dict(b.columns)
            cap = next(iter(cols.values())).capacity if cols else 1
            cols["__wm"] = Column.from_numpy(
                np.full(b.nrows, int(watermark), dtype=np.int64),
                dtype=dts.TIMESTAMP_US, capacity=cap)
            aug.append(ColumnarBatch(cols, b.nrows))
        rel = L.InMemoryRelation(
            aug, self.partial_schema + [("__wm", dts.TIMESTAMP_US)])
        # Kleene OR keeps NULL-end buckets: a null event time interns
        # as its own group (the engine's null-key semantics) and has
        # no position on the event-time axis — it can never expire.
        # A bare `end > wm` would evaluate null for those rows and
        # the filter's keep-mask discipline would silently evict a
        # real data bucket (answers would then diverge from one-shot).
        cond = Or(IsNull(UnresolvedColumn(self.window_end)),
                  GreaterThan(UnresolvedColumn(self.window_end),
                              UnresolvedColumn("__wm")))
        return L.Project(
            [UnresolvedColumn(n) for n, _ in self.partial_schema],
            L.Filter(cond, rel))

    def result_plan(self, state_batches):
        """Finalize projection over the merged state (avg = sum/count)
        with the post-aggregate operator chain re-applied."""
        from spark_rapids_tpu.plan import logical as L
        rel = L.InMemoryRelation(state_batches, self.partial_schema)
        node = L.Project(list(self.final_exprs), rel)
        for op in reversed(self.post_ops):
            if isinstance(op, L.Sort):
                node = L.Sort(list(op.orders), node)
            elif isinstance(op, L.Limit):
                node = L.Limit(op.n, node)
            else:
                node = L.Filter(op.condition, node)
        return node


# ---------------------------------------------------------------- the runner --

class _TickDegraded(Exception):
    """Internal: the incremental path cannot proceed (no state, state
    dropped, fingerprint moved) — fall through to full recompute
    WITHOUT counting a rollback (nothing provisional was written)."""


class MicroBatchRunner:
    """One standing query over an append-only input.

    ``session.incremental(df)`` → runner; ``runner.tick(new_paths)``
    ingests the appended files and returns the query's result over
    everything ingested so far, as a DataFrame over the materialized
    result (cheap to ``collect()``/``to_pandas()``).  Ticks serialize
    per runner; each execution inside a tick is an ordinary query to
    the rest of the engine (admission, budgets, ladder, watchdog)."""

    def __init__(self, session, df, fact=None,
                 watermark_delay_ms=None):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session = session
        self.df = df
        conf = session.conf
        self.enabled = bool(conf.get(rc.INCREMENTAL_ENABLED)) and \
            getattr(session, "memory_catalog", None) is not None
        self.store: Optional[IncrementalStateStore] = \
            IncrementalStateStore(session) if self.enabled else None
        # the append target: the plan's unique file scan, or the one a
        # multi-scan plan (fact⋈dim over two tables) designates via
        # ``fact`` (any path already in the fact table's file list)
        self._scan = _find_fact_scan(df.plan, fact)
        if fact is not None and self._scan is None:
            # fail fast with the candidates: swallowing this would
            # surface ticks later with an error telling the user to
            # pass the fact= they already passed
            cands = [s.paths for s in _file_scans(df.plan)]
            raise ValueError(
                f"fact={fact!r} resolves to no unique file scan of "
                "this plan (typo, relative-vs-absolute path, or the "
                "path appears in several tables); scans present: "
                + (str(cands) if cands else "none"))
        # the per-runner override lets fleet subscribers over ONE
        # shared ingest evict on their own schedules (watermark
        # independence); the session conf stays the default
        delay_ms = int(conf.get(rc.INCREMENTAL_WATERMARK_DELAY_MS)) \
            if watermark_delay_ms is None else int(watermark_delay_ms)
        self._spec = _AggSpec.analyze(
            df.plan, self._scan,
            watermark_delay_us=(delay_ms * 1000 if delay_ms >= 0
                                else None),
            topn_cap=int(conf.get(rc.INCREMENTAL_TOPN_MAX_STATE_ROWS))
        ) if self.enabled else None
        self._initial = list(self._scan.paths) if self._scan is not None \
            else []
        self._paths: List[str] = []   # committed (ingested) input set
        self._ticked = False
        self._lock = threading.Lock()
        self._phase_log: list = []  # (name, t0_ns, dur_ns) per tick
        self.last_tick_info: Dict[str, object] = {}
        # exactly-once emission surface: the committed SinkCommit of
        # the latest tick (result df attached), and an optional
        # user callback invoked after every commit — the callback runs
        # in tick SCOPE but not tick EXECUTION, so ordinary queries it
        # issues (a sink-side lookup) hit the serving caches normally
        self.last_sink_commit: Optional[SinkCommit] = None
        self.on_commit = None
        self._ingest: Optional[SharedIngest] = None  # per-tick loan

    # ------------------------------------------------------------- helpers --
    def _fingerprint(self, paths) -> str:
        from spark_rapids_tpu.io.readers import scan_input_meta
        return self._state_fingerprint(scan_input_meta(paths))

    def _dim_fingerprint(self) -> str:
        """The delta-join dimension subtree's input fingerprint
        (file triples statted now + in-memory batch identities); ""
        for non-join shapes."""
        if self._spec is None or self._spec.dim_plan is None:
            return ""
        from spark_rapids_tpu.robustness.checkpoint import (
            input_fingerprint)
        return input_fingerprint(self._spec.dim_plan)

    def _state_fingerprint(self, meta, dim_fp: Optional[str] = None
                           ) -> str:
        """Identity of everything the standing state was computed
        from: the fact scan's already-statted ``scan_input_meta``
        triples (one walk serves both the staleness check and the new
        epoch's fingerprint within a tick) plus — for delta-joins —
        the dimension subtree's input fingerprint.  ``dim_fp`` must be
        the PRE-READ stat (the tick captures it once before its first
        execution and reuses it for both the staleness check and the
        new epoch's stamp): statting the dim side after the read would
        stamp post-mutation identity onto state computed from
        pre-mutation bytes and hide the mutation forever — the same
        stat-before-read rule the fact side follows."""
        from spark_rapids_tpu.io.readers import input_signature
        sig = input_signature(sorted(meta))
        if self._spec is not None and self._spec.dim_plan is not None:
            sig += "\x1f" + (dim_fp if dim_fp is not None
                             else self._dim_fingerprint())
        return hashlib.sha256(sig.encode()).hexdigest()

    def _run(self, plan, splice: bool = False) -> list:
        """Execute one logical plan through the full robustness stack.
        With ``splice`` the persistent store rides as the query's
        checkpoint manager, so unchanged (input-fingerprinted) subtrees
        restore instead of re-running."""
        from spark_rapids_tpu.api.dataframe import DataFrame
        df = DataFrame(self.session, plan)
        with tick_execution_scope():
            if splice and self.store is not None and \
                    getattr(self.session, "mesh", None) is not None:
                self.store._splice_active = True
                self.session.checkpoints = self.store
                try:
                    # stale-entry pruning at commit is only sound when
                    # the FINAL attempt really ran on the mesh; the
                    # planner signals that via
                    # note_distributed_complete on THIS thread (a
                    # shared session attribute would race with
                    # concurrent queries), and clear() (layout rung)
                    # vetoes it for the rest of the tick
                    return df._execute_batches()
                finally:
                    self.session.checkpoints = None
            return df._execute_batches()

    @staticmethod
    def _concat(batches):
        from spark_rapids_tpu.ops.concat import concat_batches
        live = [b for b in batches if b.nrows]
        if not live:
            return None
        return concat_batches(live) if len(live) > 1 else live[0]

    def _result_df(self, batches, schema):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.plan import logical as L
        return DataFrame(self.session,
                         L.InMemoryRelation(batches, list(schema)))

    def _ingest_for(self, paths) -> Optional[SharedIngest]:
        """This tick's shared-ingest loan, iff it is usable for
        ``paths``: same file set, and a fact scan whose read shape the
        pulled batches reproduce exactly (full schema, no metadata
        columns, no pushdown pruning).  None falls back to the
        runner's own pull — correct, just unshared."""
        ing = self._ingest
        scan = self._scan
        if ing is None or scan is None or \
                set(ing.paths) != set(paths):
            return None
        if scan.file_meta or scan.pushed_filters or \
                getattr(scan, "required_columns", None):
            return None
        if [(n, d.name) for n, d in scan.schema] != ing.schema_names:
            return None
        return ing

    # ---------------------------------------------------------------- ticks --
    def tick(self, new_paths=(), _ingest=None):
        """Ingest ``new_paths`` (appended files) and return the result
        over everything ingested so far.  Every execution the RUNNER
        issues inside the tick runs under the tick-execution marker:
        the session ResultCache and direct SharedStageCache
        registration are bypassed (no lookup, no store) — a tick must
        never answer from a pre-tick entry, and its crash-consistency
        contract rests on the epoch store alone.  (Cross-query sharing
        of tick work happens instead through the epoch tier: committed
        entries published at commit, borrowed via epoch_restore.)
        ``_ingest`` is the fleet's shared-ingest loan for this round
        (internal)."""
        with self._lock:
            _TICK_TLS.depth = getattr(_TICK_TLS, "depth", 0) + 1
            self._ingest = _ingest
            try:
                return self._tick(
                    [new_paths] if isinstance(new_paths, str)
                    else list(new_paths))
            finally:
                self._ingest = None
                _TICK_TLS.depth -= 1

    def _phased(self, name: str, fn, *args, **kwargs):
        """Run one tick phase, timing it for the span runtime.  Phase
        records are EMITTED only at tick end (_tick): a phase contains
        whole query envelopes whose own spans drain mid-tick, so an
        open phase span would smear into an inner query's trace —
        deferred emission keeps tick phases in the tick's own scope."""
        from spark_rapids_tpu.utils import tracing
        if not tracing._armed:
            return fn(*args, **kwargs)
        import time as _t
        t0 = _t.perf_counter_ns()
        try:
            return fn(*args, **kwargs)
        finally:
            self._phase_log.append(
                (name, t0, _t.perf_counter_ns() - t0))

    def _tick(self, new_paths):
        from spark_rapids_tpu.utils import tracing
        if not tracing._armed:
            return self._tick_impl(new_paths)
        import time as _t
        self._phase_log = []
        t0 = _t.perf_counter()
        try:
            return self._tick_impl(new_paths)
        finally:
            for name, t0_ns, dur_ns in self._phase_log:
                tracing.emit_span(f"incremental.{name}", t0_ns,
                                  dur_ns, is_async=False)
            self._phase_log = []
            ep = self.store.epoch if self.store is not None else 0
            tracing.finish_scope(self.session, f"tick-e{ep}",
                                 (_t.perf_counter() - t0) * 1e3)

    def _tick_impl(self, new_paths):
        from spark_rapids_tpu.plan import logical as L
        if new_paths and self._scan is None:
            raise ValueError(
                "tick(new_paths) needs an append-target file scan; "
                "this plan has none, or several — designate one with "
                "session.incremental(df, fact=<path in the fact "
                "table's file list>)")
        base = list(self._paths) if self._ticked else list(self._initial)
        seen = set(base)
        delta = []
        for p in new_paths:
            if p not in seen:  # dedupe within the call too: a watcher
                seen.add(p)    # emitting [p, p] must not ingest twice
                delta.append(p)
        target = base + delta
        if not self._ticked:
            delta = list(target)  # the first tick ingests everything
        incremental_metrics.bump("ticks")
        info: Dict[str, object] = {"deltaFiles": len(delta),
                                   "mode": "full", "reused": False}

        if self.store is None:
            # incremental.enabled=false parity: every tick is a plain
            # full execution, no standing state (and no sink log — the
            # exactly-once contract needs the epoch store)
            out = self._run(self._full_plan(target))
            self._finish(target, info)
            self.last_sink_commit = None
            return self._result_df(out, self.df.plan.schema)

        try:
            out = self._tick_body(target, delta, info)
        except _TickDegraded:
            out = self._full_or_rollback(target, info)
        except Exception as exc:  # noqa: BLE001 - every escape degrades
            # mid-tick fault (exhausted ladder, fatal, admission
            # reject): roll back to the committed epoch, then answer
            # with a full recompute — never partial state, never wrong
            # bytes.  A full recompute that ALSO fails re-raises with
            # the epoch still intact.
            self.store.rollback(f"{type(exc).__name__}: {exc}")
            info["rollbackFrom"] = f"{type(exc).__name__}: {exc}"
            out = self._full_or_rollback(target, info)
        self._phased("commit", self.store.commit, info["mode"],
                     info["deltaFiles"], info["reused"],
                     info.get("evictedBuckets", 0),
                     info.get("evictedRows", 0),
                     info.get("evictedBytes", 0))
        res = self._result_df(out, self.df.plan.schema)
        sc = self.store.last_sink
        if sc is not None:
            sc.df = res
            info["sinkEpoch"] = sc.epoch
            info["sinkReplayed"] = bool(sc.replayed)
        self.last_sink_commit = sc
        self._finish(target, info)
        if sc is not None and self.on_commit is not None:
            # user code: runs in tick SCOPE (depth) but not tick
            # EXECUTION, so ordinary queries it issues cache normally;
            # a callback fault must not un-commit the epoch — it
            # already committed — so it propagates to the caller as-is
            self.on_commit(sc)
        return res

    def _finish(self, target, info) -> None:
        self._paths = list(target)
        if self._scan is not None:
            # keep the standing plan's own scan in step, so a direct
            # df.to_pandas() (the oracle form) sees the ingested set
            self._scan.paths = list(target)
        self._ticked = True
        info["epoch"] = self.store.epoch if self.store is not None else 0
        self.last_tick_info = dict(info)

    def _full_plan(self, paths):
        if self._scan is None:
            return self.df.plan
        return _replace_scan(self.df.plan, self._scan, paths)

    def _tick_body(self, target, delta, info) -> list:
        """The incremental path; raises _TickDegraded when the
        committed epoch cannot carry this tick."""
        if self._spec is None or not self._ticked:
            raise _TickDegraded
        spec = self._spec
        state = self.store.get_state()
        if state is None:
            raise _TickDegraded
        from spark_rapids_tpu.io.readers import scan_input_meta
        # one stat walk per file per tick: the committed-set walk
        # serves the staleness check, and the target fingerprint
        # derives from it plus the (small) delta walk
        meta_committed = scan_input_meta(self._paths)
        # dim side statted ONCE, before any execution: the same
        # pre-read snapshot serves the staleness check AND the new
        # epoch's stamp below — a post-execution re-stat could stamp a
        # mid-tick dim mutation's identity onto state computed from
        # the old bytes, hiding the mutation from every later check
        dim_fp = self._dim_fingerprint()
        if self.store.state_fingerprint != \
                self._state_fingerprint(meta_committed, dim_fp):
            # an already-ingested file (or the dimension side of a
            # delta-join) changed out-of-band (rewritten, truncated,
            # even same-size — mtime catches it): the state no longer
            # describes the input
            self.store.drop_state("input-fingerprint-moved")
            raise _TickDegraded
        watermark = self.store.state_watermark
        if delta:
            # stat BEFORE read: if a delta file mutates between the
            # stat and the scan, the committed fingerprint describes
            # the PRE-mutation bytes and the next tick's staleness
            # check drops the state — the safe failure mode.  Statting
            # after the read would stamp post-mutation identity onto
            # pre-mutation state and hide the mutation forever.  A
            # fleet shared-ingest loan carries its own PRE-READ stat
            # (the fleet statted before its one pull) — zero source
            # pulls and zero stats on this runner's account.
            ing = self._ingest_for(delta)
            meta_delta = list(ing.meta) if ing is not None \
                else scan_input_meta(delta)
            # delta-join: only the NEW fact batches join the unchanged
            # dimension state — the delta runs with the store riding
            # as checkpoint manager, so completed dim subtrees splice
            # from committed lineage instead of re-running
            partial = self._phased(
                "join.delta" if spec.join_type is not None else "delta",
                self._run, spec.partial_plan(
                    self._scan, delta,
                    batches=ing.batches if ing is not None else None),
                splice=spec.join_type is not None)
            merged = self._phased(
                "topn.merge" if spec.trim_n is not None else "merge",
                self._run, spec.merge_plan(
                    [state] + [b for b in partial if b.nrows]))
            state = self._concat(merged)
            if state is None:
                from spark_rapids_tpu.columnar.batch import empty_batch
                state = empty_batch(spec.partial_schema)
            state, watermark = self._advance_watermark(state, watermark,
                                                       info)
            self.store.put_state(
                state,
                self._state_fingerprint(meta_committed + meta_delta,
                                        dim_fp),
                watermark=watermark)
        out = self._phased("finalize", self._run,
                           spec.result_plan([state]))
        # stage the emission — a fault here (kill/rot in the
        # compute→commit window) degrades the tick exactly like any
        # other mid-tick fault: rollback, recompute, ONE commit
        self._phased("sink", self.store.sink_prepare, out)
        # counted only once the WHOLE incremental path answered: a
        # finalize-run fault degrades this tick to full recompute and
        # must not leave it double-counted in the reuse ratio
        info["mode"] = "incremental"
        info["reused"] = True
        info["shape"] = spec.shape
        if watermark is not None:
            info["watermark"] = int(watermark)
        incremental_metrics.bump("incrementalTicks")
        self._bump_shape_ticks(spec)
        return out

    @staticmethod
    def _bump_shape_ticks(spec) -> None:
        for field, on in (("joinTicks", spec.join_type is not None),
                          ("windowTicks", spec.window_end is not None),
                          ("topnTicks", spec.trim_n is not None)):
            if on:
                incremental_metrics.bump(field)

    def _advance_watermark(self, state, committed, info):
        """Windowed shapes with eviction armed: advance the watermark
        to max(window end seen) − delay (never regressing below the
        committed floor — monotone by construction) and evict expired
        buckets from the merged state via an engine Filter execution.
        The evicted batch is what put_state registers, so eviction and
        advance are one provisional unit that commits — or rolls
        back — atomically with the epoch.  Identity for non-windowed
        shapes."""
        spec = self._spec
        if spec.window_end is None or state.nrows == 0:
            return state, committed
        col = state.columns[spec.window_end]
        ends = np.asarray(col.host_values())[:state.nrows]
        valid = col.host_validity()
        if valid is not None:
            ends = ends[np.asarray(valid)[:state.nrows]]
        if ends.size == 0:
            return state, committed  # all-null buckets never expire
        cand = int(ends.max()) - int(spec.delay_us)
        wm = cand if committed is None else max(int(committed), cand)
        expired = ends[ends <= wm]
        info["watermark"] = wm
        if expired.size == 0:
            return state, wm
        rows_before = int(state.nrows)
        # payload buffers are capacity-padded, so attribute bytes
        # row-proportionally instead of diffing padded buffer sizes
        bytes_before = sum(a.nbytes for a in
                           _batch_payload(state).values())
        kept = self._phased("window.evict", self._run,
                            spec.evict_plan([state], wm))
        state = self._concat(kept)
        if state is None:
            from spark_rapids_tpu.columnar.batch import empty_batch
            state = empty_batch(spec.partial_schema)
        rows_evicted = max(0, rows_before - int(state.nrows))
        # units: a BUCKET is one expired time window (distinct end
        # edge); each bucket spans one state ROW per group-key tuple,
        # and bytes are attributed per row — evictedRows is the
        # denominator that makes evictedBytes ratios meaningful
        info["evictedBuckets"] = info.get("evictedBuckets", 0) + \
            int(np.unique(expired).size)
        info["evictedRows"] = info.get("evictedRows", 0) + rows_evicted
        info["evictedBytes"] = info.get("evictedBytes", 0) + \
            bytes_before * rows_evicted // max(rows_before, 1)
        return state, wm

    def _full_or_rollback(self, target, info) -> list:
        """Degraded recompute with the leak guard: a full recompute
        that dies mid-flight must not leave ITS provisional writes
        (the rebuilt state it put before the finalize run failed)
        pinned in the catalog — roll them back before re-raising, so
        the tick fails with the committed epoch exactly intact."""
        try:
            return self._tick_full(target, info)
        except Exception as exc:  # noqa: BLE001 - re-raised below
            self.store.rollback(
                f"degraded-recompute-failed: {type(exc).__name__}: "
                f"{exc}")
            raise

    def _tick_full(self, target, info) -> list:
        """Full recompute: correct under every degradation.  With a
        delta-capable plan the state rebuilds from one partial pass
        over ALL inputs (result derives from it); otherwise the
        original plan re-runs with the lineage splice restoring
        unchanged subtrees.  Windowed shapes advance+evict against the
        SAME committed watermark floor the incremental path would
        have used, so a degraded tick's answer is identical to the
        incremental tick it replaced (expired buckets rebuilt from
        history evict right back out — no resurrection)."""
        incremental_metrics.bump("fullRecomputes")
        info["mode"] = "full"
        # a rolled-back incremental attempt may have advanced/evicted
        # into this SAME info dict before it died; those provisional
        # facts were discarded with the rollback, and the recompute
        # recounts its own from scratch — without the reset the one
        # commit would stamp roughly double onto StateWatermark and
        # the watermarkEvicted* counters
        for k in ("watermark", "evictedBuckets", "evictedRows",
                  "evictedBytes"):
            info.pop(k, None)
        if self._spec is not None:
            spec = self._spec
            info["shape"] = spec.shape
            # a fleet loan covers this recompute only when it spans
            # the WHOLE target (the first tick: delta == everything);
            # a degraded later tick must re-read history it owns
            ing = self._ingest_for(target)
            # stat before read (see _tick_body): a mid-scan mutation
            # must leave the state stamped with PRE-mutation identity
            fp = self._state_fingerprint(list(ing.meta)) \
                if ing is not None else self._fingerprint(target)
            partial = self._phased(
                "recompute", self._run,
                spec.partial_plan(
                    self._scan, target,
                    batches=ing.batches if ing is not None else None),
                splice=spec.join_type is not None)
            state = self._concat(partial)
            if state is None:
                from spark_rapids_tpu.columnar.batch import empty_batch
                state = empty_batch(spec.partial_schema)
            state, watermark = self._advance_watermark(
                state, self.store.state_watermark, info)
            self.store.put_state(state, fp, watermark=watermark)
            out = self._phased("finalize", self._run,
                               spec.result_plan([state]))
            self._phased("sink", self.store.sink_prepare, out)
            return out
        # reuse detection reads the STORE-LOCAL resume counter, not the
        # process-global one: concurrent runners must not contaminate
        # each other's reusedState flag
        info["shape"] = "splice"
        r0 = self.store.local["resumes"]
        out = self._phased("recompute", self._run,
                           self._full_plan(target), splice=True)
        info["reused"] = self.store.local["resumes"] > r0
        self._phased("sink", self.store.sink_prepare, out)
        return out

    def close(self) -> None:
        """Release the standing state (the runner's epochs die here;
        the session's catalog sweep would collect them at stop()
        anyway)."""
        if self.store is not None:
            self.store.close()
