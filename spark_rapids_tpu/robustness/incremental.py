"""Continuous micro-batch ingest on checkpoint lineage: crash-consistent
incremental state with epoch semantics.

PR5 made completed exchange stages durable *within* one query (the
per-query :class:`~spark_rapids_tpu.robustness.checkpoint.CheckpointManager`);
this module promotes that log into a **session-persistent
IncrementalStateStore** and turns the checkpoint subsystem from a
failure feature into a latency feature (ROADMAP item 5): a standing
query over an append-only input re-executes only what the appended
files can change, and resumes everything else from state.

The unit of standing work is a :class:`MicroBatchRunner`
(``session.incremental(df)``); each ``runner.tick(new_paths)`` is one
micro-batch with **epoch semantics**:

- the tick executes against the last *committed* epoch; everything it
  writes — the new partial-aggregate state, fresh stage checkpoints —
  lands in a *provisional* epoch;
- the provisional epoch **commits atomically only when the tick
  completes**; any fault mid-tick (chaos-injected or real: reader
  fault, shuffle wedge, spill corruption, watchdog timeout, admission
  reject) **rolls back** to the committed epoch and the tick degrades
  to a full recompute — standing state is never half-updated, a
  degraded tick answers with recomputed (correct) bytes, never wrong
  ones;
- the full robustness stack is live the whole time: every execution
  inside a tick runs through ``DataFrame._execute_batches`` — admission
  control, per-query budgets, the recovery ladder, watchdog deadlines,
  spill integrity and per-query stage checkpoints all apply unchanged.

Two reuse mechanisms compose:

1. **Delta re-aggregation** (the streaming-aggregation workload class):
   plans of shape ``[Sort|Limit|Filter]* <- Aggregate <-
   [Filter|Project]* <- FileRelation`` decompose into mergeable
   partials (sum→sum, count→sum, min→min, max→max, avg→(sum,count)).
   The tick aggregates ONLY the appended files and merges
   (old-state ⊕ delta) through the engine's own aggregate merge
   discipline — zero re-pulls of already-ingested source files.
2. **Lineage splice** for everything else: the store subclasses the
   PR5 CheckpointManager with ``always_resume`` — stage ids now fold in
   an **input fingerprint** (file list + sizes + mtimes,
   ``checkpoint.input_fingerprint``), so appending files invalidates
   exactly the scan-adjacent subtrees and a full-recompute tick still
   splices unchanged subtrees (a static dimension side of a join, a
   pre-aggregated reference table) via the existing
   ``try_distributed(resume=True)`` machinery.

State lives in the spill catalog at ``INCREMENTAL_STATE_PRIORITY``
(colder than per-query checkpoints — standing state never competes
with live queries for HBM) under its own budget/tier confs
(``spark.rapids.tpu.incremental.enabled`` / ``.maxStateBytes`` /
``.tiers``); eviction or CRC failure of a state entry degrades the
next tick to recompute — it never fails a tick and never returns wrong
bytes.  Observable end to end: ``StateCommit`` / ``StateRollback`` /
``StateEvict`` / ``IncrementalResume`` events → eventlog
``QueryInfo.incremental`` → profiling "Continuous ingest" section and
health checks.
"""

from __future__ import annotations

import copy
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.robustness.checkpoint import (CheckpointManager,
                                                    CheckpointMetrics)
from spark_rapids_tpu.robustness.inject import (fire, fire_mutate,
                                                register_point)

# chaos surface: a raise/delay rule on the write covers a wedged state
# commit; a corrupt rule on the restore flips state bytes so the CRC
# gate has real rot to catch (fire_mutate site)
register_point("incremental.state.write")
register_point("incremental.state.restore")


class IncrementalMetrics(CheckpointMetrics):
    """Process-wide continuous-ingest counters (bench.py --ingest-ticks
    and the profiling tool read these alongside the checkpoint/recovery
    counters).  Same lock/bump/snapshot discipline as the checkpoint
    counters, wider field set; ``stateBytes`` is a gauge (last
    committed epoch's size), everything else is a counter."""

    FIELDS = ("ticks", "incrementalTicks", "fullRecomputes", "commits",
              "rollbacks", "writes", "bytesWritten", "resumes",
              "stagesSkipped", "evictions", "invalid", "stateBytes",
              "stateBytesRaw")

    def set(self, field: str, value: int) -> None:
        with self._lock:
            self.counters[field] = int(value)


incremental_metrics = IncrementalMetrics()


def _batch_payload(batch) -> dict:
    """Canonical host payload of a ColumnarBatch (the spill module's
    key layout) for the store's own checksum — a DEVICE-resident state
    batch is verified on restore even though the catalog's CRC only
    stamps at tier crossings.  Host-backed buffers are used bit-exact;
    every still-on-device buffer comes down in ONE budgeted transfer
    (utils/hostsync.fetch_all — syncs are a counted resource, and a
    per-buffer ``np.asarray`` would pay a tunnel round trip per column
    on real hardware, the checkpoint._frame_payload discipline)."""
    payload = {}
    pending = []  # (payload key, device buffer)
    for name, col in batch.columns.items():
        for suffix, np_buf, jax_buf in (
                ("data", col._np_data, col._jax_data),
                ("validity", col._np_validity, col._jax_validity),
                ("offsets", col._np_offsets, col._jax_offsets)):
            if np_buf is not None:
                payload[f"{name}.{suffix}"] = \
                    np.ascontiguousarray(np_buf)
            elif jax_buf is not None:
                pending.append((f"{name}.{suffix}", jax_buf))
    if pending:
        from spark_rapids_tpu.utils.hostsync import fetch_all
        fetched = fetch_all([b for _, b in pending])
        for (key, _), host in zip(pending, fetched):
            payload[key] = np.ascontiguousarray(np.asarray(host))
    return payload


class AggState:
    """One epoch's partial-aggregate state: the spill-catalog handle
    holding the merged partial batch plus the input fingerprint it was
    computed from."""

    __slots__ = ("handle", "nrows", "crc", "size_bytes", "fingerprint",
                 "epoch")

    def __init__(self, handle, nrows: int, crc: int, size_bytes: int,
                 fingerprint: str, epoch: int):
        self.handle = handle
        self.nrows = nrows
        self.crc = crc
        self.size_bytes = size_bytes
        self.fingerprint = fingerprint
        self.epoch = epoch


class IncrementalStateStore(CheckpointManager):
    """Session-persistent lineage + aggregate state with epochs.

    The PR5 CheckpointManager, promoted: entries outlive a query, stage
    ids are input-fingerprinted (safe to splice across queries —
    ``always_resume``), and every mutation lands provisionally until
    :meth:`commit` — :meth:`rollback` restores the committed epoch
    exactly.  Committed entries are only ever *dropped* outside the
    epoch discipline (CRC failure, eviction) — a drop degrades a future
    tick to recompute, which is always correct."""

    always_resume = True

    def __init__(self, session):
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.memory.spill import (
            INCREMENTAL_STATE_PRIORITY)
        # base wiring (session/catalog/entry log/counters) is the
        # manager's; only the governing confs and the priority differ
        super().__init__(session)
        conf = session.conf
        self.enabled = bool(conf.get(rc.INCREMENTAL_ENABLED))
        self.max_bytes = int(conf.get(rc.INCREMENTAL_MAX_STATE_BYTES))
        self.tiers = tuple(
            t.strip().upper()
            for t in conf.get(rc.INCREMENTAL_TIERS).split(",")
            if t.strip())
        self.priority = INCREMENTAL_STATE_PRIORITY
        self.epoch = 0
        self._agg: Optional[AggState] = None
        self._agg_prov: Optional[AggState] = None
        self._provisional: set = set()
        self._touched: set = set()
        self._splice_active = False
        # True only when a splice execution ran DISTRIBUTED end to end
        # — the precondition for stale-entry pruning at commit: an
        # attempt that fell off the mesh (ladder demotion, fallback)
        # touched nothing, and "untouched" must not read as "stale"
        self._splice_complete = False

    # ------------------------------------------------------- metric/event taps --
    # the base class's save/restore/drop machinery is reused verbatim;
    # only where its counters and events land changes
    _EVENT_MAP = {"CheckpointWrite": None,  # commit carries the bytes
                  "CheckpointResume": "IncrementalResume",
                  "CheckpointEvict": "StateEvict",
                  "CheckpointInvalid": "StateEvict"}

    def _bump(self, field: str, by: int = 1) -> None:
        incremental_metrics.bump(field, by)
        if field in self.local:
            self.local[field] += int(by)

    def _emit(self, event: str, **fields) -> None:
        mapped = self._EVENT_MAP.get(event, event)
        if mapped is None:
            return
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session(mapped, session=self.session, **fields)

    # ------------------------------------------------------------ stage lineage --
    def save(self, sid: str, frame, stages: int = 1) -> None:
        known = sid in self._entries
        super().save(sid, frame, stages)
        if not known and sid in self._entries:
            self._provisional.add(sid)
        self._touched.add(sid)

    def restore(self, sid: str, mesh):
        frame = super().restore(sid, mesh)
        if frame is not None:
            self._touched.add(sid)
        return frame

    def drop(self, sid: str, reason: str, evict: bool = False) -> None:
        self._provisional.discard(sid)
        super().drop(sid, reason, evict=evict)

    def note_distributed_complete(self) -> None:
        """The planner's on-thread completion signal: the final
        attempt of a splice execution really ran distributed, so
        untouched entries are provably stale at commit.  clear() (a
        layout rung) can only be followed by off-mesh attempts, which
        never reach this hook — the veto sticks."""
        if self._splice_active:
            self._splice_complete = True

    def clear(self, reason: str) -> None:
        """A layout-changing ladder rung inside one tick invalidates
        only that tick's PROVISIONAL work: committed entries are keyed
        to (subtree, mesh layout, input fingerprint), all of which
        survive the rung — the next tick runs on the mesh again and
        they splice correctly.  (The per-query manager clears its whole
        log here; a persistent store that did the same would throw away
        every standing epoch on one transient demotion.)"""
        self._splice_complete = False  # a layout rung ran: this tick
        # can no longer vouch for which committed entries are stale
        for sid in list(self._provisional):
            entry = self._entries.pop(sid, None)
            self._provisional.discard(sid)
            if entry is not None:
                try:
                    entry.handle.close()
                except Exception:
                    pass
        if self._agg_prov is not None:
            try:
                self._agg_prov.handle.close()
            except Exception:
                pass
            self._agg_prov = None

    # ------------------------------------------------------------ agg state I/O --
    def put_state(self, batch, fingerprint: str) -> None:
        """Register the tick's merged partial-aggregate batch as the
        PROVISIONAL epoch's state (replacing any earlier provisional
        from the same tick — a degraded tick overwrites its own
        half-built state, never the committed epoch)."""
        from spark_rapids_tpu.memory.spill import _payload_checksum
        fire("incremental.state.write")
        if self._agg_prov is not None:
            try:
                self._agg_prov.handle.close()
            except Exception:
                pass
            self._agg_prov = None
        payload = _batch_payload(batch)
        crc = _payload_checksum(payload, batch.nrows)
        # put_state runs BETWEEN a tick's query executions (no
        # QueryContext to auto-tag from), but the standing state must
        # still bill its tenant: the tick thread's ident is the same
        # owner ident every QueryContext of this tick registers its
        # budgets under, so per-owner accounting and the eviction
        # floor see the state as the standing query's, not nobody's
        handle = self.catalog.register(batch, priority=self.priority,
                                       owner=threading.get_ident())
        if "DEVICE" not in self.tiers:
            self.catalog.demote(
                handle, self.tiers[0] if self.tiers else "HOST")
        self._agg_prov = AggState(handle, batch.nrows, crc,
                                  handle.size_bytes, fingerprint,
                                  self.epoch + 1)
        self._bump("writes")
        self._bump("bytesWritten", handle.size_bytes)
        self._evict_over_budget()

    def get_state(self):
        """The COMMITTED epoch's state batch, or None when the next
        tick must full-recompute (no state, evicted, CRC mismatch,
        undecodable spill frame).  Wrong bytes are never returned: any
        verification failure drops the state and lands a StateEvict on
        the trail."""
        from spark_rapids_tpu.memory.spill import _payload_checksum
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        st = self._agg
        if st is None:
            return None
        try:
            batch = st.handle.materialize()
        except (CorruptionFault, OSError, ValueError) as e:
            self.drop_state(f"{type(e).__name__}: {e}")
            return None
        payload = _batch_payload(batch)
        key = next((k for k in sorted(payload)
                    if payload[k].size > 0), None)
        if key is not None:
            mutated = fire_mutate("incremental.state.restore",
                                  payload[key])
            if mutated is not payload[key]:
                payload = dict(payload)
                payload[key] = mutated
        got = _payload_checksum(payload, st.nrows)
        if got != st.crc:
            self.drop_state(f"crc {got:#010x} != stored {st.crc:#010x}")
            return None
        return batch

    def drop_state(self, reason: str, evict: bool = False,
                   provisional: bool = False) -> None:
        """Release one aggregate-state slot (committed by default, the
        in-flight provisional one under budget pressure) with the
        shared eviction accounting — both paths must emit the same
        StateEvict shape."""
        if provisional:
            st, self._agg_prov = self._agg_prov, None
        else:
            st, self._agg = self._agg, None
        if st is None:
            return
        try:
            st.handle.close()
        except Exception:
            pass
        self._bump("evictions" if evict else "invalid")
        self._emit("StateEvict", kind="aggState", reason=reason,
                   bytes=st.size_bytes, epoch=st.epoch)

    @property
    def state_fingerprint(self) -> Optional[str]:
        return self._agg.fingerprint if self._agg is not None else None

    @property
    def state_bytes(self) -> int:
        """STORED bytes of all standing state — compressed host/disk
        frames meter their encoded size, so maxStateBytes holds
        proportionally more state when the storage codec is on."""
        n = self.live_bytes
        for st in (self._agg, self._agg_prov):
            if st is not None:
                n += self._entry_bytes(st)
        return n

    @property
    def state_bytes_raw(self) -> int:
        n = self.live_bytes_raw
        for st in (self._agg, self._agg_prov):
            if st is not None:
                n += st.size_bytes
        return n

    # -------------------------------------------------------------------- epochs --
    def commit(self, mode: str, delta_files: int, reused: bool) -> int:
        """Atomically promote the provisional epoch: the new aggregate
        state replaces the old (whose payload is released), provisional
        stage entries become committed, and — when this tick spliced —
        committed entries the tick never touched are pruned (their
        input fingerprints have moved on; they can never match again).
        The commit is the LAST step of a tick: everything before it is
        invisible to the next tick until this returns."""
        self.epoch += 1
        if self._agg_prov is not None:
            old, self._agg = self._agg, self._agg_prov
            self._agg_prov = None
            if old is not None:
                try:
                    old.handle.close()
                except Exception:
                    pass
        if self._splice_active and self._splice_complete:
            # lifecycle GC, not pressure: a DISTRIBUTED splice tick
            # that completed on the mesh and never touched an entry
            # proves its input fingerprint moved on — the key can
            # never match again.  Removed silently (no StateEvict, no
            # eviction counter): routine pruning on a healthy standing
            # query must not trip the eviction-thrash health check.
            # Guarded by _splice_complete: a tick whose final attempt
            # left the mesh (layout rung, planner fallback) touched
            # nothing, and pruning then would wipe still-valid lineage
            for sid in [s for s in self._entries
                        if s not in self._touched]:
                entry = self._entries.pop(sid)
                self._provisional.discard(sid)
                try:
                    entry.handle.close()
                except Exception:
                    pass
        self._provisional.clear()
        self._touched.clear()
        self._splice_active = False
        self._splice_complete = False
        self._evict_over_budget()
        incremental_metrics.bump("commits")
        incremental_metrics.set("stateBytes", self.state_bytes)
        incremental_metrics.set("stateBytesRaw", self.state_bytes_raw)
        self._emit("StateCommit", epoch=self.epoch,
                   stateBytes=self.state_bytes,
                   entries=len(self._entries), mode=mode,
                   deltaFiles=delta_files, reusedState=bool(reused))
        return self.epoch

    def rollback(self, reason: str) -> None:
        """Discard every provisional write; the committed epoch is
        untouched — a chaos-killed tick leaves the standing state
        exactly as the last commit left it."""
        self.clear(reason)
        self._touched.clear()
        self._splice_active = False
        self._splice_complete = False
        incremental_metrics.bump("rollbacks")
        self._emit("StateRollback", epoch=self.epoch, reason=reason)

    def _evict_over_budget(self) -> None:
        """maxStateBytes over ALL state: oldest stage entries evict
        first (stale lineage is the cheapest loss), then the committed
        aggregate state (superseded at the next commit anyway), and
        only then the provisional one — each eviction degrades a
        future tick to recompute, never fails one."""
        while self.state_bytes > self.max_bytes and self._entries:
            victim = min(self._entries.values(), key=lambda e: e.seq)
            self.drop(victim.stage_id, reason="max-state-bytes",
                      evict=True)
        if self.state_bytes > self.max_bytes and self._agg is not None:
            self.drop_state("max-state-bytes", evict=True)
        if self.state_bytes > self.max_bytes and \
                self._agg_prov is not None:
            self.drop_state("max-state-bytes", evict=True,
                            provisional=True)

    def close(self) -> None:
        """Release every payload (runner teardown / session stop)."""
        self.clear("store-closed")
        for sid in list(self._entries):
            entry = self._entries.pop(sid)
            try:
                entry.handle.close()
            except Exception:
                pass
        if self._agg is not None:
            try:
                self._agg.handle.close()
            except Exception:
                pass
            self._agg = None


# ------------------------------------------------------------- plan analysis --

def _single_file_scan(plan):
    """The unique FileRelation leaf of a plan, or None (no scan, or
    more than one — appending paths would be ambiguous)."""
    from spark_rapids_tpu.plan import logical as L
    scans = []

    def walk(node):
        if isinstance(node, L.FileRelation):
            scans.append(node)
        for c in node.children:
            walk(c)

    walk(plan)
    return scans[0] if len(scans) == 1 else None


def _replace_scan(plan, scan, paths):
    """Clone ``plan`` with ``scan``'s path list swapped for ``paths``.
    Expressions stay shared (they are bound by ordinal and the delta
    scan exposes the identical schema); only the node spine is
    copied."""
    from spark_rapids_tpu.plan import logical as L
    if plan is scan:
        new = copy.copy(plan)
        new.paths = list(paths)
        new.pushed_filters = list(plan.pushed_filters)
        new.file_meta = set(plan.file_meta)
        return new
    if not plan.children:
        return plan
    new = copy.copy(plan)
    new.children = tuple(_replace_scan(c, scan, paths)
                         for c in plan.children)
    return new


class _AggSpec:
    """Decomposition of an aggregation plan into mergeable partials.

    ``[Sort|Limit|Filter]* <- Aggregate <- [Filter|Project]* <- scan``
    splits into: a partial-aggregate plan template (run over the delta
    files only), a merge aggregate (re-reduce (old-state ⊕ delta)
    partial rows — the same update/merge split the engine's chunked and
    distributed aggregates use, ops/aggregates.merge_kind), a finalize
    projection (avg = sum/count), and the post-aggregate operator chain
    re-applied on top.  ``None`` from :meth:`analyze` means the plan
    has no delta form — ticks then full-recompute (with lineage
    splice), which is always correct."""

    def __init__(self, agg, pre_chain_root, post_ops, partial_aggs,
                 merge_keys, merge_aggs, final_exprs, partial_schema):
        self.agg = agg
        self.pre_root = pre_chain_root  # plan node directly above scan
        self.post_ops = post_ops        # outermost-first [Sort|Limit|Filter]
        self.partial_aggs = partial_aggs
        self.merge_keys = merge_keys
        self.merge_aggs = merge_aggs
        self.final_exprs = final_exprs
        self.partial_schema = partial_schema

    @classmethod
    def analyze(cls, plan, scan):
        from spark_rapids_tpu.columnar import dtypes as dts
        from spark_rapids_tpu.ops import aggregates as ag
        from spark_rapids_tpu.ops.arithmetic import Divide
        from spark_rapids_tpu.ops.cast import Cast
        from spark_rapids_tpu.ops.expressions import (Alias,
                                                      UnresolvedColumn)
        from spark_rapids_tpu.plan import logical as L
        from spark_rapids_tpu.plan.logical import AggregateExpression
        if scan is None:
            return None
        post, node = [], plan
        while isinstance(node, (L.Sort, L.Limit, L.Filter)):
            post.append(node)
            node = node.children[0]
        if not isinstance(node, L.Aggregate):
            return None
        agg = node
        pre = agg.child
        c = pre
        while isinstance(c, (L.Filter, L.Project)):
            c = c.children[0]
        if c is not scan:
            return None

        keys = [(ge.name, ge.dtype) for ge in agg.group_exprs]
        if len({n for n, _ in keys}) != len(keys):
            return None  # duplicate key names would mis-merge
        if any(n.startswith("__p") for n, _ in keys):
            return None  # reserved partial-column prefix
        partial_aggs: List = []   # Alias(AggregateExpression, pname)
        merge_aggs: List = []
        final_tail: List = []
        partial_cols: List[Tuple[str, object]] = []

        def add(pname, update_func, merge_cls):
            ae = AggregateExpression(update_func)
            partial_aggs.append(Alias(ae, pname))
            partial_cols.append((pname, ae.dtype))
            merge_aggs.append(Alias(AggregateExpression(
                merge_cls(UnresolvedColumn(pname))), pname))

        for i, e in enumerate(agg.agg_exprs):
            name = e.name
            inner = e.children[0] if isinstance(e, Alias) else e
            if not isinstance(inner, AggregateExpression):
                return None
            func = inner.func
            child = func.child
            if child is not None and child.dtype.is_decimal:
                return None  # sum(decimal) widens per level; no merge form
            if isinstance(func, ag.Average):
                sname, cname = f"__p{i}s", f"__p{i}c"
                add(sname, ag.Sum(Cast(child, dts.FLOAT64)), ag.Sum)
                add(cname, ag.Count(child), ag.Sum)
                final_tail.append(Alias(
                    Divide(UnresolvedColumn(sname),
                           UnresolvedColumn(cname)), name))
            elif isinstance(func, ag.Sum):
                add(f"__p{i}", ag.Sum(child), ag.Sum)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            elif isinstance(func, ag.Count):
                add(f"__p{i}", ag.Count(child), ag.Sum)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            elif isinstance(func, ag.Min):
                add(f"__p{i}", ag.Min(child), ag.Min)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            elif isinstance(func, ag.Max):
                add(f"__p{i}", ag.Max(child), ag.Max)
                final_tail.append(Alias(UnresolvedColumn(f"__p{i}"),
                                        name))
            else:
                return None  # first/last/collect/moments: order- or
                #               shape-dependent; no safe delta merge yet

        partial_schema = keys + partial_cols
        merge_keys = [Alias(UnresolvedColumn(n), n) for n, _ in keys]
        final_exprs = [UnresolvedColumn(n) for n, _ in keys] + final_tail
        spec = cls(agg, pre, post, partial_aggs, merge_keys, merge_aggs,
                   final_exprs, partial_schema)
        # the decomposition must reproduce the original output schema
        # exactly — name or dtype drift means the merge form is not the
        # same query, so refuse it rather than answer differently
        try:
            probe = spec.result_plan([])
        except Exception:
            return None
        if [(n, dt.name) for n, dt in probe.schema] != \
                [(n, dt.name) for n, dt in plan.schema]:
            return None
        return spec

    # -- plan builders ----------------------------------------------------
    def partial_plan(self, scan, paths):
        """Partial aggregate over ONLY ``paths`` (the delta)."""
        from spark_rapids_tpu.plan import logical as L
        child = _replace_scan(self.pre_root, scan, paths)
        return L.Aggregate(list(self.agg.group_exprs),
                           list(self.partial_aggs), child)

    def merge_plan(self, batches):
        """Re-aggregate (old-state ⊕ delta) partial rows into the next
        epoch's state — the aggregate merge discipline over an
        in-memory union of partial batches."""
        from spark_rapids_tpu.plan import logical as L
        rel = L.InMemoryRelation(batches, self.partial_schema)
        return L.Aggregate(list(self.merge_keys), list(self.merge_aggs),
                           rel)

    def result_plan(self, state_batches):
        """Finalize projection over the merged state (avg = sum/count)
        with the post-aggregate operator chain re-applied."""
        from spark_rapids_tpu.plan import logical as L
        rel = L.InMemoryRelation(state_batches, self.partial_schema)
        node = L.Project(list(self.final_exprs), rel)
        for op in reversed(self.post_ops):
            if isinstance(op, L.Sort):
                node = L.Sort(list(op.orders), node)
            elif isinstance(op, L.Limit):
                node = L.Limit(op.n, node)
            else:
                node = L.Filter(op.condition, node)
        return node


# ---------------------------------------------------------------- the runner --

class _TickDegraded(Exception):
    """Internal: the incremental path cannot proceed (no state, state
    dropped, fingerprint moved) — fall through to full recompute
    WITHOUT counting a rollback (nothing provisional was written)."""


class MicroBatchRunner:
    """One standing query over an append-only input.

    ``session.incremental(df)`` → runner; ``runner.tick(new_paths)``
    ingests the appended files and returns the query's result over
    everything ingested so far, as a DataFrame over the materialized
    result (cheap to ``collect()``/``to_pandas()``).  Ticks serialize
    per runner; each execution inside a tick is an ordinary query to
    the rest of the engine (admission, budgets, ladder, watchdog)."""

    def __init__(self, session, df):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session = session
        self.df = df
        conf = session.conf
        self.enabled = bool(conf.get(rc.INCREMENTAL_ENABLED)) and \
            getattr(session, "memory_catalog", None) is not None
        self.store: Optional[IncrementalStateStore] = \
            IncrementalStateStore(session) if self.enabled else None
        self._scan = _single_file_scan(df.plan)
        self._spec = _AggSpec.analyze(df.plan, self._scan) \
            if self.enabled else None
        self._initial = list(self._scan.paths) if self._scan is not None \
            else []
        self._paths: List[str] = []   # committed (ingested) input set
        self._ticked = False
        self._lock = threading.Lock()
        self._phase_log: list = []  # (name, t0_ns, dur_ns) per tick
        self.last_tick_info: Dict[str, object] = {}

    # ------------------------------------------------------------- helpers --
    def _fingerprint(self, paths) -> str:
        from spark_rapids_tpu.io.readers import scan_input_meta
        return self._meta_fingerprint(scan_input_meta(paths))

    @staticmethod
    def _meta_fingerprint(meta) -> str:
        """Fingerprint of an already-statted ``scan_input_meta``
        result — lets one stat walk serve both the staleness check and
        the new epoch's fingerprint within a tick."""
        from spark_rapids_tpu.io.readers import input_signature
        return hashlib.sha256(
            input_signature(sorted(meta)).encode()).hexdigest()

    def _run(self, plan, splice: bool = False) -> list:
        """Execute one logical plan through the full robustness stack.
        With ``splice`` the persistent store rides as the query's
        checkpoint manager, so unchanged (input-fingerprinted) subtrees
        restore instead of re-running."""
        from spark_rapids_tpu.api.dataframe import DataFrame
        df = DataFrame(self.session, plan)
        if splice and self.store is not None and \
                getattr(self.session, "mesh", None) is not None:
            self.store._splice_active = True
            self.session.checkpoints = self.store
            try:
                # stale-entry pruning at commit is only sound when the
                # FINAL attempt really ran on the mesh; the planner
                # signals that via note_distributed_complete on THIS
                # thread (a shared session attribute would race with
                # concurrent queries), and clear() (layout rung)
                # vetoes it for the rest of the tick
                return df._execute_batches()
            finally:
                self.session.checkpoints = None
        return df._execute_batches()

    @staticmethod
    def _concat(batches):
        from spark_rapids_tpu.ops.concat import concat_batches
        live = [b for b in batches if b.nrows]
        if not live:
            return None
        return concat_batches(live) if len(live) > 1 else live[0]

    def _result_df(self, batches, schema):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.plan import logical as L
        return DataFrame(self.session,
                         L.InMemoryRelation(batches, list(schema)))

    # ---------------------------------------------------------------- ticks --
    def tick(self, new_paths=()):
        """Ingest ``new_paths`` (appended files) and return the result
        over everything ingested so far."""
        with self._lock:
            return self._tick([new_paths] if isinstance(new_paths, str)
                              else list(new_paths))

    def _phased(self, name: str, fn, *args, **kwargs):
        """Run one tick phase, timing it for the span runtime.  Phase
        records are EMITTED only at tick end (_tick): a phase contains
        whole query envelopes whose own spans drain mid-tick, so an
        open phase span would smear into an inner query's trace —
        deferred emission keeps tick phases in the tick's own scope."""
        from spark_rapids_tpu.utils import tracing
        if not tracing._armed:
            return fn(*args, **kwargs)
        import time as _t
        t0 = _t.perf_counter_ns()
        try:
            return fn(*args, **kwargs)
        finally:
            self._phase_log.append(
                (name, t0, _t.perf_counter_ns() - t0))

    def _tick(self, new_paths):
        from spark_rapids_tpu.utils import tracing
        if not tracing._armed:
            return self._tick_impl(new_paths)
        import time as _t
        self._phase_log = []
        t0 = _t.perf_counter()
        try:
            return self._tick_impl(new_paths)
        finally:
            for name, t0_ns, dur_ns in self._phase_log:
                tracing.emit_span(f"incremental.{name}", t0_ns,
                                  dur_ns, is_async=False)
            self._phase_log = []
            ep = self.store.epoch if self.store is not None else 0
            tracing.finish_scope(self.session, f"tick-e{ep}",
                                 (_t.perf_counter() - t0) * 1e3)

    def _tick_impl(self, new_paths):
        from spark_rapids_tpu.plan import logical as L
        if new_paths and self._scan is None:
            raise ValueError(
                "tick(new_paths) needs a plan with exactly one file "
                "scan to append to; this plan has none (or several)")
        base = list(self._paths) if self._ticked else list(self._initial)
        seen = set(base)
        delta = []
        for p in new_paths:
            if p not in seen:  # dedupe within the call too: a watcher
                seen.add(p)    # emitting [p, p] must not ingest twice
                delta.append(p)
        target = base + delta
        if not self._ticked:
            delta = list(target)  # the first tick ingests everything
        incremental_metrics.bump("ticks")
        info: Dict[str, object] = {"deltaFiles": len(delta),
                                   "mode": "full", "reused": False}

        if self.store is None:
            # incremental.enabled=false parity: every tick is a plain
            # full execution, no standing state
            out = self._run(self._full_plan(target))
            self._finish(target, info)
            return self._result_df(out, self.df.plan.schema)

        try:
            out = self._tick_body(target, delta, info)
        except _TickDegraded:
            out = self._full_or_rollback(target, info)
        except Exception as exc:  # noqa: BLE001 - every escape degrades
            # mid-tick fault (exhausted ladder, fatal, admission
            # reject): roll back to the committed epoch, then answer
            # with a full recompute — never partial state, never wrong
            # bytes.  A full recompute that ALSO fails re-raises with
            # the epoch still intact.
            self.store.rollback(f"{type(exc).__name__}: {exc}")
            info["rollbackFrom"] = f"{type(exc).__name__}: {exc}"
            out = self._full_or_rollback(target, info)
        self._phased("commit", self.store.commit, info["mode"],
                     info["deltaFiles"], info["reused"])
        self._finish(target, info)
        return self._result_df(out, self.df.plan.schema)

    def _finish(self, target, info) -> None:
        self._paths = list(target)
        if self._scan is not None:
            # keep the standing plan's own scan in step, so a direct
            # df.to_pandas() (the oracle form) sees the ingested set
            self._scan.paths = list(target)
        self._ticked = True
        info["epoch"] = self.store.epoch if self.store is not None else 0
        self.last_tick_info = dict(info)

    def _full_plan(self, paths):
        if self._scan is None:
            return self.df.plan
        return _replace_scan(self.df.plan, self._scan, paths)

    def _tick_body(self, target, delta, info) -> list:
        """The incremental path; raises _TickDegraded when the
        committed epoch cannot carry this tick."""
        if self._spec is None or not self._ticked:
            raise _TickDegraded
        state = self.store.get_state()
        if state is None:
            raise _TickDegraded
        from spark_rapids_tpu.io.readers import scan_input_meta
        # one stat walk per file per tick: the committed-set walk
        # serves the staleness check, and the target fingerprint
        # derives from it plus the (small) delta walk
        meta_committed = scan_input_meta(self._paths)
        if self.store.state_fingerprint != \
                self._meta_fingerprint(meta_committed):
            # an already-ingested file changed out-of-band (rewritten,
            # truncated, even same-size — mtime catches it): the state
            # no longer describes the input
            self.store.drop_state("input-fingerprint-moved")
            raise _TickDegraded
        if delta:
            # stat BEFORE read: if a delta file mutates between the
            # stat and the scan, the committed fingerprint describes
            # the PRE-mutation bytes and the next tick's staleness
            # check drops the state — the safe failure mode.  Statting
            # after the read would stamp post-mutation identity onto
            # pre-mutation state and hide the mutation forever.
            meta_delta = scan_input_meta(delta)
            partial = self._phased(
                "delta", self._run,
                self._spec.partial_plan(self._scan, delta))
            merged = self._phased(
                "merge", self._run, self._spec.merge_plan(
                    [state] + [b for b in partial if b.nrows]))
            state = self._concat(merged)
            if state is None:
                from spark_rapids_tpu.columnar.batch import empty_batch
                state = empty_batch(self._spec.partial_schema)
            self.store.put_state(state, self._meta_fingerprint(
                meta_committed + meta_delta))
        out = self._phased("finalize", self._run,
                           self._spec.result_plan([state]))
        # counted only once the WHOLE incremental path answered: a
        # finalize-run fault degrades this tick to full recompute and
        # must not leave it double-counted in the reuse ratio
        info["mode"] = "incremental"
        info["reused"] = True
        incremental_metrics.bump("incrementalTicks")
        return out

    def _full_or_rollback(self, target, info) -> list:
        """Degraded recompute with the leak guard: a full recompute
        that dies mid-flight must not leave ITS provisional writes
        (the rebuilt state it put before the finalize run failed)
        pinned in the catalog — roll them back before re-raising, so
        the tick fails with the committed epoch exactly intact."""
        try:
            return self._tick_full(target, info)
        except Exception as exc:  # noqa: BLE001 - re-raised below
            self.store.rollback(
                f"degraded-recompute-failed: {type(exc).__name__}: "
                f"{exc}")
            raise

    def _tick_full(self, target, info) -> list:
        """Full recompute: correct under every degradation.  With a
        delta-capable plan the state rebuilds from one partial pass
        over ALL inputs (result derives from it); otherwise the
        original plan re-runs with the lineage splice restoring
        unchanged subtrees."""
        incremental_metrics.bump("fullRecomputes")
        info["mode"] = "full"
        if self._spec is not None:
            # stat before read (see _tick_body): a mid-scan mutation
            # must leave the state stamped with PRE-mutation identity
            fp = self._fingerprint(target)
            partial = self._phased(
                "recompute", self._run,
                self._spec.partial_plan(self._scan, target))
            state = self._concat(partial)
            if state is None:
                from spark_rapids_tpu.columnar.batch import empty_batch
                state = empty_batch(self._spec.partial_schema)
            self.store.put_state(state, fp)
            return self._phased("finalize", self._run,
                                self._spec.result_plan([state]))
        # reuse detection reads the STORE-LOCAL resume counter, not the
        # process-global one: concurrent runners must not contaminate
        # each other's reusedState flag
        r0 = self.store.local["resumes"]
        out = self._phased("recompute", self._run,
                           self._full_plan(target), splice=True)
        info["reused"] = self.store.local["resumes"] > r0
        return out

    def close(self) -> None:
        """Release the standing state (the runner's epochs die here;
        the session's catalog sweep would collect them at stop()
        anyway)."""
        if self.store is not None:
            self.store.close()
