"""QueryRetryDriver: query-level recovery with a bounded degradation
ladder.

Operator-level recovery (memory/retry.py) absorbs faults that stay
inside one exec node.  Whatever escapes to the query boundary lands
here, where the recovery options get progressively more drastic:

  retry      — re-run the same plan after a short backoff (transient
               reader/transport/preemption faults)
  spill      — demote the whole device store to host (memory/retry's
               ``_spill_device_store``) and re-run (device OOM)
  split      — re-plan with the scan/coalesce batch sizes halved so
               every operator sees smaller working sets (the query-
               level face of split-and-retry)
  demote     — re-plan the distributed query onto a single device
               (mesh sessions only; shuffle/host-sync faults that
               survive retries)
  shrink     — rebuild the mesh over the SURVIVING hosts and re-plan
               on it (fleet sessions only; a HostLossFault enters
               here — the lost host's shards are gone, but the
               remaining fleet can still answer distributed)
  cpu        — re-plan the whole query onto the CPU fallback chain
               (exec/fallback.py) — slow, but it answers

The ladder only ever moves forward (a fault during the split attempt
never goes back to plain retries), every action is appended to
``session.recovery_log`` and emitted as a ``RecoveryAction`` event on
the session's event log, and FATAL faults re-raise immediately — the
driver exists to absorb classified infrastructure failures, never to
mask bugs.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from spark_rapids_tpu.robustness import faults as F

# distinct jitter seeds for unlabeled drivers (see QueryRetryDriver)
_jitter_seeds = itertools.count(1)

# ladder rungs, in escalation order
RETRY = "retry"
SPILL_RETRY = "spill"
SPLIT_RETRY = "split"
DEMOTE_SINGLE_DEVICE = "demote"
SHRINK_FLEET = "shrink"
CPU_FALLBACK = "cpu"

# canonical escalation order (every ladder is a subsequence of this)
RUNG_ORDER = [RETRY, SPILL_RETRY, SPLIT_RETRY, DEMOTE_SINGLE_DEVICE,
              SHRINK_FLEET, CPU_FALLBACK]

# rungs that change the plan's shard layout: stage-checkpoint lineage
# keyed to the mesh layout is stale once any of these runs
_LAYOUT_CHANGING = (SPLIT_RETRY, DEMOTE_SINGLE_DEVICE, SHRINK_FLEET,
                    CPU_FALLBACK)


@dataclass
class AttemptMode:
    """What the next execution attempt is allowed to look like.  The
    attempt callable receives this and shapes planning accordingly."""

    rung: str = "initial"
    use_mesh: bool = True
    cpu_only: bool = False
    batch_scale: float = 1.0
    # resume capability, orthogonal to the rungs: when True the
    # distributed planner consults the query's stage-checkpoint lineage
    # log (robustness/checkpoint.py) and splices completed subtrees in
    # from the spill catalog instead of re-running them.  Armed for
    # retry-class re-attempts that keep the shard layout; rungs that
    # change it (split/demote/cpu) clear the log instead
    resume: bool = False


class RecoveryMetrics:
    """Process-wide recovery counters (per-action), surfaced by
    tools/profiling.py alongside the OOM retry counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {}

    def bump(self, action: str) -> None:
        with self._lock:
            self.counts[action] = self.counts.get(action, 0) + 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)

    def reset(self) -> None:
        with self._lock:
            self.counts.clear()


recovery_metrics = RecoveryMetrics()


def record_degradation(session, kind: str, action: str, error: str
                       ) -> None:
    """Record a recovery action handled *locally* by a subsystem (e.g.
    the UDF worker pool degrading to inline evaluation) so it shows up
    in the same trail/event stream as driver-level recoveries."""
    recovery_metrics.bump(action)
    rec = {"action": action, "fault": kind, "error": error,
           "rung": "local"}
    if session is not None:
        getattr(session, "recovery_log", []).append(rec)
        ev = getattr(session, "events", None)
        if ev is not None and ev.enabled:
            ev.emit("RecoveryAction",
                    queryId=getattr(session, "_current_qid", None),
                    action=action, fault=kind, error=error,
                    rung="local")


class QueryRetryDriver:
    """Drives one query's execution attempts down the degradation
    ladder.  ``run(attempt)`` calls ``attempt(mode)`` until it returns,
    the ladder is exhausted, or a FATAL fault surfaces."""

    def __init__(self, session, label: str = ""):
        import random
        import zlib
        self.session = session
        self.label = label
        self.trail: List[dict] = []
        from spark_rapids_tpu.config import rapids_conf as rc
        conf = session.conf
        self.enabled = conf.get(rc.QUERY_RECOVERY_ENABLED)
        self.max_retries = conf.get(rc.QUERY_RECOVERY_MAX_RETRIES)
        self.backoff_s = conf.get(rc.QUERY_RECOVERY_BACKOFF_MS) / 1e3
        self.backoff_cap_s = \
            conf.get(rc.QUERY_RECOVERY_BACKOFF_CAP_MS) / 1e3
        # jitter de-synchronizes retry herds (every SPMD controller
        # re-driving the same preempted step at once).  A labeled
        # driver seeds from its label so chaos runs replay the exact
        # same sleep sequence; unlabeled (production) drivers each
        # draw a distinct seed, else every driver would jitter in
        # lockstep and the herd would survive
        seed = zlib.crc32(label.encode()) if label else \
            (os.getpid() << 20) ^ next(_jitter_seeds)
        self._rng = random.Random(seed)
        # ladder cursor state (reset by run(); initialized here so
        # _advance_to is exercisable standalone in unit tests)
        self._rungs: List[str] = self._ladder()
        self._pos = 0

    # ------------------------------------------------------------ ladder --
    def _ladder(self) -> List[str]:
        rungs = [RETRY] * self.max_retries + [SPILL_RETRY, SPLIT_RETRY]
        if getattr(self.session, "mesh", None) is not None:
            rungs.append(DEMOTE_SINGLE_DEVICE)
            if getattr(self.session, "fleet_membership", None) \
                    is not None:
                rungs.append(SHRINK_FLEET)
        rungs.append(CPU_FALLBACK)
        return rungs

    @staticmethod
    def _entry_rung(fault: F.Fault) -> str:
        if fault.kind == "host_loss":
            # identical re-execution waits on a dead peer forever; the
            # shrink rung rebuilds the mesh over survivors first.  A
            # non-fleet session has no shrink rung in its ladder, so
            # _advance_to escalates this entry to cpu — the only rung
            # that doesn't need the lost host
            return SHRINK_FLEET
        if fault.severity == F.DEGRADABLE:
            # identical re-execution is pointless; jump to plan
            # changes.  Spill corruption enters at SPLIT: the dropped
            # batch's bytes only exist at the source, and a re-planned
            # attempt re-reads inputs (demoting off the mesh would not)
            return SPLIT_RETRY \
                if fault.kind in ("device_oom", "spill_corruption") \
                else DEMOTE_SINGLE_DEVICE
        if fault.kind == "device_oom":
            # a bare retry without freeing HBM would just OOM again
            return SPILL_RETRY
        return RETRY

    def _mode_for(self, rung: str, prev: AttemptMode) -> AttemptMode:
        mode = AttemptMode(rung=rung, use_mesh=prev.use_mesh,
                           cpu_only=prev.cpu_only,
                           batch_scale=prev.batch_scale)
        if rung == SPLIT_RETRY:
            mode.batch_scale = prev.batch_scale / 2
        elif rung == DEMOTE_SINGLE_DEVICE:
            mode.use_mesh = False
        elif rung == SHRINK_FLEET:
            # stays distributed: the attempt re-reads session.mesh,
            # which _shrink_fleet just rebuilt over the survivors
            mode.use_mesh = True
        elif rung == CPU_FALLBACK:
            mode.use_mesh = False
            mode.cpu_only = True
        return mode

    # ------------------------------------------------------------ events --
    def _record(self, action: str, fault: F.Fault,
                exc: BaseException) -> None:
        recovery_metrics.bump(action)
        rec = {"action": action, "fault": fault.kind,
               "severity": fault.severity,
               "error": f"{type(exc).__name__}: {exc}"}
        self.trail.append(rec)
        getattr(self.session, "recovery_log", []).append(rec)
        ev = getattr(self.session, "events", None)
        if ev is not None and ev.enabled:
            ev.emit("RecoveryAction",
                    queryId=getattr(self.session, "_current_qid", None),
                    action=action, fault=fault.kind,
                    severity=fault.severity, error=rec["error"],
                    label=self.label)

    def _emit_summary(self, status: str) -> None:
        if not self.trail:
            return
        ev = getattr(self.session, "events", None)
        if ev is not None and ev.enabled:
            ev.emit("QueryRecovery",
                    queryId=getattr(self.session, "_current_qid", None),
                    status=status, actions=self.trail,
                    label=self.label)

    def _advance_to(self, level: str) -> None:
        """Move the ladder cursor FORWARD (never backward) to the
        first rung at or above ``level`` in the canonical escalation
        order — the single place rung-reentry position is computed.  A
        device OOM never burns plain-retry budget, a degradable fault
        never burns the spill/split budget, and an entry rung missing
        from this ladder (demote without a mesh) escalates to the next
        rung present.  A cursor already past the requested level stays
        where it is: the ladder only ever moves forward."""
        want = RUNG_ORDER.index(level)
        entry_pos = next(
            (i for i, r in enumerate(self._rungs)
             if RUNG_ORDER.index(r) >= want), len(self._rungs))
        self._pos = max(self._pos, entry_pos)

    def _update_lineage(self, rung: str, mode: AttemptMode) -> None:
        """Stage-checkpoint wiring: retry-class re-attempts keep the
        shard layout and may resume from the lineage log; layout-
        changing rungs (split/demote/cpu) invalidate the log via
        ``clear()`` — for the per-query manager that wipes everything
        (its stage ids are keyed to a layout this query no longer
        runs on), while the session-persistent incremental store
        overrides clear() to drop only this tick's PROVISIONAL
        entries: its committed epochs stay keyed to the mesh layout,
        which survives the rung and serves the next tick."""
        mgr = getattr(self.session, "checkpoints", None)
        if rung in _LAYOUT_CHANGING or not mode.use_mesh or \
                mode.cpu_only or mode.batch_scale != 1.0:
            mode.resume = False
            if mgr is not None:
                mgr.clear(f"rung:{rung}")
        elif mgr is not None:
            mode.resume = True

    # --------------------------------------------------------------- run --
    def run(self, attempt: Callable[[AttemptMode], Any]) -> Any:
        mode = AttemptMode()
        if not self.enabled:
            return attempt(mode)
        self._rungs = self._ladder()
        self._pos = 0  # next rung to use on failure; only moves forward
        backoffs = 0
        self._gray_enter()
        try:
            while True:
                try:
                    result = attempt(mode)
                    self._emit_summary("recovered")
                    return result
                except Exception as exc:  # noqa: BLE001 - classified below
                    fault = F.classify(exc)
                    if fault.fatal:
                        self._emit_summary("fatal")
                        raise
                    self._advance_to(self._entry_rung(fault))
                    if self._pos >= len(self._rungs):
                        self._emit_summary("exhausted")
                        raise
                    rung = self._rungs[self._pos]
                    self._pos += 1
                    self._record(rung, fault, exc)
                    mode = self._mode_for(rung, mode)
                    self._update_lineage(rung, mode)
                    if rung == SPILL_RETRY:
                        self._spill_device_store()
                    if rung == SHRINK_FLEET:
                        self._shrink_fleet(exc)
                    if rung == RETRY and self.backoff_s > 0:
                        # exponential backoff, capped (backoffCapMs) and
                        # jittered into [0.5, 1.0]x — chaos tests and
                        # real preemptions both stay responsive, and
                        # concurrent drivers never retry in lockstep
                        base = min(self.backoff_s * (2 ** backoffs),
                                   self.backoff_cap_s)
                        time.sleep(
                            base * (0.5 + 0.5 * self._rng.random()))
                        backoffs += 1
        finally:
            self._gray_exit()

    def _gray_enter(self) -> None:
        """Safe boundary for gray-failure mitigation: before a query's
        FIRST attempt (no plan in flight on this driver yet), let the
        session apply due quarantine drains / rejoins, so the attempt
        plans on the post-mitigation mesh.  The inflight count gates
        mesh swaps — a concurrent query mid-flight defers mitigation to
        the next boundary.  No-op without a tracker."""
        if getattr(self.session, "gray_health", None) is None:
            return
        with self.session._gray_lock:
            self.session._gray_inflight += 1
        try:
            self.session.maybe_apply_gray_actions()
        except Exception:
            pass  # mitigation is best-effort; never blocks the query

    def _gray_exit(self) -> None:
        if getattr(self.session, "gray_health", None) is None:
            return
        with self.session._gray_lock:
            self.session._gray_inflight = max(
                0, self.session._gray_inflight - 1)

    def _shrink_fleet(self, exc: BaseException) -> None:
        """Rebuild the session mesh over surviving hosts (the shrink
        rung's side effect; the re-attempt reads session.mesh fresh
        and re-plans on the new layout).  Best-effort: a shrink that
        cannot help — nothing survives, no fleet — leaves the mesh
        alone and the re-attempt's failure escalates to cpu."""
        try:
            self.session.shrink_fleet_mesh(
                lost_host=getattr(exc, "host", -1))
        except Exception:
            pass

    @staticmethod
    def _spill_device_store() -> None:
        import gc
        gc.collect()  # drop dead device buffers so XLA can reuse HBM
        from spark_rapids_tpu.memory.retry import _spill_device_store
        _spill_device_store()
