"""Stage-boundary lineage checkpoints for partial query recovery.

The PR1 recovery ladder (driver.py) re-executes every failed query
*from source*: a fault in the last exchange of a five-stage plan throws
away every completed stage, and under chaos spray the retry ladder
multiplies end-to-end latency by the number of completed stages.
Theseus-style resilience (PAPERS.md) treats materialized exchange
outputs as durable, restartable units; this module is that unit for the
mesh engine.

Every time the distributed planner (parallel/dist_planner.py) completes
an exchange-consuming operator — aggregate, join, sort, window, top-N —
the post-shuffle, compacted ShardedFrame is registered here as a named
**StageCheckpoint** in a per-query lineage log:

- the **stage id** is a stable hash of the plan subtree plus the shard
  layout (mesh axes/devices and the packed-wire flag), so the same
  subtree re-planned on the next attempt resolves to the same entry;
- the **payload** lives in the session's spill catalog
  (memory/spill.py) and therefore inherits CRC32 integrity stamps,
  DEVICE→HOST→DISK tier demotion under HBM pressure, and atomic disk
  frames; the manager additionally stamps its own canonical checksum at
  write time so a checkpoint that never left the DEVICE tier is still
  verified on restore;
- on a **resume** attempt (QueryRetryDriver arms ``mode.resume`` for
  retry/spill rungs) the planner consults the log before recursing into
  a subtree and splices the checkpoint in place of the completed work —
  skipping its readers, stages, and collectives entirely;
- a checkpoint that fails verification, no longer materializes, or was
  evicted is **dropped from the log and the subtree re-runs** — never
  wrong bytes, never a stuck query;
- rungs that change the shard layout (split scales batches, demote/cpu
  leave the mesh) **clear the log**: lineage keyed to a layout that no
  longer exists must not resurface.

Governed by ``spark.rapids.sql.recovery.checkpoint.enabled`` /
``.maxBytes`` / ``.tiers``; observable end to end — ``CheckpointWrite``
/ ``CheckpointResume`` / ``CheckpointEvict`` / ``CheckpointInvalid``
events → eventlog ``QueryInfo.checkpoint`` → profiling report + health
checks — with watchdog sections around write/restore so a wedged disk
write classifies as a ``TimeoutFault`` instead of hanging the query.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from spark_rapids_tpu.robustness import watchdog
from spark_rapids_tpu.robustness.inject import (fire, fire_mutate,
                                                register_point)

# checkpoints are insurance, colder than shuffle outputs: under HBM
# pressure they demote before any live batch (SpillPriorities analog)
CHECKPOINT_PRIORITY = -1500

# injection surface: a raise/delay rule on the write covers a wedged
# checkpoint store; a corrupt rule on the restore flips payload bits so
# the CRC gate has real rot to catch (the fire_mutate chaos hook)
register_point("checkpoint.write")
register_point("checkpoint.restore")


class CheckpointMetrics:
    """Process-wide checkpoint counters, surfaced by tools/profiling
    and bench.py alongside the recovery/watchdog counters."""

    FIELDS = ("writes", "bytesWritten", "resumes", "stagesSkipped",
              "evictions", "invalid")

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {k: 0 for k in self.FIELDS}

    def bump(self, field: str, by: int = 1) -> None:
        with self._lock:
            self.counters[field] += int(by)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def reset(self) -> None:
        with self._lock:
            for k in self.counters:
                self.counters[k] = 0


checkpoint_metrics = CheckpointMetrics()


def input_fingerprint(plan, memo: Optional[dict] = None) -> str:
    """Identity of everything a subtree READS: for every FileRelation
    leaf the sorted (path, size, mtime_ns) triples of its input files
    (appending a file — or mutating one: new size, or a SAME-SIZE
    in-place rewrite, which only the mtime catches — changes the
    fingerprint), and for every InMemoryRelation the identity of its
    live batch objects (two relations alive at once can never share an
    id; the owning plan keeps its batches alive, so a recycled id
    cannot alias).  Folded into the stage lineage key of the
    session-persistent store (robustness/incremental.py) so a
    cross-query splice can only ever use a frame computed from
    byte-identical inputs; the per-query log skips the fold — its ids
    only need intra-query stability, and inputs cannot change
    mid-query.

    ``memo`` (a per-planner-run dict) caches each scan node's stat
    walk: a deep plan stats every file once per EXECUTION ATTEMPT, not
    once per enclosing checkpointable subtree — safe because inputs
    may not change mid-attempt (the existing lineage contract), and
    the memo dies with the planner, so a later attempt (or tick)
    re-observes the filesystem."""
    from spark_rapids_tpu.plan import logical as L
    parts = []

    def scan_part(node):
        if memo is not None and id(node) in memo:
            return memo[id(node)]
        from spark_rapids_tpu.io.readers import (input_signature,
                                                 scan_input_meta)
        part = "files:" + input_signature(scan_input_meta(node.paths))
        if memo is not None:
            memo[id(node)] = part
        return part

    def walk(node):
        if isinstance(node, L.FileRelation):
            parts.append(scan_part(node))
        elif isinstance(node, L.InMemoryRelation):
            parts.append("mem:" + ";".join(
                f"{id(b)}={b.nrows}" for b in node.batches))
        for c in node.children:
            walk(c)

    walk(plan)
    return "\x1e".join(parts)


def stage_id(plan, mesh, packed: bool = True,
             memo: Optional[dict] = None, inputs: bool = True) -> str:
    """Stable lineage key for one plan subtree on one shard layout.
    Structural, not object identity: every re-planned attempt of the
    same query resolves the same subtree to the same id, and two
    occurrences of an identical subtree (a self-join) legitimately
    share one checkpoint — same plan, same layout, same bytes.  With
    ``inputs`` (the default, and what the session-persistent store
    needs) the key also folds in the subtree's INPUT fingerprint
    (file list + sizes + mtimes; see input_fingerprint) so a lineage store
    resuming ACROSS queries can never splice a frame computed from
    different bytes: appending files moves exactly the scan-adjacent
    subtrees' ids and leaves static subtrees resumable.  The
    per-query manager passes ``inputs=False`` — its keys only need
    intra-query stability (inputs cannot change mid-query), and the
    fingerprint's stat walk is pure planning-path overhead there.
    A full-width sha256 digest, not a 32-bit crc: a lineage-key
    collision between two different subtrees would splice the WRONG
    stage's (individually valid) bytes into a resumed plan, the one
    failure the payload checksum cannot catch."""
    import hashlib
    sig = "\x1f".join([
        plan.tree_string(),
        input_fingerprint(plan, memo) if inputs else "",
        ",".join(mesh.axis_names),
        "x".join(str(d) for d in mesh.devices.shape),
        ",".join(str(d) for d in mesh.devices.flat),
        f"packed={bool(packed)}",
    ])
    return hashlib.sha256(sig.encode()).hexdigest()


class StageCheckpoint:
    """One lineage entry: the spill-catalog handle holding the frame
    payload plus the host-side frame metadata (schema, dictionaries,
    shard layout) needed to splice it back into a plan."""

    __slots__ = ("stage_id", "handle", "names", "log_dtypes", "enc",
                 "nshards", "capacity", "crc", "size_bytes", "stages",
                 "seq")

    def __init__(self, sid: str, handle, names, log_dtypes, enc,
                 nshards: int, capacity: int, crc: int,
                 size_bytes: int, stages: int, seq: int):
        self.stage_id = sid
        self.handle = handle
        self.names = list(names)
        self.log_dtypes = list(log_dtypes)
        self.enc = {k: list(v) for k, v in enc.items()}
        self.nshards = nshards
        self.capacity = capacity
        self.crc = crc
        self.size_bytes = size_bytes
        self.stages = stages  # exchange stages the subtree contains
        self.seq = seq


def _frame_payload(frame) -> dict:
    """Canonical host payload of a ShardedFrame: per-column value and
    mask buffers plus the per-shard counts vector, keyed so the spill
    module's canonical checksum covers every byte.  The whole frame
    comes down in ONE budgeted transfer (utils/hostsync.fetch_all) —
    syncs are a counted resource, and per-buffer ``np.asarray`` would
    pay a tunnel round trip per column on real hardware."""
    from spark_rapids_tpu.utils.hostsync import fetch_all
    bufs = [frame.nrows]
    for v, m in frame.cols:
        bufs.append(v)
        bufs.append(m)
    host = fetch_all(bufs)
    payload = {"__counts.data": np.ascontiguousarray(
        np.asarray(host[0], dtype=np.int32))}
    for i in range(len(frame.cols)):
        payload[f"c{i}.data"] = np.ascontiguousarray(host[1 + 2 * i])
        payload[f"c{i}.validity"] = np.ascontiguousarray(
            np.asarray(host[2 + 2 * i], dtype=bool))
    return payload


class CheckpointManager:
    """Per-query lineage log of StageCheckpoints.

    Lives on ``session.checkpoints`` for the duration of one
    ``DataFrame._execute_batches`` call (all attempts of one query);
    the driver arms ``resume`` on retry-class rungs and clears the log
    on layout-changing rungs; the planner saves after every completed
    exchange stage and restores on resume attempts."""

    # the session-persistent subclass (robustness/incremental.py
    # IncrementalStateStore) sets this True: the planner then consults
    # the log on FIRST attempts too, not only recovery re-attempts —
    # input-fingerprinted stage ids make the cross-query splice safe
    always_resume = False
    # spill priority stage payloads register at (the persistent store
    # registers colder still — standing state never competes with a
    # live query's checkpoints for HBM)
    priority = CHECKPOINT_PRIORITY

    def __init__(self, session):
        from spark_rapids_tpu.config import rapids_conf as rc
        self.session = session
        conf = session.conf
        self.enabled = bool(conf.get(rc.RECOVERY_CHECKPOINT_ENABLED))
        self.max_bytes = int(conf.get(rc.RECOVERY_CHECKPOINT_MAX_BYTES))
        self.tiers = tuple(
            t.strip().upper()
            for t in conf.get(rc.RECOVERY_CHECKPOINT_TIERS).split(",")
            if t.strip())
        self.catalog = getattr(session, "memory_catalog", None)
        self._entries: Dict[str, StageCheckpoint] = {}
        self._seq = 0
        self.local = {k: 0 for k in CheckpointMetrics.FIELDS}

    # --------------------------------------------------------------- plumbing --
    @classmethod
    def for_query(cls, session) -> Optional["CheckpointManager"]:
        """Install a manager on the session for one query execution.
        None (and no session mutation) when checkpointing cannot apply:
        no mesh, conf disabled, no spill catalog, or a manager already
        active (a nested query must not clobber the outer lineage)."""
        if getattr(session, "mesh", None) is None:
            return None
        if getattr(session, "checkpoints", None) is not None:
            return None
        mgr = cls(session)
        if not mgr.enabled or mgr.catalog is None:
            return None
        session.checkpoints = mgr
        return mgr

    def finish(self) -> None:
        """Query over (success or not): release every payload and
        detach from the session.  Lineage never outlives its query —
        the stage ids are only meaningful against this query's plan."""
        for e in list(self._entries.values()):
            try:
                e.handle.close()
            except Exception:
                pass
        self._entries.clear()
        if getattr(self.session, "checkpoints", None) is self:
            self.session.checkpoints = None

    def _bump(self, field: str, by: int = 1) -> None:
        checkpoint_metrics.bump(field, by)
        self.local[field] += int(by)

    def _emit(self, event: str, **fields) -> None:
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session(event, session=self.session, **fields)

    def snapshot(self) -> Dict[str, int]:
        out = dict(self.local)
        out["live"] = len(self._entries)
        out["liveBytes"] = self.live_bytes
        out["liveBytesRaw"] = self.live_bytes_raw
        return out

    def note_distributed_complete(self) -> None:
        """Hook called by ``try_distributed`` on the executing thread
        when a query ANSWERS distributed (the final successful
        attempt, by construction).  No-op here; the session-persistent
        store uses it as the thread-safe signal that stale-entry
        pruning is sound — a shared session attribute like
        ``last_dist_explain`` would race under concurrent queries."""

    @staticmethod
    def _entry_bytes(entry) -> int:
        """Bytes an entry occupies at its CURRENT tier: compressed
        host/disk frames (encoding.storage.hostCodec) meter their
        encoded size, so maxBytes buys proportionally more retained
        lineage when the codec is on."""
        h = getattr(entry, "handle", None)
        if h is not None and not h.closed:
            return h.stored_bytes
        return entry.size_bytes

    @property
    def live_bytes(self) -> int:
        return sum(self._entry_bytes(e)
                   for e in self._entries.values())

    @property
    def live_bytes_raw(self) -> int:
        """Decoded (device-canonical) size of the same entries — the
        raw side of the storage-compression ratio."""
        return sum(e.size_bytes for e in self._entries.values())

    # ------------------------------------------------------------------ write --
    def save(self, sid: str, frame, stages: int = 1,
             shareable: bool = False) -> None:
        """Register a completed stage's ShardedFrame under ``sid``.
        Best-effort: an I/O failure while persisting drops the
        checkpoint (the query continues without it); a watchdog
        deadline on a wedged write still classifies as TimeoutFault.
        ``shareable`` is the planner's hint that the sid's input
        fingerprint is purely file-backed (no in-memory batch
        identities), i.e. derivable by OTHER queries holding the same
        subtree — ignored here; the session-persistent store uses it
        to scope cross-query epoch publication."""
        if not self.enabled or sid in self._entries:
            return
        with watchdog.section("checkpoint.write"):
            fire("checkpoint.write")
            try:
                self._save_body(sid, frame, stages)
            except OSError:
                # a checkpoint is an optimization; losing one must
                # never fail the query that just computed the data
                self.drop(sid, reason="write-failed")

    def _save_body(self, sid: str, frame, stages: int) -> None:
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.column import Column
        from spark_rapids_tpu.memory.spill import (DEVICE,
                                                   _payload_checksum)
        if not frame.cols:
            return
        payload = _frame_payload(frame)
        total = int(payload["c0.data"].shape[0])
        crc = _payload_checksum(payload, total)
        # every column carries the batch's logical nrows (the flat
        # nshards*capacity buffer length) so the spill codec keeps the
        # full padded buffers; __counts is just a short int32 buffer
        # riding along (nothing iterates it by nrows)
        cols = {"__counts": Column(
            _int32_dtype(), payload["__counts.data"], total)}
        for i, dt in enumerate(frame.phys_dtypes):
            cols[f"c{i}"] = Column(dt, payload[f"c{i}.data"], total,
                                   validity=payload[f"c{i}.validity"])
        batch = ColumnarBatch(cols, nrows=total)
        handle = self.catalog.register(batch, priority=self.priority)
        entry = StageCheckpoint(
            sid, handle, frame.names, frame.log_dtypes, frame.enc,
            frame.nshards, frame.capacity, crc, handle.size_bytes,
            stages, self._seq)
        self._seq += 1
        self._entries[sid] = entry
        if DEVICE not in self.tiers:
            # tier policy excludes HBM residency: demote the payload
            # now so checkpoints never compete with live batches
            self.catalog.demote(handle,
                                self.tiers[0] if self.tiers else "HOST")
        self._bump("writes")
        self._bump("bytesWritten", entry.size_bytes)
        self._emit("CheckpointWrite", stageId=sid,
                   bytes=entry.size_bytes, stages=stages,
                   tier=handle.tier)
        self._evict_over_budget()

    def _evict_over_budget(self) -> None:
        """Oldest-first eviction keeps the lineage log inside
        ``maxBytes`` — the same HBM-goal accounting the coalesce/spill
        path applies to transient wire bytes (PR4 precedent): the spill
        catalog already counts the payloads against the device budget,
        and this cap bounds what the log may pin across ALL tiers."""
        while self.live_bytes > self.max_bytes and self._entries:
            victim = min(self._entries.values(), key=lambda e: e.seq)
            self.drop(victim.stage_id, reason="max-bytes", evict=True)

    # ---------------------------------------------------------------- restore --
    def restore(self, sid: str, mesh):
        """Materialize the checkpoint for ``sid`` back into a
        ShardedFrame, or None when the subtree must re-run (no entry,
        eviction, CRC mismatch, undecodable payload).  Wrong bytes are
        never returned: verification failure drops the entry and lands
        a CheckpointInvalid event on the trail."""
        entry = self._entries.get(sid)
        if entry is None:
            return None
        from spark_rapids_tpu.robustness.faults import CorruptionFault
        with watchdog.section("checkpoint.restore"):
            try:
                batch = entry.handle.materialize()
            except (CorruptionFault, OSError, ValueError) as e:
                # the spill tiers' own CRC gate (or a vanished disk
                # frame / closed handle) already dropped the batch;
                # treat it as an invalid checkpoint, not a query fault
                self.drop(sid, reason=f"{type(e).__name__}: {e}")
                return None
            return self._restore_body(sid, entry, batch, mesh)

    def _restore_body(self, sid, entry, batch, mesh):
        from spark_rapids_tpu.memory.spill import _payload_checksum
        from spark_rapids_tpu.parallel.dist_planner import ShardedFrame
        payload = {"__counts.data":
                   batch.columns["__counts"].host_values()
                   [:entry.nshards].astype(np.int32)}
        for i in range(len(entry.names)):
            col = batch.columns[f"c{i}"]
            payload[f"c{i}.data"] = col.host_values()
            v = col.host_validity()
            payload[f"c{i}.validity"] = v if v is not None else \
                np.ones(col.capacity, dtype=bool)
        # chaos hook: offer the first data buffer to an armed corrupt
        # rule so the verification gate has real rot to catch
        mutated = fire_mutate("checkpoint.restore", payload["c0.data"]) \
            if entry.names else payload.get("c0.data")
        if mutated is not None:
            payload["c0.data"] = mutated
        total = int(payload["c0.data"].shape[0]) if entry.names else 0
        got = _payload_checksum(payload, total)
        if got != entry.crc:
            self.drop(sid, reason=f"crc {got:#010x} != stored "
                                  f"{entry.crc:#010x}")
            return None
        # host_put, not jnp.asarray: every fleet controller restores the
        # identical host payload, so each contributes its shards of the
        # global frame (single-controller this IS jnp.asarray)
        from spark_rapids_tpu.parallel.mesh import host_put
        cols = [(host_put(mesh, payload[f"c{i}.data"]),
                 host_put(mesh, payload[f"c{i}.validity"]))
                for i in range(len(entry.names))]
        nrows = host_put(mesh, payload["__counts.data"])
        self._bump("resumes")
        self._bump("stagesSkipped", entry.stages)
        self._emit("CheckpointResume", stageId=sid,
                   bytes=entry.size_bytes, stagesSaved=entry.stages)
        return ShardedFrame(mesh, entry.names, entry.log_dtypes, cols,
                            nrows, entry.enc)

    # ------------------------------------------------------------ invalidation --
    def drop(self, sid: str, reason: str, evict: bool = False) -> None:
        """Remove one entry (verification failure, eviction, write
        failure); its subtree simply re-runs on the next attempt."""
        entry = self._entries.pop(sid, None)
        if entry is not None:
            try:
                entry.handle.close()
            except Exception:
                pass
        if evict:
            self._bump("evictions")
            self._emit("CheckpointEvict", stageId=sid, reason=reason,
                       bytes=entry.size_bytes if entry else 0)
        else:
            self._bump("invalid")
            self._emit("CheckpointInvalid", stageId=sid, reason=reason)

    def clear(self, reason: str) -> None:
        """Invalidate the whole log — a ladder rung changed the shard
        layout (split/demote/cpu), so every lineage key is stale."""
        if not self._entries:
            return
        for sid in list(self._entries):
            entry = self._entries.pop(sid)
            try:
                entry.handle.close()
            except Exception:
                pass
        self._bump("invalid")
        self._emit("CheckpointInvalid", stageId="*", reason=reason)


def _int32_dtype():
    from spark_rapids_tpu.columnar import dtypes as dts
    return dts.INT32
