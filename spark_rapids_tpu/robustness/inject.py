"""Scoped fault-injection registry: named points, count/probability
rules, context-manager scoping.

Generalizes the ad-hoc ``memory/retry.inject_oom(n)`` pattern (the
RmmSpark force-retry analog) to every failure surface the taxonomy
names.  Each subsystem threads one cheap ``fire(point)`` checkpoint
through its hot path; tests arm rules against those points:

    with injected("shuffle.exchange", count=2):
        df.to_pandas()          # first two exchanges die, driver recovers

Rules are thread-scoped by default (a rule armed on the test thread
never fires in another session's worker thread); points that execute
on pool threads — the disk spill writers — take ``all_threads=True``.

Beyond the default ``kind="raise"``, rules can **delay/hang**
(``kind="delay"``: the checkpoint wedges for ``delay_s`` seconds, or
until the rule is disarmed when ``delay_s`` is None — the hang the
watchdog must detect; each wedge slice is a cooperative cancellation
checkpoint, so a tripped deadline aborts the stuck caller exactly like
the runtime aborting a dead collective) or **corrupt**
(``kind="corrupt"``: flips one seeded bit in the payload offered at a
``fire_mutate`` site — the spill-tier restore paths — so checksum
verification has real rot to catch).

Adding an injection point is two lines: ``register_point(name,
default_exc)`` here (or at the subsystem's import time), and a
``fire(name)`` call at the failure site.  The default exception class
pins the fault kind/severity the real failure would classify as, so
the recovery path under test is the production one.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Type

from spark_rapids_tpu.robustness import faults as F
from spark_rapids_tpu.robustness import watchdog as _watchdog

RULE_KINDS = ("raise", "delay", "corrupt")

# known points -> the fault each raises by default.  "memory.oom" is
# the legacy inject_oom surface; its exception type lives in
# memory/retry.py (it must stay a MemoryError for is_oom), so it
# registers lazily from there.
_POINTS: Dict[str, Optional[Type[BaseException]]] = {
    "io.read": F.InjectedReaderFault,
    "shuffle.exchange": F.InjectedShuffleFault,
    "dist.host_sync": F.InjectedHostSyncFault,
    "spill.disk": F.InjectedSpillFault,
    # mutate-capable restore points (memory/spill.py fire_mutate):
    # corrupt rules flip payload bits here; raise/delay rules also apply
    "spill.corrupt.host": F.InjectedSpillFault,
    "spill.corrupt.disk": F.InjectedSpillFault,
    "udf.worker": F.InjectedWorkerFault,
    # async exchange path (parallel/exchange_async.py): the deferred
    # resolve-time verification of an in-flight exchange, and the
    # host-RAM staging round trip for oversized payloads.  Both
    # retryable shuffle faults: the ladder re-drives and the planner
    # degrades to the synchronous path on recovery re-attempts
    "exchange.async.resolve": F.InjectedShuffleFault,
    "exchange.host_staging": F.InjectedShuffleFault,
    # persistent jit-cache load (ops/jit_cache.py): raise/delay rules
    # simulate unreadable entries, corrupt rules flip payload bits at
    # the fire_mutate site so the CRC gate has rot to catch — every
    # flavor degrades to a fresh compile, never a failed query
    "jitcache.load": F.InjectedFault,
}


def register_point(name: str,
                   default_exc: Optional[Type[BaseException]] = None
                   ) -> None:
    """Declare an injection point (idempotent).  Subsystems call this
    at import time so ``injection_points()`` is the live catalog."""
    _POINTS.setdefault(name, default_exc)
    if default_exc is not None and _POINTS[name] is None:
        _POINTS[name] = default_exc


def injection_points() -> List[str]:
    return sorted(_POINTS)


class InjectionRule:
    """One armed rule.  Count-based by default (fire the next ``count``
    checkpoints after skipping ``skip``); with ``probability`` set,
    each checkpoint fires with that chance (seeded — chaos runs must
    replay) until ``count`` faults have fired."""

    def __init__(self, point: str, *, count: int = 1, skip: int = 0,
                 probability: Optional[float] = None,
                 seed: Optional[int] = None,
                 exc: Optional[Callable[..., BaseException]] = None,
                 all_threads: bool = False, kind: str = "raise",
                 delay_s: Optional[float] = None,
                 scope: Optional[str] = None):
        if point not in _POINTS:
            raise KeyError(
                f"unknown injection point {point!r}; known: "
                f"{injection_points()} (register_point to add one)")
        if kind not in RULE_KINDS:
            raise ValueError(
                f"unknown rule kind {kind!r}; known: {RULE_KINDS}")
        self.kind = kind
        # delay kind: wedge this long; None = hang until disarmed
        self.delay_s = delay_s
        self.point = point
        self.remaining = int(count)
        self.skip = int(skip)
        self.probability = probability
        self._rng = random.Random(seed)
        self.exc = exc or _POINTS[point] or F.InjectedFault
        self.thread_id = None if all_threads else threading.get_ident()
        # who armed this rule (effective ident: an adopted pipeline
        # worker arms on behalf of its driving thread) — scoped_rules
        # containment removes exactly ITS thread-tree's rules on exit,
        # so concurrent scopes on other threads never clobber each
        # other's armed rules
        _ident = threading.get_ident()
        self.armer = _adopted.get(_ident, _ident)
        # keyed scope (multi-tenant chaos): the rule only fires on
        # threads whose active scope key matches — explicit arg, or
        # inherited from the enclosing scoped_rules(key=...) block
        self.scope = scope if scope is not None else \
            _arming_scope()
        self.fired = 0

    def _matches_thread(self) -> bool:
        if self.scope is not None and self.scope != _active_scope():
            return False
        if self.thread_id is None:
            return True
        ident = threading.get_ident()
        # a pipeline worker adopts its driving thread's identity, so
        # rules armed on the test/driver thread still fire inside the
        # pipelined iterator (exec/pipeline.py)
        return self.thread_id == _adopted.get(ident, ident)

    def _should_fire(self) -> bool:
        if self.remaining <= 0 or not self._matches_thread():
            return False
        if self.probability is not None:
            return self._rng.random() < self.probability
        if self.skip > 0:
            self.skip -= 1
            return False
        return True

    def make_exc(self, note: str) -> BaseException:
        if isinstance(self.exc, type) and \
                issubclass(self.exc, F.InjectedFault):
            return self.exc(self.point, note)
        # plain exception classes (e.g. the legacy InjectedOomError)
        # take a single message
        return self.exc(f"injected fault at {self.point!r}"
                        + (f": {note}" if note else ""))


_lock = threading.Lock()
_rules: List[InjectionRule] = []
# worker thread ident -> the driving thread it acts for (plain dict:
# int-keyed put/get/del are atomic under the GIL, and _matches_thread
# runs on the hot path)
_adopted: Dict[int, int] = {}


def adopt_thread(owner_ident: int) -> None:
    """Make rules armed by ``owner_ident`` fire on the calling thread.
    Used by exec/pipeline.py so a fault injected for a query keeps
    firing when the operator iterator moves to the pipeline worker."""
    _adopted[threading.get_ident()] = owner_ident


def release_thread() -> None:
    _adopted.pop(threading.get_ident(), None)


def disown(ident: int) -> None:
    """Sever ``ident``'s adoption from the outside (a driver
    abandoning a wedged worker): the zombie must not keep consuming
    rule budgets armed for the driving thread's next attempt."""
    _adopted.pop(ident, None)


def purge_adoptions(mapping: Dict[int, int], owner_ident: int) -> None:
    """Drop every entry of a worker->owner adoption dict that maps TO
    ``owner_ident`` — THE query-exit cleanup shared by every adoption
    registry (inject, watchdog, hostsync, retry, serving/context): the
    OS reuses thread idents, so an adoption a finished worker left
    behind would bind a future thread with the recycled ident to a
    dead query.  Callers holding a lock call this under it; the
    module-level dicts rely on GIL-atomic dict ops as everywhere else.
    """
    for ident in [i for i, o in list(mapping.items())
                  if o == owner_ident]:
        mapping.pop(ident, None)


def purge_owner(owner_ident: int) -> None:
    """This registry's query-exit cleanup (see :func:`purge_adoptions`
    and serving/context.QueryContext.__exit__)."""
    purge_adoptions(_adopted, owner_ident)


# scope keys (multi-tenant chaos): owner thread ident -> active key.
# Worker threads resolve through _adopted, so a rule scoped to one
# query fires in that query's pipeline worker but never in another
# query's — even with all_threads=True.
_scopes: Dict[int, str] = {}
# thread ident -> key new rules armed by that thread inherit
_arming: Dict[int, str] = {}


def _active_scope() -> Optional[str]:
    ident = threading.get_ident()
    return _scopes.get(_adopted.get(ident, ident))


def _arming_scope() -> Optional[str]:
    return _arming.get(threading.get_ident())


# thread trees (owner idents) with a scoped_rules block currently
# open: a rule armed by a tree with its OWN open scope is that
# scope's to clean up; a rule armed by any other thread is an orphan
# the enclosing scope removes on exit (the test-fixture containment
# guarantee)
_open_scopes: Dict[int, int] = {}


# cheap hot-path guard: fire() is threaded through per-batch loops and
# must cost one attribute read when nothing is armed
_armed = False


def inject(point: str, **kw) -> InjectionRule:
    """Arm a rule; see ``InjectionRule`` for the knobs.  Returns the
    rule (pass to ``remove``/inspect ``fired``)."""
    global _armed
    rule = InjectionRule(point, **kw)
    with _lock:
        _rules.append(rule)
        _armed = True
    return rule


def remove(rule: InjectionRule) -> None:
    global _armed
    with _lock:
        if rule in _rules:
            _rules.remove(rule)
        _armed = bool(_rules)


def clear(point: Optional[str] = None, *,
          this_thread_only: bool = False) -> None:
    """Disarm every rule (or just one point's).  With
    ``this_thread_only``, only rules armed by the calling thread are
    removed — the inject_oom shim needs the old ``threading.local``
    semantics where one thread's re-arm never disarms another's."""
    global _armed
    tid = threading.get_ident()

    def _keep(r: InjectionRule) -> bool:
        if point is not None and r.point != point:
            return True
        return this_thread_only and r.thread_id != tid

    with _lock:
        _rules[:] = [r for r in _rules if _keep(r)]
        _armed = bool(_rules)


def clear_point(point: str) -> None:
    """Disarm every rule armed on one point, regardless of which
    thread armed it — the targeted cleanup for a test that sprayed a
    single point across threads (``clear(point)`` sugar, named so the
    intent reads at the call site)."""
    clear(point)


@contextmanager
def injected(point: str, **kw):
    """Scope a rule to a ``with`` block — the chaos-test idiom."""
    rule = inject(point, **kw)
    try:
        yield rule
    finally:
        remove(rule)


@contextmanager
def scoped_rules(key: Optional[str] = None):
    """Hard containment scope: every rule armed inside the block —
    including rules the body leaked by never removing them, or armed
    on worker threads with ``all_threads=True`` — is disarmed on exit,
    UNLESS the arming thread tree has its own scoped_rules block still
    open (then that scope's exit owns the cleanup — one concurrent
    client finishing must never disarm another client's still-armed
    rules).  Rules armed BEFORE the scope survive it (and stay
    removable inside it).  Test fixtures wrap each test in one of
    these so injection rules can never leak across tests, whatever
    the teardown order.

    With ``key``, the scope is **keyed** (the multi-tenant chaos
    form): rules armed inside the block carry the key and only fire on
    threads whose active scope matches — this thread for the duration
    of the block, plus any worker adopted into it.  Concurrent clients
    each wrap their query in ``scoped_rules(key=client_id)``: client
    A's rules provably cannot fire inside client B's query, whatever
    ``all_threads``/probability knobs the rules use."""
    global _armed
    ident = threading.get_ident()
    my_tree = _adopted.get(ident, ident)
    prev_scope = _scopes.get(ident)
    prev_arming = _arming.get(ident)
    if key is not None:
        _scopes[ident] = key
        _arming[ident] = key
    with _lock:
        before = list(_rules)
        _open_scopes[my_tree] = _open_scopes.get(my_tree, 0) + 1
    try:
        yield
    finally:
        if key is not None:
            if prev_scope is None:
                _scopes.pop(ident, None)
            else:
                _scopes[ident] = prev_scope
            if prev_arming is None:
                _arming.pop(ident, None)
            else:
                _arming[ident] = prev_arming
        with _lock:
            # close MY scope first so my own rules are not protected
            # by it, then remove every rule armed inside the block
            # except those owned by another LIVE tree's still-open
            # scope.  The liveness check keeps the fixture guarantee
            # against scopes whose thread died without exiting (a
            # killed client can never run its own cleanup), and prunes
            # their stale _open_scopes entries so a recycled ident
            # cannot inherit the protection
            n = _open_scopes.get(my_tree, 1) - 1
            if n:
                _open_scopes[my_tree] = n
            else:
                _open_scopes.pop(my_tree, None)
            live = {t.ident for t in threading.enumerate()}
            for tree in [t for t in _open_scopes if t not in live]:
                del _open_scopes[tree]
            survivors = [r for r in _rules
                         if r in before or
                         (r.armer != my_tree and
                          _open_scopes.get(r.armer, 0) > 0)]
            _rules[:] = survivors
            _armed = bool(_rules)


def _pick(point: str, mutating: bool) -> Optional[InjectionRule]:
    """Select-and-consume the next firing rule for ``point``.  Corrupt
    rules only apply at mutate-capable sites (``fire_mutate``)."""
    with _lock:
        for rule in _rules:
            if rule.point != point:
                continue
            if rule.kind == "corrupt" and not mutating:
                continue
            if rule._should_fire():
                rule.remaining -= 1
                rule.fired += 1
                return rule
    return None


def _wedge(rule: InjectionRule) -> None:
    """The delay/hang kind: sleep in slices until the rule's duration
    elapses or the rule is disarmed (tests un-wedge by removing it).
    Each slice is a watchdog cancellation checkpoint, so a tripped
    deadline aborts the stuck caller — the cooperative analog of the
    runtime tearing down a dead collective with DEADLINE_EXCEEDED."""
    t_end = None if rule.delay_s is None else \
        time.monotonic() + rule.delay_s
    while True:
        _watchdog.checkpoint()
        if t_end is not None and time.monotonic() >= t_end:
            return
        with _lock:
            if rule not in _rules:
                return
        time.sleep(0.005)


def fire(point: str, note: str = "") -> None:
    """Checkpoint: apply the armed rule for ``point``, if any (raise
    its fault, or wedge for a delay rule).  Called on the engine's hot
    paths — the unarmed cost is one global read.  Every fire site is
    also a watchdog cancellation checkpoint."""
    _watchdog.checkpoint()
    if not _armed:
        return
    rule = _pick(point, mutating=False)
    if rule is None:
        return
    if rule.kind == "delay":
        _wedge(rule)
        return
    raise rule.make_exc(note)


def fire_mutate(point: str, data):
    """Mutate-capable checkpoint: offered a payload (bytes or a numpy
    array), a corrupt rule returns a copy with one seeded bit flipped;
    raise/delay rules behave as at ``fire``.  Returns ``data``
    unchanged when nothing fires."""
    _watchdog.checkpoint()
    if not _armed:
        return data
    rule = _pick(point, mutating=True)
    if rule is None:
        return data
    if rule.kind == "delay":
        _wedge(rule)
        return data
    if rule.kind == "corrupt":
        return _flip_bit(data, rule._rng)
    raise rule.make_exc("")


def _flip_bit(data, rng: random.Random):
    """One seeded bit flip in a COPY of the payload (the stored
    original must rot, not the caller's live view — callers pass the
    stored buffer and adopt the return value)."""
    import numpy as np
    if isinstance(data, (bytes, bytearray)):
        if not data:
            return data
        arr = np.frombuffer(bytes(data), dtype=np.uint8).copy()
        arr[rng.randrange(arr.size)] ^= 1 << rng.randrange(8)
        return arr.tobytes()
    out = np.ascontiguousarray(data).copy()
    flat = out.view(np.uint8).reshape(-1)
    if not flat.size:
        return data
    flat[rng.randrange(flat.size)] ^= 1 << rng.randrange(8)
    return out
