"""Robustness subsystem: fault taxonomy, scoped fault injection, and the
query-level recovery/degradation driver.

The reference engine spreads resilience across RMM OOM callbacks, UCX
shuffle retry/heartbeats, and per-operator split-and-retry (SURVEY.md
section 2.5).  This package centralises the TPU port's answer:

- ``faults``  — classify every failure the engine can see into
  retryable / degradable / fatal (absorbing ``memory/retry.is_oom``).
- ``inject``  — named injection points threaded through the I/O,
  shuffle, multi-host sync, spill, and UDF layers, generalizing the
  ad-hoc ``inject_oom(n)`` test hook; rules can raise, delay/hang,
  or corrupt payload bits.
- ``driver``  — ``QueryRetryDriver``: wraps plan execution with a
  bounded degradation ladder (retry -> spill-retry -> split-batch ->
  single-device replan -> CPU fallback) and records every recovery
  action as a structured event.
- ``watchdog`` — deadlines over monitored engine sections with a
  heartbeat from the pipeline worker; overruns become retryable
  ``TimeoutFault``s delivered at cooperative cancellation
  checkpoints, so hangs enter the same ladder as exceptions.
"""

from spark_rapids_tpu.robustness.faults import (  # noqa: F401
    DEGRADABLE, FATAL, RETRYABLE, CorruptionFault, Fault,
    HostSyncError, InjectedFault, SpillIOError, TimeoutFault, classify)
# NOTE: the ``inject`` submodule is imported as a module (its main
# entry point is also named ``inject``, which would shadow it here);
# use ``from spark_rapids_tpu.robustness import inject`` and call
# ``inject.inject(...)`` / ``inject.injected(...)``.
from spark_rapids_tpu.robustness.inject import (  # noqa: F401
    fire, injected, injection_points)
