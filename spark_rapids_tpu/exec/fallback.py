"""CPU fallback operator.

The analog of the reference leaving unconverted Spark ops on the CPU: a
logical node with no (or disallowed) TPU conversion executes on the host via
pandas.  Columnar data crosses the device boundary exactly once each way
(the GpuColumnarToRow/RowToColumnar transition-pair analog,
GpuTransitionOverrides.scala:44).  Per-row nodes (project/filter/expand/
generate/union/limit, and the probe side of inner/left joins) STREAM one
child batch at a time; aggregates fold chunks into mergeable per-group
partial states — only sort and the build/global sides of joins ever
materialize a whole child.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.plan import logical as L


def _ansi_fail(cast_expr, value):
    """ANSI casts raise on conversion failure even on the CPU path."""
    if getattr(cast_expr, "ansi", False):
        raise ArithmeticError(
            f"invalid input {value!r} for ANSI cast to "
            f"{cast_expr.target}")
    return None


def _isnull(v) -> bool:
    """Null test for scalar values out of pandas (None, pd.NA, or NaN —
    python float AND numpy float32/float64 scalars)."""
    return v is None or v is pd.NA or (
        isinstance(v, (float, np.floating)) and pd.isna(v))


def _align_datetime_operands(l: pd.Series, r: pd.Series):
    """Make date/timestamp comparisons work on the host path.

    The arrow bridge yields tz-aware ``datetime64[us, UTC]`` for
    TIMESTAMP columns and ``datetime.date`` objects for DATE32, while
    API literals arrive as raw python ``date``/``datetime`` values —
    pandas refuses to compare those shapes directly.  Normalize both
    sides to Timestamps (date -> midnight, the date32->timestamp cast
    semantics) and match tz-awareness."""
    import datetime as _dt

    import pandas.api.types as pt

    def kind(s):
        if pt.is_datetime64_any_dtype(s):
            return "ts"
        if s.dtype == object:
            probe = next((v for v in s if not _isnull(v)), None)
            if isinstance(probe, (_dt.date, _dt.datetime, np.datetime64)):
                return "obj"
        return None

    kl, kr = kind(l), kind(r)
    if not (kl and kr):
        return l, r
    if kl == kr == "ts":
        # both already datetime64 — still align tz-awareness (an
        # arrow-bridge tz-aware series vs a fallback-cast naive one
        # raises TypeError in pandas otherwise)
        ltz = getattr(l.dtype, "tz", None)
        rtz = getattr(r.dtype, "tz", None)
        if ltz is not None and rtz is None:
            return l, r.dt.tz_localize(ltz)
        if rtz is not None and ltz is None:
            return l.dt.tz_localize(rtz), r
        return l, r
    def norm(s, k):
        if k != "obj":
            return s
        return pd.to_datetime(s.map(
            lambda v: None if _isnull(v) else pd.Timestamp(v)))
    l2, r2 = norm(l, kl), norm(r, kr)
    ltz = getattr(l2.dtype, "tz", None)
    rtz = getattr(r2.dtype, "tz", None)
    if ltz is not None and rtz is None:
        r2 = r2.dt.tz_localize(ltz)
    elif rtz is not None and ltz is None:
        l2 = l2.dt.tz_localize(rtz)
    return l2, r2


def _eval_pandas(expr, df: pd.DataFrame):
    """Host evaluation of an expression over a pandas frame — the CPU-Spark
    analog used when a Project/Filter falls back (e.g. uncompilable UDFs)."""
    from spark_rapids_tpu.ops import arithmetic as A
    from spark_rapids_tpu.ops import predicates as P
    from spark_rapids_tpu.ops.expressions import (
        Alias, BoundReference, Literal, ParamSlot, UnresolvedColumn)
    from spark_rapids_tpu.udf.python_exec import PythonUDF

    e = expr
    if isinstance(e, Alias):
        return _eval_pandas(e.child, df)
    if isinstance(e, BoundReference):
        return df.iloc[:, e.ordinal]
    if isinstance(e, UnresolvedColumn):
        return df[e.col_name]
    if isinstance(e, Literal):
        return pd.Series([e.value] * len(df))
    if isinstance(e, ParamSlot):
        # hoisted literal (plan/template.py): the CPU rung evaluates the
        # current binding — a recovery re-drive of a prepared run must
        # see the same value the kernels would have
        return pd.Series([e.value] * len(df))
    if isinstance(e, PythonUDF):
        args = [_eval_pandas(c, df) for c in e.children]
        out = [None if any(pd.isna(v) for v in row) else e.fn(*row)
               for row in zip(*[a.tolist() for a in args])] if args else []
        return pd.Series(out, dtype=object)
    binops = {A.Add: "__add__", A.Subtract: "__sub__",
              A.Multiply: "__mul__", A.Divide: "__truediv__",
              P.LessThan: "__lt__", P.LessThanOrEqual: "__le__",
              P.GreaterThan: "__gt__", P.GreaterThanOrEqual: "__ge__",
              P.EqualTo: "__eq__"}
    comparisons = (P.LessThan, P.LessThanOrEqual, P.GreaterThan,
                   P.GreaterThanOrEqual, P.EqualTo)
    for cls, method in binops.items():
        if type(e) is cls:
            l = _eval_pandas(e.children[0], df)
            r = _eval_pandas(e.children[1], df)
            if cls in comparisons:
                l, r = _align_datetime_operands(l, r)
            return getattr(l, method)(r)
    if isinstance(e, P.And):
        return _eval_pandas(e.left, df) & _eval_pandas(e.right, df)
    if isinstance(e, P.Or):
        return _eval_pandas(e.left, df) | _eval_pandas(e.right, df)
    if isinstance(e, P.Not):
        return ~_eval_pandas(e.child, df)
    from spark_rapids_tpu.ops.cast import Cast as _Cast
    if isinstance(e, _Cast):
        child = _eval_pandas(e.child, df)
        t = e.target
        def conv(v):
            if _isnull(v):
                return None
            try:
                if t.is_string:
                    if isinstance(v, bool):
                        return "true" if v else "false"
                    if isinstance(v, float):
                        import math
                        if math.isnan(v):
                            return "NaN"
                        if math.isinf(v):
                            return "Infinity" if v > 0 else "-Infinity"
                        if v == int(v) and abs(v) < 1e16:
                            return f"{v:.1f}"
                    return str(v)
                if t.is_boolean:
                    if isinstance(v, str):
                        lv = v.strip().lower()
                        if lv in ("true", "t", "yes", "y", "1"):
                            return True
                        if lv in ("false", "f", "no", "n", "0"):
                            return False
                        return _ansi_fail(e, v)
                    return bool(v)
                if t.is_integral:
                    return int(float(v)) if isinstance(v, str) else int(v)
                if t.is_floating:
                    return float(v)
            except (ValueError, TypeError, OverflowError):
                return _ansi_fail(e, v)
            return v
        return child.map(conv)
    from spark_rapids_tpu.ops import stringops as S
    if isinstance(e, S.Like):
        import re
        rx = "(?s)" + "".join(".*" if ch == "%" else "." if ch == "_"
                              else re.escape(ch) for ch in e.pattern)
        child = _eval_pandas(e.child, df)
        return child.str.match(rx + r"\Z", na=False)
    if isinstance(e, S.Upper):
        # full-Unicode semantics on the CPU path: the device op is
        # ASCII-only (its incompat flag), and the fallback exists
        # precisely to provide CPU Spark behavior
        child = _eval_pandas(e.child, df)
        return child.map(lambda v: None if _isnull(v) else v.upper())
    if isinstance(e, S.Lower):
        child = _eval_pandas(e.child, df)
        return child.map(lambda v: None if _isnull(v) else v.lower())
    if isinstance(e, S.InitCap):
        child = _eval_pandas(e.child, df)
        def initcap(v):
            out = []
            prev_space = True
            for ch in v:
                out.append(ch.upper() if prev_space else ch.lower())
                prev_space = ch == " "
            return "".join(out)
        return child.map(lambda v: None if _isnull(v) else initcap(v))
    if isinstance(e, (S.StringTrim, S.StringTrimLeft, S.StringTrimRight)):
        child = _eval_pandas(e.child, df)
        fn = {"StringTrim": lambda v: v.strip(" "),
              "StringTrimLeft": lambda v: v.lstrip(" "),
              "StringTrimRight": lambda v: v.rstrip(" ")}[
                  type(e).__name__]
        return child.map(lambda v: None if _isnull(v) else fn(v))
    if isinstance(e, S.Length):
        child = _eval_pandas(e.child, df)
        return child.map(lambda v: None if _isnull(v) else len(v))
    if isinstance(e, S.Substring):
        child = _eval_pandas(e.child, df)

        def sub(v):
            pos, ln = e.pos, e.length
            if ln < 0:
                return ""
            if pos > 0:
                start = pos - 1
            elif pos == 0:
                start = 0
            else:
                # Spark substringSQL: the window is [len+pos, len+pos+ln)
                # BEFORE clamping, so a far-negative pos eats into ln
                start = len(v) + pos
                end = start + ln
                return v[max(start, 0):max(end, 0)]
            return v[start:start + ln]

        return child.map(lambda v: None if _isnull(v) else sub(v))
    if isinstance(e, S.ConcatStrings):
        parts = [_eval_pandas(c, df) for c in e.children]
        return pd.Series([
            None if any(_isnull(v) for v in row) else "".join(row)
            for row in zip(*parts)])
    if isinstance(e, (S.StartsWith, S.EndsWith, S.Contains)):
        child = _eval_pandas(e.child, df)
        fn = {"StartsWith": str.startswith, "EndsWith": str.endswith,
              "Contains": str.__contains__}[type(e).__name__]
        return child.map(lambda v: None if _isnull(v)
                         else fn(v, e.pattern))
    from spark_rapids_tpu.ops import json_ops as J
    if isinstance(e, (J.GetJsonObject, J.StringSplit)):
        child = _eval_pandas(e.children[0], df)
        return child.map(lambda v: None if _isnull(v)
                         else e.eval_host(v))
    from spark_rapids_tpu.ops import datetime_ops as DT
    if isinstance(e, DT.DateFormatClass):
        child = _eval_pandas(e.children[0], df)
        strf = e.fmt
        for a, b in (("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
                     ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
                     ("EEEE", "%A"), ("E", "%a"), ("DDD", "%j")):
            strf = strf.replace(a, b)
        return child.map(lambda v: None if _isnull(v)
                         else pd.Timestamp(v).strftime(strf))
    if isinstance(e, DT.TimeWindow):
        child = _eval_pandas(e.children[0], df)

        def edge(v):
            ts = pd.Timestamp(v).value // 1000  # ns -> us
            start = ts - (ts - e.start_us) % e.slide_us - e.shift_us
            out = start if e.field == "start" else start + e.window_us
            return pd.Timestamp(out * 1000)

        return child.map(lambda v: None if _isnull(v) else edge(v))
    from spark_rapids_tpu.ops.predicates import InSet as _InSet
    if isinstance(e, _InSet):
        child = _eval_pandas(e.children[0], df)
        hit = child.isin(list(e.table))
        out = hit.astype(object)
        if e.has_null:
            out[~hit] = None
        out[child.isna()] = None
        return out
    from spark_rapids_tpu.ops import regexops as RX
    if isinstance(e, RX.RLike):
        import re
        child = _eval_pandas(e.child, df)
        rx = re.compile(e.pattern)
        return child.map(lambda v: None if _isnull(v)
                         else bool(rx.search(v)))
    if isinstance(e, RX.RegExpReplace):
        import re
        child = _eval_pandas(e.child, df)
        rx = re.compile(e.pattern)
        # translate a Java replacement to Python re.sub syntax: $n (multi
        # digit) -> \g<n>, \x -> literal x, lone backslashes escaped
        out = []
        i = 0
        r = e.replacement
        while i < len(r):
            ch = r[i]
            if ch == "\\" and i + 1 < len(r):
                out.append(re.escape(r[i + 1]) if r[i + 1] != "\\"
                           else "\\\\")
                i += 2
            elif ch == "$" and i + 1 < len(r) and r[i + 1].isdigit():
                j = i + 1
                while j < len(r) and r[j].isdigit():
                    j += 1
                out.append(f"\\g<{r[i + 1:j]}>")
                i = j
            elif ch == "\\":
                out.append("\\\\")
                i += 1
            else:
                out.append(ch)
                i += 1
        repl = "".join(out)
        return child.map(lambda v: None if _isnull(v)
                         else rx.sub(repl, v))
    if isinstance(e, RX.StringReplace):
        child = _eval_pandas(e.child, df)
        if not e.search:  # Spark: empty search leaves input unchanged
            return child
        return child.map(lambda v: None if _isnull(v) else
                         v.replace(e.search, e.replacement))
    if isinstance(e, RX.Translate):
        child = _eval_pandas(e.child, df)
        # Spark: FIRST occurrence in from_str wins (str.maketrans would
        # apply last-wins and deletion-overrides)
        tbl = {}
        for i, ch in enumerate(e.from_str):
            o = ord(ch)
            if o not in tbl:
                tbl[o] = e.to_str[i] if i < len(e.to_str) else None
        return child.map(lambda v: None if _isnull(v)
                         else v.translate(tbl))
    if isinstance(e, RX.SplitPart):
        import re
        child = _eval_pandas(e.child, df)
        def part(v):
            if _isnull(v):
                return None
            parts = re.split(e.delim, v)
            return parts[e.index] if 0 <= e.index < len(parts) else None
        return child.map(part)
    if isinstance(e, RX.ConcatWs):
        parts = [_eval_pandas(c, df) for c in e.children]
        return pd.Series([
            e.sep.join(str(v) for v in row if not _isnull(v))
            for row in zip(*parts)])
    from spark_rapids_tpu.ops.misc_exprs import Md5 as _Md5
    if isinstance(e, _Md5):
        import hashlib
        child = _eval_pandas(e.child, df)
        return child.map(lambda v: None if _isnull(v) else
                         hashlib.md5(str(v).encode()).hexdigest())
    from spark_rapids_tpu.ops import collections_ops as C
    if isinstance(e, C.CreateArray):
        parts = [_eval_pandas(c, df) for c in e.children]
        return pd.Series([list(row) for row in zip(*parts)])
    if isinstance(e, C.Size):
        child = _eval_pandas(e.child, df)
        return child.map(lambda v: -1 if _isnull(v) else len(v))
    if isinstance(e, C.SortArray):
        child = _eval_pandas(e.children[0], df)
        return child.map(lambda v: None if _isnull(v) else
                         sorted(v, reverse=not e.ascending))
    if isinstance(e, C.ElementAt):
        arr = _eval_pandas(e.children[0], df)
        idx = _eval_pandas(e.children[1], df)
        def at(v, i):
            if _isnull(v):
                return None
            j = i - 1 if i > 0 else len(v) + i
            return v[j] if 0 <= j < len(v) else None
        return pd.Series([at(v, i) for v, i in zip(arr, idx)])
    if isinstance(e, C.GetArrayItem):
        arr = _eval_pandas(e.children[0], df)
        idx = _eval_pandas(e.children[1], df)
        return pd.Series([None if _isnull(v) or not 0 <= i < len(v)
                          else v[i] for v, i in zip(arr, idx)])
    if isinstance(e, C.ArrayContains):
        arr = _eval_pandas(e.children[0], df)
        val = _eval_pandas(e.children[1], df)
        return pd.Series([None if _isnull(v) else (x in v)
                          for v, x in zip(arr, val)])
    if isinstance(e, (C.ArrayMin, C.ArrayMax)):
        import math
        child = _eval_pandas(e.children[0], df)
        want_max = isinstance(e, C.ArrayMax)

        def extreme(v):
            if _isnull(v) or not len(v):
                return None
            vals = list(v)
            nans = [x for x in vals
                    if isinstance(x, float) and math.isnan(x)]
            if nans:
                # Spark total order: NaN > everything
                if want_max or len(nans) == len(vals):
                    return float("nan")
                vals = [x for x in vals
                        if not (isinstance(x, float) and math.isnan(x))]
            import builtins
            return builtins.max(vals) if want_max else builtins.min(vals)
        return child.map(extreme)
    if isinstance(e, C.Reverse):
        child = _eval_pandas(e.children[0], df)
        return child.map(lambda v: None if _isnull(v) else (
            v[::-1] if isinstance(v, str) else list(reversed(v))))
    if isinstance(e, C.Slice):
        child = _eval_pandas(e.children[0], df)

        def sl(v):
            s = e.start - 1 if e.start > 0 else len(v) + e.start
            if s < 0:  # Spark: out-of-range negative start -> empty
                return []
            return list(v[s:s + e.length])
        return child.map(lambda v: None if _isnull(v) else sl(v))
    if isinstance(e, C.ArrayRepeat):
        child = _eval_pandas(e.children[0], df)
        return child.map(lambda v: None if _isnull(v)
                         else [v] * e.times)
    from spark_rapids_tpu.ops.arithmetic import Hypot as _Hypot
    if isinstance(e, _Hypot):
        l = pd.to_numeric(_eval_pandas(e.children[0], df),
                          errors="coerce")
        r = pd.to_numeric(_eval_pandas(e.children[1], df),
                          errors="coerce")
        return pd.Series(np.hypot(l, r))
    if isinstance(e, DT.NextDay):
        child = _eval_pandas(e.children[0], df)

        def nd(v):
            if e.target is None:
                return None
            ts = pd.Timestamp(v)
            ahead = (e.target - ts.weekday() + 7) % 7 or 7
            return (ts + pd.Timedelta(days=ahead)).date()
        return child.map(lambda v: None if _isnull(v) else nd(v))
    if isinstance(e, S.Ascii):
        child = _eval_pandas(e.children[0], df)
        return child.map(lambda v: None if _isnull(v)
                         else (ord(v[0]) if v else 0))
    if isinstance(e, S.Chr):
        import builtins
        child = _eval_pandas(e.children[0], df)
        return child.map(lambda v: None if _isnull(v) else
                         ("" if int(v) < 0 else builtins.chr(int(v) % 256)))
    raise NotImplementedError(
        f"CPU fallback cannot evaluate {type(e).__name__}")


def _is_expand(node) -> bool:
    from spark_rapids_tpu.exec.expand import Expand
    return isinstance(node, Expand)


class _Neg:
    """Order-inverting wrapper so descending keys ride the same
    ascending k-way merge (works for any comparable type, unlike
    numeric negation)."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, o):
        return o.v < self.v

    def __eq__(self, o):
        return self.v == o.v


class _Unset:
    def __repr__(self):
        return "<unset>"


_UNSET = _Unset()


class _NullKey:
    """Canonical hashable stand-in for a null group key.  NaN objects
    coming out of per-chunk ``groupby`` hash by identity, so merging
    partial states across chunks needs one shared null token."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<null>"


_NULL_KEY = _NullKey()


def _agg_update(func, state, sub: pd.DataFrame):
    """Fold one input chunk into a mergeable partial state for one
    aggregate function — the host-side partial/merge split that keeps
    the fallback from ever holding the whole input in one frame."""
    k = func.name
    s = _eval_pandas(func.child, sub) if func.child is not None else None
    if s is not None and (k not in ("first", "last") or
                          getattr(func, "ignore_nulls", False)):
        # first/last keep nulls unless ignoreNulls was requested
        # (Spark default ignoreNulls=false); every other aggregate is
        # null-skipping by definition
        s = s.dropna()
    if k == "count":
        n = len(s) if s is not None else len(sub)
        return n if state is _UNSET else state + n
    if k == "sum":
        if not len(s):
            return state
        v = s.sum()
        return v if state is _UNSET else state + v
    if k == "min":
        if not len(s):
            return state
        v = s.min()
        return v if state is _UNSET or v < state else state
    if k == "max":
        if not len(s):
            return state
        v = s.max()
        return v if state is _UNSET or v > state else state
    if k in ("avg", "average", "mean"):
        st = (0, 0) if state is _UNSET else state
        if len(s):
            st = (st[0] + s.sum(), st[1] + len(s))
        return st
    if k == "first":
        if state is not _UNSET:
            return state
        return s.iloc[0] if len(s) else _UNSET
    if k == "last":
        return s.iloc[-1] if len(s) else state
    if k == "collect_list":
        st = [] if state is _UNSET else state
        st.extend(s)
        return st
    if k == "collect_set":
        st = set() if state is _UNSET else state
        st.update(s)
        return st
    if k in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        st = (0.0, 0.0, 0) if state is _UNSET else state
        if len(s):
            x = s.astype(float)
            st = (st[0] + x.sum(), st[1] + (x * x).sum(), st[2] + len(x))
        return st
    raise NotImplementedError(f"CPU fallback aggregate {k}")


def _agg_finalize(func, state):
    k = func.name
    if k == "count":
        return 0 if state is _UNSET else state
    if k in ("avg", "average", "mean"):
        if state is _UNSET or state[1] == 0:
            return None
        return state[0] / state[1]
    if k == "collect_list":
        return [] if state is _UNSET else state
    if k == "collect_set":
        return [] if state is _UNSET else sorted(state)
    if k in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        if state is _UNSET or state[2] == 0:
            return None
        s1, s2, n = state
        ddof = 1 if k.endswith("samp") else 0
        if n - ddof <= 0:
            return float("nan")  # Spark: sample stats of one row
        m2 = max(s2 - s1 * s1 / n, 0.0)
        out = m2 / (n - ddof)
        return out ** 0.5 if k.startswith("stddev") else out
    return None if state is _UNSET else state


class CpuFallbackExec(TpuExec):
    def __init__(self, node: L.LogicalPlan, children: List[TpuExec]):
        super().__init__(*children)
        self.node = node

    @property
    def schema(self) -> Schema:
        return self.node.schema

    def describe(self):
        return f"CpuFallbackExec[{self.node.describe()}]"

    def _child_pandas(self, i: int) -> pd.DataFrame:
        """Materialize child i — used only by nodes whose semantics need
        the whole input at once (sort, right/full join build)."""
        import pyarrow as pa
        batches = [b.to_arrow() for b in self.children[i].execute()]
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch
            return empty_batch(self.children[i].schema).to_pandas()
        return pa.concat_tables(batches).to_pandas()

    def _child_frames(self, i: int) -> Iterator[pd.DataFrame]:
        """Yield child i's output one bounded pandas frame per columnar
        batch.  Nodes with per-row semantics stream through this so one
        fallback node on big data never holds more than a batch of host
        rows (the round-3 verdict's OOC-discipline gap); always yields
        at least one (possibly empty) frame so the typed empty batch is
        still emitted."""
        empty = True
        for b in self.children[i].execute():
            empty = False
            yield b.to_arrow().to_pandas()
        if empty:
            from spark_rapids_tpu.columnar.batch import empty_batch
            yield empty_batch(self.children[i].schema).to_pandas()

    def do_execute(self) -> Iterator[ColumnarBatch]:
        node = self.node
        # ---- streaming nodes: per-row semantics, one chunk in flight ----
        if isinstance(node, L.Project):
            for df in self._child_frames(0):
                yield self._build_batch(pd.DataFrame(
                    {e.name: _eval_pandas(e, df) for e in node.exprs}))
            return
        if isinstance(node, L.Filter):
            for df in self._child_frames(0):
                mask = _eval_pandas(node.condition, df).fillna(False)
                yield self._build_batch(df[mask.astype(bool)])
            return
        if isinstance(node, L.Limit):
            remaining = node.n
            for df in self._child_frames(0):
                take = df.head(max(remaining, 0))
                remaining -= len(take)
                yield self._build_batch(take)
                if remaining <= 0:
                    break
            return
        if isinstance(node, L.Union):
            want = [n for n, _ in node.schema]
            for i in range(len(self.children)):
                for df in self._child_frames(i):
                    # union is positional: rename child cols in place
                    yield self._build_batch(df.set_axis(want, axis=1))
            return
        if _is_expand(node):
            from spark_rapids_tpu.exec.expand import NullLiteral
            # chunk-major / projection-inner, matching the device
            # Expand exec's batch ordering (exec/expand.py do_execute)
            for df in self._child_frames(0):
                for proj in node.projections:
                    cols = {}
                    for name, e in zip(node.names, proj):
                        if isinstance(e, NullLiteral):
                            cols[name] = pd.Series([None] * len(df),
                                                   dtype=object)
                        else:
                            cols[name] = _eval_pandas(e, df).reset_index(
                                drop=True)
                    yield self._build_batch(
                        pd.DataFrame(cols, columns=node.names))
            return
        if isinstance(node, L.Generate):
            for df in self._child_frames(0):
                arrs = _eval_pandas(node.generator, df)
                rows = []
                req = {e.name: _eval_pandas(e, df)
                       for e in node.required}
                for i, a in enumerate(arrs):
                    if a is None or (not isinstance(a, (list, tuple))
                                     and pd.isna(a)):
                        continue
                    for p, el in enumerate(a):
                        row = {n: s.iloc[i] for n, s in req.items()}
                        if node.position:
                            row[node.pos_name] = p
                        row[node.col_name] = el
                        rows.append(row)
                yield self._build_batch(pd.DataFrame(
                    rows, columns=[n for n, _ in node.schema]))
            return
        if isinstance(node, L.FileRelation):
            # disabled-format scan (sql.format.<fmt>.enabled=false): the
            # CPU-Spark-reads-it analog — stream one arrow record batch
            # at a time straight from the dataset, never the whole file
            if node.file_meta:
                raise NotImplementedError(
                    "CPU fallback scan does not expose file metadata "
                    "columns; re-enable the columnar scan")
            from spark_rapids_tpu.io.readers import _dataset
            dataset = _dataset(node.paths, node.file_format)
            names = [n for n, _ in node.schema]
            got_any = False
            for rb in dataset.to_batches(columns=names):
                got_any = True
                yield self._build_batch(rb.to_pandas())
            if not got_any:
                yield self._build_batch(pd.DataFrame(columns=names))
            return
        if isinstance(node, L.InMemoryRelation):
            for b in node.batches:
                yield self._build_batch(b.to_arrow().to_pandas())
            if not node.batches:
                yield self._build_batch(
                    pd.DataFrame(columns=[n for n, _ in node.schema]))
            return
        if isinstance(node, L.Join):
            yield from self._execute_join(node)
            return
        if isinstance(node, L.Aggregate):
            # chunked partial aggregation: bounded state per group, the
            # whole input never lives in one frame
            yield self._build_batch(self._aggregate_frame(node))
            return
        if isinstance(node, L.Sort):
            yield from self._execute_sort(node)
            return
        raise NotImplementedError(
            f"no CPU fallback for {type(node).__name__}")

    # sorted-run spill threshold: inputs under this many rows sort in
    # one in-memory pass; larger inputs run an external merge sort
    SORT_RUN_ROWS = 1 << 20

    def _execute_sort(self, node) -> Iterator[ColumnarBatch]:
        """External merge sort: accumulate bounded sorted runs, spill
        each to a parquet file, then stream a k-way merge — the
        host-side analog of the engine's out-of-core sort
        (exec/sort.py), so even a fallback SORT never holds the whole
        input (CPU Spark's UnsafeExternalSorter role)."""
        by = [e.name for e, _, _ in node.orders]
        ascending = [not d for _, d, _ in node.orders]
        # 0 = nulls sort before values, 1 = after, PER KEY.  pandas
        # sort_values cannot express per-key na_position in one call, so
        # the run sort applies one stable single-key pass per key in
        # reverse order (classic lexicographic composition) — this keeps
        # run ordering byte-identical with the merge's keyify tuples.
        null_ranks = [0 if nf else 1 for _, _, nf in node.orders]

        from spark_rapids_tpu.utils.hostsort import sort_per_key_nulls

        def sort_frame(df):
            return sort_per_key_nulls(
                df, by, ascending, [nr == 0 for nr in null_ranks])

        # spill dir cleanup must survive an early-stopped consumer
        # (GeneratorExit at a mid-merge yield) or a merge exception:
        # the finally below wraps every yield
        import tempfile

        pend: List[pd.DataFrame] = []
        pend_rows = 0
        runs: List[str] = []
        tmpdir = None
        try:
            for df in self._child_frames(0):
                pend.append(df)
                pend_rows += len(df)
                if pend_rows >= self.SORT_RUN_ROWS:
                    if tmpdir is None:
                        tmpdir = tempfile.mkdtemp(prefix="tpu-fbsort-")
                    run = sort_frame(pd.concat(pend, ignore_index=True))
                    path = f"{tmpdir}/run-{len(runs)}.parquet"
                    run.to_parquet(path, index=False)
                    runs.append(path)
                    pend, pend_rows = [], 0
            tail = sort_frame(pd.concat(pend, ignore_index=True)) \
                if pend else None
            if not runs:
                yield self._build_batch(
                    tail if tail is not None
                    else pd.DataFrame(
                        columns=[n for n, _ in node.schema]))
                return
            yield from self._sort_merge(runs, tail, by, ascending,
                                        null_ranks)
        finally:
            if tmpdir is not None:
                import shutil
                shutil.rmtree(tmpdir, ignore_errors=True)

    def _sort_merge(self, runs, tail, by, ascending, null_ranks
                    ) -> Iterator[ColumnarBatch]:
        import heapq

        # k-way merge over sorted sources: rows keyed by a tuple that
        # encodes asc/desc and the per-key null rank
        def is_null_scalar(v):
            if v is None:
                return True
            try:
                return bool(pd.isna(v))
            except (TypeError, ValueError):
                return False

        def keyify(kr):
            out = []
            for v, asc, nr in zip(kr, ascending, null_ranks):
                if is_null_scalar(v):
                    out.append((nr, 0))
                else:
                    out.append((1 - nr, v if asc else _Neg(v)))
            return tuple(out)

        def rows_of(source):
            """(key, full-row) pairs streamed from one sorted run."""
            import pyarrow.parquet as pq
            if isinstance(source, str):
                f = pq.ParquetFile(source)
                frames = (b.to_pandas()
                          for b in f.iter_batches(batch_size=1 << 16))
            else:
                frames = iter([source])
            for fr in frames:
                keys = fr[by].itertuples(index=False, name=None)
                full = fr.itertuples(index=False, name=None)
                for kr, row in zip(keys, full):
                    yield keyify(kr), row

        sources = list(runs) + ([tail] if tail is not None else [])
        if tail is not None:
            cols = list(tail.columns)
        else:
            import pyarrow.parquet as pq
            cols = pq.ParquetFile(runs[0]).schema_arrow.names
        merged = heapq.merge(*[rows_of(s) for s in sources],
                             key=lambda kv: kv[0])
        buf = []
        for _, row in merged:
            buf.append(row)
            if len(buf) >= (1 << 16):
                yield self._build_batch(
                    pd.DataFrame(buf, columns=cols))
                buf = []
        yield self._build_batch(pd.DataFrame(buf, columns=cols))

    def _execute_join(self, node) -> Iterator[ColumnarBatch]:
        lk = [e.name for e in node.left_keys]
        rk = [e.name for e in node.right_keys]
        how = {"inner": "inner", "left": "left", "right": "right",
               "full": "outer", "cross": "cross"}.get(node.join_type)
        if how is None:
            raise NotImplementedError(
                f"CPU fallback join type {node.join_type}")
        if how in ("inner", "left", "cross"):
            # per-probe-row output: build side materializes, probe side
            # streams one chunk at a time
            right = self._child_pandas(1)
            for left in self._child_frames(0):
                yield self._build_batch(
                    self._join_frames(node, left, right, how, lk, rk))
            return
        # right/full joins need global build-side match accounting
        left = self._child_pandas(0)
        right = self._child_pandas(1)
        yield self._build_batch(
            self._join_frames(node, left, right, how, lk, rk))

    def _join_frames(self, node, left: pd.DataFrame, right: pd.DataFrame,
                     how: str, lk, rk) -> pd.DataFrame:
        if node.condition is not None and how in ("left", "right",
                                                  "outer"):
            if how in ("right", "outer"):
                raise NotImplementedError(
                    "CPU fallback right/full join with residual "
                    "condition not supported")
            # residual applies to the MATCH: matched-but-failing rows
            # revert to null-extended output, they are not dropped
            lid = "__fallback_lid"
            left2 = left.copy()
            left2[lid] = np.arange(len(left2))
            if lk:
                inner = left2.merge(right, left_on=lk, right_on=rk,
                                    how="inner")
            else:  # pure non-equi: nested loop = cross
                inner = left2.merge(right, how="cross")
            mask = _eval_pandas(node.condition, inner.drop(
                columns=[lid])).fillna(False).astype(bool)
            inner = inner[mask.values]
            missing = left2[~left2[lid].isin(inner[lid])]
            pad = missing.reindex(
                columns=list(left2.columns) +
                [c for c in right.columns if c not in left2.columns])
            inner = pd.concat([inner, pad], ignore_index=True)
            return inner.drop(columns=[lid])
        out = left.merge(right, left_on=lk, right_on=rk, how=how)
        if node.condition is not None:
            mask = _eval_pandas(node.condition,
                                out).fillna(False).astype(bool)
            out = out[mask.values]
        return out

    def _aggregate_frame(self, node) -> pd.DataFrame:
        """Fold child batches into per-group mergeable partial states
        (the GpuHashAggregate partial/merge split, host-side), then
        finalize + evaluate non-bare result expressions."""
        from spark_rapids_tpu.plan.logical import AggregateExpression
        from spark_rapids_tpu.ops.expressions import Alias as _Alias
        from spark_rapids_tpu.ops.expressions import UnresolvedColumn
        group_names = [e.name for e in node.group_exprs]
        # non-bare outputs (sum(a)*2, sum(a)/sum(b)...): compute the
        # bare aggregates first, then evaluate the result expression
        # over the aggregated frame (the planner's resultExpressions
        # split, mirrored host-side)
        aggs = []
        result_exprs = []  # per output: None (bare) or rewritten expr

        def extract(e):
            if isinstance(e, AggregateExpression):
                name = f"_a{len(aggs)}"
                aggs.append((name, e.func))
                return UnresolvedColumn(name)
            if not e.children:
                return e
            return e.with_children([extract(c) for c in e.children])

        for e in node.agg_exprs:
            name = e.name
            inner = e.children[0] if isinstance(e, _Alias) else e
            if isinstance(inner, AggregateExpression):
                aggs.append((name, inner.func))
                result_exprs.append(None)
            else:
                result_exprs.append((name, extract(inner)))

        states: dict = {}   # normalized key tuple -> per-agg states
        key_vals: dict = {}  # normalized key tuple -> group col values
        if not group_names:
            # global aggregate emits one row even on empty input
            states[()] = [_UNSET] * len(aggs)
            key_vals[()] = {}
        for df in self._child_frames(0):
            if not len(df):
                continue
            if group_names:
                gvals = pd.DataFrame(
                    {e.name: _eval_pandas(e, df).reset_index(drop=True)
                     for e in node.group_exprs})
                gvals["__data_idx"] = np.arange(len(df))
                for key, grp in gvals.groupby(group_names, dropna=False,
                                              sort=False):
                    key = key if isinstance(key, tuple) else (key,)
                    nkey = tuple(_NULL_KEY if _isnull(v) else v
                                 for v in key)
                    sub = df.iloc[grp["__data_idx"].to_numpy()]
                    st = states.get(nkey)
                    if st is None:
                        states[nkey] = st = [_UNSET] * len(aggs)
                        key_vals[nkey] = {
                            n: (None if v is _NULL_KEY else v)
                            for n, v in zip(group_names, nkey)}
                    for j, (_, func) in enumerate(aggs):
                        st[j] = _agg_update(func, st[j], sub)
            else:
                st = states[()]
                for j, (_, func) in enumerate(aggs):
                    st[j] = _agg_update(func, st[j], df)
        rows = []
        for nkey, st in states.items():
            row = dict(key_vals[nkey])
            for (name, _func), s in zip(aggs, st):
                row[name] = _agg_finalize(_func, s)
            rows.append(row)
        agg_frame = pd.DataFrame(
            rows, columns=group_names + [n for n, _ in aggs])
        # evaluate non-bare result expressions over the agg frame
        out_cols = {}
        agg_names = [e.name for e in node.agg_exprs]
        for name in group_names:
            out_cols[name] = agg_frame[name]
        for name, spec in zip(agg_names, result_exprs):
            if spec is None:
                out_cols[name] = agg_frame[name]
            else:
                out_cols[name] = _eval_pandas(spec[1], agg_frame)
        return pd.DataFrame(out_cols,
                            columns=[n for n, _ in node.schema])

    def _build_batch(self, out: pd.DataFrame) -> ColumnarBatch:
        node = self.node
        out = out.reset_index(drop=True)
        want = [n for n, _ in node.schema]
        if list(out.columns) != want:
            out = out[want]
        # build against the node's declared schema: pandas loses types on
        # all-null / object columns (arrow would type them `null`)
        from spark_rapids_tpu.columnar.column import Column
        cols = {}
        for name, dt in node.schema:
            s = out[name]
            if dt.is_string:
                vals = [None if v is None or
                        (not isinstance(v, str) and pd.isna(v))
                        else str(v) for v in s]
                cols[name] = Column.from_strings(vals)
            elif dt.is_array:
                vals = [None if v is None or
                        (not isinstance(v, (list, tuple, np.ndarray))
                         and pd.isna(v)) else list(v) for v in s]
                cols[name] = Column.from_arrays(vals, dt.element)
            elif dt.is_date or dt.is_timestamp:
                # datetime values (tz-aware Timestamps from the arrow
                # bridge, datetime.date objects for DATE32) back to the
                # engine's int day/us encodings
                valid = s.notna().to_numpy()
                vals = pd.to_datetime(s, errors="coerce")
                if getattr(vals.dtype, "tz", None) is not None:
                    vals = vals.dt.tz_convert("UTC").dt.tz_localize(None)
                unit = "us" if dt.is_timestamp else "D"
                ints = vals.to_numpy().astype(
                    f"datetime64[{unit}]").astype(np.int64)
                ints = np.where(valid, ints, 0)
                cols[name] = Column.from_numpy(
                    ints.astype(dt.storage), dtype=dt,
                    validity=None if valid.all() else valid)
            elif dt.is_decimal:
                # unscaled int64 at the declared scale (HALF_UP), not a
                # value-truncating astype over Decimal objects
                import decimal as _d
                q = _d.Decimal(1).scaleb(-dt.scale)
                valid = s.notna().to_numpy()
                ints = [0 if (v is None or pd.isna(v)) else
                        int(_d.Decimal(v).quantize(
                            q, rounding=_d.ROUND_HALF_UP)
                            .scaleb(dt.scale)) for v in s]
                cols[name] = Column.from_numpy(
                    np.asarray(ints, dtype=np.int64), dtype=dt,
                    validity=None if valid.all() else valid)
            else:
                valid = s.notna().to_numpy()
                filled = s.fillna(0).to_numpy()
                cols[name] = Column.from_numpy(
                    np.asarray(filled).astype(dt.storage, copy=False),
                    dtype=dt,
                    validity=None if valid.all() else valid)
        return ColumnarBatch(cols, len(out))
