"""CPU fallback operator.

The analog of the reference leaving unconverted Spark ops on the CPU: a
logical node with no (or disallowed) TPU conversion executes on the host via
pandas over the collected child output.  Columnar data crosses the device
boundary exactly once each way (the GpuColumnarToRow/RowToColumnar
transition-pair analog, GpuTransitionOverrides.scala:44).
"""

from __future__ import annotations

from typing import Iterator, List

import pandas as pd

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.plan import logical as L


class CpuFallbackExec(TpuExec):
    def __init__(self, node: L.LogicalPlan, children: List[TpuExec]):
        super().__init__(*children)
        self.node = node

    @property
    def schema(self) -> Schema:
        return self.node.schema

    def describe(self):
        return f"CpuFallbackExec[{self.node.describe()}]"

    def _child_pandas(self, i: int) -> pd.DataFrame:
        import pyarrow as pa
        batches = [b.to_arrow() for b in self.children[i].execute()]
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch
            return empty_batch(self.children[i].schema).to_pandas()
        return pa.concat_tables(batches).to_pandas()

    def do_execute(self) -> Iterator[ColumnarBatch]:
        node = self.node
        if isinstance(node, L.Sort):
            df = self._child_pandas(0)
            by = [e.name for e, _, _ in node.orders]
            ascending = [not d for _, d, _ in node.orders]
            na_position = "first" if node.orders[0][2] else "last"
            out = df.sort_values(by=by, ascending=ascending,
                                 na_position=na_position, kind="stable")
        elif isinstance(node, L.Join):
            left = self._child_pandas(0)
            right = self._child_pandas(1)
            lk = [e.name for e in node.left_keys]
            rk = [e.name for e in node.right_keys]
            how = {"inner": "inner", "left": "left", "right": "right",
                   "full": "outer", "cross": "cross"}.get(node.join_type)
            if how is None:
                raise NotImplementedError(
                    f"CPU fallback join type {node.join_type}")
            out = left.merge(right, left_on=lk, right_on=rk, how=how)
        elif isinstance(node, L.Limit):
            out = self._child_pandas(0).head(node.n)
        elif isinstance(node, L.Union):
            out = pd.concat([self._child_pandas(i)
                             for i in range(len(self.children))])
        else:
            raise NotImplementedError(
                f"no CPU fallback for {type(node).__name__}")
        out = out.reset_index(drop=True)
        want = [n for n, _ in node.schema]
        if list(out.columns) != want:
            out = out[want]
        yield ColumnarBatch.from_pandas(out)
