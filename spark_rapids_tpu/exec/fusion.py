"""Whole-stage fusion: collapse Filter/Project chains into ONE compiled
stage (the cross-operator half of the stage compiler).

``ops/compiler.py`` already fuses WITHIN one operator — a project's whole
expression forest, or a filter's predicate + compaction, is one XLA
computation.  This module fuses ACROSS operators: a
``Filter <- Project <- Filter`` chain that today dispatches three jitted
callables (with a full device materialization between each) composes into
a single :class:`FusedStageExec` whose compiled function evaluates every
member's expressions in one trace — projections substitute through
(``substitute_bound``), predicates AND into one row mask carried inside
the trace, and the selection compacts ONCE at the stage boundary instead
of once per filter.  Intermediates never leave registers/VMEM; each batch
costs one jit dispatch per pipeline stage ("Data Path Fusion in GPU for
Analytical Query Processing", PAPERS.md).

Composition is the logical-plan walk in ``plan/overrides.py``
(``TpuOverrides._try_fuse_chain``) and
``parallel/dist_planner.py`` (``DistPlanner._fused_chain``); this module
holds the shared chain composer and the single-process operator.  Fusion
never crosses an exchange, a cached plan node, or a member the fuser
cannot ingest (black-box UDFs, CPU-fallback expressions) — those chains
run unfused, counted as ``fusibleChains`` so the profiling health check
can flag the lost fusion.  ``spark.rapids.tpu.fusion.enabled=false`` is
the A/B switch: results are bit-identical either way (masked evaluation
and per-operator compaction select the same rows in the same order).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import (NUM_INPUT_BATCHES, NUM_INPUT_ROWS,
                                        Schema, TpuExec)
from spark_rapids_tpu.ops.compiler import FilterStageFn, StageFn
from spark_rapids_tpu.ops.expressions import (BoundReference, Expression,
                                              substitute_bound)

# QueryEnd "fusion" dict metric names (tools/eventlog.QueryInfo.fusion)
FUSED_OPERATORS = "fusedOperators"
DISPATCHES_SAVED = "dispatchesSaved"


class FusionMetrics:
    """Process-wide fusion counters (the checkpoint_metrics discipline),
    surfaced by bench.py alongside the jit-cache counters."""

    FIELDS = ("fusedStages", "fusedOperators", "fusibleChains",
              "fallbacks",
              # Pallas hash-kernel dispatch breadcrumbs: launches that
              # went through the hash table, and launches that came back
              # with the overflow flag set and re-ran the sort kernel
              # (rows are never dropped — the fallback is the exact path).
              "hashKernelLaunches", "hashOverflowFallbacks",
              # Wire-fused distributed stages: stages that emitted the
              # packed wire payload inside the compute program, and warm
              # stages that COULD have fused but ran the two-dispatch
              # path (the "fusible chain ran unfused" health-check family).
              "fusedWireStages", "wireUnfusedLaunches")

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {k: 0 for k in self.FIELDS}

    def bump(self, field: str, by: int = 1) -> None:
        with self._lock:
            self.counters[field] += int(by)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def reset(self) -> None:
        with self._lock:
            for k in self.counters:
                self.counters[k] = 0


fusion_metrics = FusionMetrics()

# Hash-kernel / wire-fusion counters folded into each QueryEnd fusion
# dict as per-query deltas of the process-wide counters above.  Only
# non-zero deltas are merged: a query with no hash-kernel or wire-fusion
# activity emits a fusion dict bit-identical to HEAD's.
QUERY_DELTA_FIELDS = ("hashKernelLaunches", "hashOverflowFallbacks",
                      "fusedWireStages", "wireUnfusedLaunches")


def hash_wire_delta(before: Dict[str, int]) -> Dict[str, int]:
    """Non-zero per-query deltas of the hash/wire fusion counters since
    ``before`` (a ``fusion_metrics.snapshot()`` taken at query start)."""
    now = fusion_metrics.snapshot()
    return {k: now.get(k, 0) - before.get(k, 0)
            for k in QUERY_DELTA_FIELDS
            if now.get(k, 0) - before.get(k, 0)}


def compose_chain(exprs: Optional[List[Expression]],
                  conds: List[Expression], node,
                  schema: Schema) -> Tuple[List[Expression],
                                           List[Expression]]:
    """Fold one chain member into the running (exprs, conds) pair.

    Invariant: after folding member ``node``, ``exprs`` and every
    conjunct in ``conds`` are expressed over ``node``'s INPUT (child)
    namespace — a Project substitutes its expressions through all of
    them, a Filter (pass-through namespace) prepends its predicate, so
    ``conds`` stays in BOTTOM-FIRST chain order (the evaluation order
    FilterStageFn's progressive ANSI-check masking needs).  Masked
    evaluation selects the same rows as per-operator compaction:
    compaction preserves row order and every expression is pure, so
    evaluating a projection before (rather than after) a downstream
    filter's compaction gathers identical values for the surviving
    rows."""
    from spark_rapids_tpu.plan import logical as L
    if isinstance(node, L.Project):
        repl = list(node.exprs)
        if exprs is None:
            exprs = repl
        else:
            exprs = [substitute_bound(e, repl) for e in exprs]
        conds = [substitute_bound(c, repl) for c in conds]
    else:  # Filter: namespace unchanged
        if exprs is None:
            exprs = [BoundReference(i, dt, name=n)
                     for i, (n, dt) in enumerate(schema)]
        conds = [node.condition] + conds
    return exprs, conds


def has_check_exprs(exprs) -> bool:
    """True when any expression tree records trace-time ANSI checks
    (today: ``Cast(ansi=True)``, the only ``EmitContext.add_check``
    producer).  The AGGREGATE fold must refuse such chains: the
    aggregation kernels return (keys, buffers, count) with no check-
    flag channel, so a check recorded inside them would be silently
    dropped — the chain fuses as a FusedStageExec (whose stage wrappers
    surface checks) feeding an unfused aggregate instead."""
    from spark_rapids_tpu.ops.cast import Cast

    def walk(e) -> bool:
        if isinstance(e, Cast) and e.ansi:
            return True
        return any(walk(c) for c in e.children)

    return any(walk(e) for e in exprs)


def collect_runtime_savings(exec_root: TpuExec) -> Dict[str, int]:
    """Walk an executed physical tree for fusion attribution: stages and
    member operators actually fused, plus the jit dispatches banked this
    run (one per collapsed operator per batch) — the runtime half of the
    QueryEnd ``fusion`` dict."""
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    out = {"fusedStages": 0, "fusedOperators": 0, "dispatchesSaved": 0,
           "encodedStages": 0}

    def rec(n):
        if isinstance(n, FusedStageExec):
            out["fusedStages"] += 1
            out["fusedOperators"] += len(n.members)
            out["dispatchesSaved"] += n.metrics[DISPATCHES_SAVED].value
        elif isinstance(n, TpuHashAggregateExec):
            if getattr(n, "fused_ops", 0):
                out["fusedStages"] += 1
                out["fusedOperators"] += n.fused_ops + 1
                out["dispatchesSaved"] += \
                    n.fused_ops * n.metrics[NUM_INPUT_BATCHES].value
            if getattr(n, "_encoded_exec", False):
                # encoded execution: the stage ran on dictionary codes
                # (bench encoded_stage_count / QueryEnd fusion dict)
                out["encodedStages"] += 1
        for c in n.children:
            rec(c)

    rec(exec_root)
    return out


class FusedStageExec(TpuExec):
    """One compiled stage for a collapsed Filter/Project chain.

    ``exprs`` are the stage's output expressions and ``conds`` the
    member predicates (bottom-first), all over the child's schema.
    With predicates the stage runs a :class:`FilterStageFn` (one
    progressively-masked predicate pass + projections + a single
    compaction in one XLA computation); without, a plain
    :class:`StageFn`.  ``members`` names the collapsed logical
    operators (display + observability)."""

    ephemeral_output = True

    def __init__(self, exprs: Sequence[Expression],
                 conds: Sequence[Expression], child: TpuExec,
                 members: Sequence[str], donate: bool = False):
        super().__init__(child)
        self.exprs = list(exprs)
        self.conds = list(conds or [])
        self.condition = self.conds[0] if self.conds else None
        self.members = list(members)
        in_dtypes = [dt for _, dt in child.schema]
        donate = donate and child.ephemeral_output
        if self.conds:
            self._fn = FilterStageFn(self.conds, self.exprs, in_dtypes,
                                     donate=donate)
        else:
            self._fn = StageFn(self.exprs, in_dtypes, donate=donate)
        self._register_metric(NUM_INPUT_ROWS)
        self._register_metric(NUM_INPUT_BATCHES)
        m = self._register_metric(FUSED_OPERATORS)
        m.value = len(self.members)
        self._register_metric(DISPATCHES_SAVED)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return [(e.name, e.dtype) for e in self.exprs]

    def describe(self) -> str:
        return (f"FusedStageExec[{'+'.join(self.members)}; "
                f"{len(self.exprs)} cols"
                + (", filtered" if self.condition is not None else "")
                + "]")

    def _compute_batch(self, batch, names):
        """One fused dispatch (span point ``stage.fused``)."""
        if self.condition is None:
            cols = self._fn(batch)
            return ColumnarBatch(dict(zip(names, cols)),
                                 batch.row_count)
        cols, n = self._fn(batch)
        return None if n == 0 else \
            ColumnarBatch(dict(zip(names, cols)), n)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import with_retry
        names = [e.name for e in self.exprs]
        saved_per_batch = max(len(self.members) - 1, 0)

        def tallied():
            from spark_rapids_tpu.parallel.exchange_async import (
                resolve_pending)
            for batch in self.child.execute():
                self.metrics[NUM_INPUT_ROWS] += batch.row_count
                self.metrics[NUM_INPUT_BATCHES] += 1
                yield batch
                # fused-stage batch boundary = async-exchange resolution
                # point: the stage's compute for this batch has been
                # dispatched, so any in-flight exchange on this thread
                # (a distributed sub-execution feeding the stage)
                # verifies NOW, behind that dispatch — no-op when the
                # thread holds no window (parallel/exchange_async.py)
                resolve_pending()

        from spark_rapids_tpu.utils import tracing
        stage_op = "+".join(self.members)

        def compute(batch):
            # one jit dispatch where the unfused chain pays one per
            # member — the saving the QueryEnd fusion dict reports.
            # Counted per ATTEMPT (an OOM retry re-dispatches here, and
            # would have re-dispatched every member unfused), so the
            # metric can legitimately exceed members-1 x inputBatches
            # on retried queries
            self.metrics[DISPATCHES_SAVED] += saved_per_batch
            if tracing._armed:
                with tracing.span("stage.fused", op=stage_op):
                    return self._compute_batch(batch, names)
            return self._compute_batch(batch, names)

        if self._fn.donate:
            # donated inputs are consumed by the kernel: operator-level
            # OOM retry is unsafe, faults escalate to query-level
            # recovery (docs/performance.md#donation)
            for batch in tallied():
                out = compute(batch)
                if out is not None:
                    yield out
            return
        for out in with_retry(tallied(), compute):
            if out is not None:
                yield out
