"""Window physical operator + window expression classes.

Counterpart of GpuWindowExec / GpuWindowExpression (SURVEY.md section 2.4
"Window": frame types, lead/lag/rank/row_number/count/sum/min/max, and the
running-window optimization).  Here *every* supported frame is computed from
one sort + segment arithmetic (ops/window.py), so the reference's special
"running window" fast path is simply the general path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import SORT_TIME, Schema, TpuExec
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops import window as W
from spark_rapids_tpu.ops.compiler import StageFn
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.expressions import ColVal, Expression


@dataclasses.dataclass
class Frame:
    kind: str = "range"          # 'rows' | 'range'
    lo: Optional[int] = None     # None = unbounded preceding
    hi: Optional[int] = 0        # 0 = current row; None = unbounded following


class WindowSpec:
    def __init__(self, partition_exprs: Sequence[Expression] = (),
                 orders: Sequence[Tuple[Expression, bool, bool]] = (),
                 frame: Optional[Frame] = None):
        self.partition_exprs = list(partition_exprs)
        self.orders = list(orders)
        if frame is None:
            # Spark default: range running frame if ordered, else whole
            # partition
            frame = Frame("range", None, 0) if self.orders else \
                Frame("rows", None, None)
        self.frame = frame

    def bind(self, schema) -> "WindowSpec":
        return WindowSpec([e.bind(schema) for e in self.partition_exprs],
                          [(e.bind(schema), d, nf)
                           for e, d, nf in self.orders], self.frame)

    def cache_key(self):
        return (tuple(e.cache_key() for e in self.partition_exprs),
                tuple((e.cache_key(), d, nf) for e, d, nf in self.orders),
                (self.frame.kind, self.frame.lo, self.frame.hi))


class WindowExpression(Expression):
    """func OVER spec."""

    def __init__(self, kind: str, spec: WindowSpec,
                 child: Optional[Expression] = None, offset: int = 1,
                 default: Optional[Expression] = None):
        self.kind = kind  # row_number|rank|dense_rank|percent_rank|
        #                   lead|lag|sum|count|min|max|avg
        self.spec = spec
        self.child_expr = child
        self.offset = offset
        self.default = default
        kids = [e for e, _, _ in spec.orders] + list(spec.partition_exprs)
        if child is not None:
            kids.append(child)
        if default is not None:
            kids.append(default)
        self.children = tuple(kids)

    def bind(self, schema):
        return WindowExpression(
            self.kind, self.spec.bind(schema),
            self.child_expr.bind(schema) if self.child_expr is not None
            else None,
            self.offset,
            self.default.bind(schema) if self.default is not None else None)

    @property
    def dtype(self) -> DataType:
        if self.kind in ("row_number", "rank", "dense_rank"):
            return dts.INT32
        if self.kind == "percent_rank":
            return dts.FLOAT64
        if self.kind == "count":
            return dts.INT64
        if self.kind == "avg":
            return dts.FLOAT64
        if self.kind == "sum":
            t = self.child_expr.dtype
            return dts.FLOAT64 if t.is_floating else (
                t if t.is_decimal else dts.INT64)
        return self.child_expr.dtype

    @property
    def nullable(self) -> bool:
        return self.kind not in ("row_number", "rank", "dense_rank",
                                 "percent_rank", "count")

    @property
    def name(self) -> str:
        return f"{self.kind}()"

    def emit(self, ctx):
        raise RuntimeError("WindowExpression must be planned by "
                           "TpuWindowExec")

    def cache_key(self):
        return ("WindowExpression", self.kind, self.offset,
                self.spec.cache_key(),
                self.child_expr.cache_key() if self.child_expr else None,
                self.default.cache_key() if self.default is not None
                else None)

    def supported_reason(self) -> Optional[str]:
        f = self.spec.frame
        if self.kind in ("row_number", "rank", "dense_rank", "percent_rank",
                         "lead", "lag"):
            if not self.spec.orders and self.kind != "row_number":
                return f"{self.kind} requires an ORDER BY"
            return None
        if self.kind in ("sum", "count", "avg"):
            if f.kind == "range" and not (f.lo is None and f.hi in (0, None)):
                return "range frames with value offsets not supported"
            return None
        if self.kind in ("min", "max"):
            whole = f.lo is None and f.hi is None
            running = f.lo is None and f.hi == 0
            if not (whole or running):
                return f"{self.kind} supports only running or " \
                    "whole-partition frames"
            return None
        return f"unknown window function {self.kind}"


def group_by_spec(window_exprs):
    """[(orig_idx, name, we)] groups, one per distinct window spec, in
    first-appearance order — shared by the single-process converter
    (plan/overrides._conv_window) and the distributed planner
    (dist_planner._window) so both split multi-spec Window nodes
    identically."""
    groups, by_key = [], {}
    for j, (name, we) in enumerate(window_exprs):
        k = we.spec.cache_key()
        if k not in by_key:
            by_key[k] = len(groups)
            groups.append([])
        groups[by_key[k]].append((j, name, we))
    return groups


def eval_window_expr(we: WindowExpression, sp: W.SortedPartitions,
                 c: Optional[ColVal], seg_boundary, capacity: int
                 ) -> Tuple[ColVal, tuple]:
    """(output, aux): aux carries the running-state arrays used by
    the chunked path to continue a partition across chunks (empty
    for non-running frames)."""
    f = we.spec.frame
    kind = we.kind
    if kind == "row_number":
        rn = W.row_number(sp)
        return rn, (rn.values,)
    if kind == "rank":
        return W.rank(sp), ()
    if kind == "dense_rank":
        return W.dense_rank(sp), ()
    if kind == "percent_rank":
        return W.percent_rank(sp), ()
    if kind in ("lead", "lag"):
        off = we.offset if kind == "lead" else -we.offset
        # defaults are literals; emit standalone
        dflt = None
        if we.default is not None:
            from spark_rapids_tpu.ops.expressions import EmitContext
            dflt = we.default.emit(EmitContext([], jnp.int32(0),
                                               capacity))
        return W.lead_lag(sp, c, off, dflt), ()

    rows = f.kind == "rows"
    result_dt = we.dtype
    if kind in ("sum", "count", "avg"):
        cin = c if kind != "count" else (c or ColVal(
            dts.INT64, jnp.ones(capacity, dtype=jnp.int64)))
        vals = cin.values.astype(result_dt.storage) \
            if kind == "sum" else cin.values
        if kind == "avg":
            vals = vals.astype(jnp.float64)
        cv = ColVal(cin.dtype, vals, cin.validity)
        running = f.lo is None and f.hi == 0
        if not rows and running:
            # range running: include full tie run
            s, n = W.frame_sum(sp, cv, None, 0, rows=False)
        else:
            s, n = W.frame_sum(sp, cv, f.lo, f.hi, rows=True)
        aux = (s, n) if running else ()
        if kind == "count":
            return ColVal(dts.INT64, n), aux
        if kind == "avg":
            return ColVal(dts.FLOAT64,
                          s / jnp.maximum(n, 1).astype(jnp.float64),
                          n > 0), aux
        return ColVal(result_dt, s, n > 0), aux
    if kind in ("min", "max"):
        whole = f.lo is None and f.hi is None
        if whole:
            v, n = W.partition_reduce(sp, c, kind, capacity)
            return ColVal(result_dt, v, n > 0), ()
        v, n = W.running_minmax(sp, c, kind, seg_boundary)
        if f.kind == "range":
            v = v[sp.run_end]
            n = n[sp.run_end]
        return ColVal(result_dt, v, n > 0), (v, n)
    raise ValueError(kind)


class TpuWindowExec(TpuExec):
    # frames a running carry can continue across chunk boundaries
    _RUNNING_KINDS = ("sum", "count", "avg", "min", "max", "row_number")

    def __init__(self, window_exprs: Sequence[Tuple[str, WindowExpression]],
                 child: TpuExec, presorted: bool = False,
                 batch_rows: int = 1 << 20):
        super().__init__(child)
        self.window_exprs = list(window_exprs)
        self.presorted = presorted
        self.batch_rows = batch_rows
        self._register_metric(SORT_TIME)
        spec = self.window_exprs[0][1].spec
        for _, we in self.window_exprs[1:]:
            if we.spec.cache_key() != spec.cache_key():
                raise ValueError("one TpuWindowExec handles one window spec")
        self.spec = spec
        in_dtypes = [dt for _, dt in child.schema]
        # stage A: partition keys, order keys, agg children, defaults
        self._pre_exprs: List[Expression] = list(spec.partition_exprs) + \
            [e for e, _, _ in spec.orders]
        n_keys = len(self._pre_exprs)
        self._extra_ofs: Dict[int, int] = {}
        for i, (_, we) in enumerate(self.window_exprs):
            if we.child_expr is not None:
                self._extra_ofs[i] = len(self._pre_exprs) - n_keys
                self._pre_exprs.append(we.child_expr)
        self._pre_fn = StageFn(self._pre_exprs, in_dtypes)
        self._string_part_idx = [
            i for i, e in enumerate(spec.partition_exprs)
            if e.dtype.is_string]
        from spark_rapids_tpu.exec.aggregate import _StringKeyEncoder
        self._encoders = {i: _StringKeyEncoder()
                          for i in self._string_part_idx}
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        sig = ("window",
               tuple(we.cache_key() for _, we in self.window_exprs),
               tuple(dt.name for dt in in_dtypes), presorted)
        self._kernel = cached_jit(sig, lambda: self._run)

    def _running_capable(self) -> bool:
        """Every window function can carry running state across chunks
        (needed to stream a partition larger than one chunk)."""
        for _, we in self.window_exprs:
            f = we.spec.frame
            if we.kind == "row_number":
                continue
            if we.kind in self._RUNNING_KINDS and \
                    f.lo is None and f.hi == 0:
                continue
            return False
        return True

    def _needs_run_aligned_split(self) -> bool:
        """RANGE running frames include the full order-key tie run, so a
        chunk split inside a run would emit rows missing later run
        members — splits must land on run boundaries."""
        return any(we.spec.frame.kind == "range"
                   for _, we in self.window_exprs
                   if we.kind != "row_number")

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return list(self.child.schema) + \
            [(name, we.dtype) for name, we in self.window_exprs]

    def describe(self):
        return (f"TpuWindowExec[{[n for n, _ in self.window_exprs]} over "
                f"part={[e.name for e in self.spec.partition_exprs]}]")

    # ---- kernel --------------------------------------------------------------
    def _run(self, part_keys: List[ColVal], order_keys: List[ColVal],
             extras: List[ColVal], payload: List[ColVal], nrows):
        # row capacity — via offsets for string/array ColVals, whose
        # .values is the CHARS/element buffer (a different bucket)
        def _cap(c):
            if c.offsets is not None:
                return int(c.offsets.shape[0]) - 1
            return int(c.values.shape[0])
        capacity = _cap(payload[0]) if payload else \
            _cap((part_keys + order_keys)[0])
        live = jnp.arange(capacity, dtype=jnp.int32) < nrows
        keys = list(part_keys) + list(order_keys)
        if keys and not self.presorted:
            perm = agg.sort_permutation(
                keys, live, capacity,
                descending=[False] * len(part_keys) +
                [d for _, d, _ in self.spec.orders],
                nulls_first=[True] * len(part_keys) +
                [nf for _, _, nf in self.spec.orders])
            s_part = selection.gather(part_keys, perm, nrows)
            s_order = selection.gather(order_keys, perm, nrows)
            s_extras = selection.gather(extras, perm, nrows)
            s_payload = selection.gather(payload, perm, nrows)
        else:
            s_part, s_order = part_keys, order_keys
            s_extras, s_payload = extras, payload
        s_live = jnp.arange(capacity, dtype=jnp.int32) < nrows

        seg_boundary = _boundaries(s_part, s_live, capacity)
        run_boundary = _boundaries(s_order, s_live, capacity) \
            if s_order else jnp.zeros(capacity, dtype=jnp.bool_)
        sp = W.SortedPartitions(seg_boundary, run_boundary, s_live, capacity)

        outs: List[ColVal] = []
        auxs = []
        for i, (_, we) in enumerate(self.window_exprs):
            c = s_extras[self._extra_ofs[i]] if i in self._extra_ofs else None
            out, aux = eval_window_expr(we, sp, c, seg_boundary,
                                        capacity)
            outs.append(out)
            auxs.append(aux)
        return s_payload, outs, tuple(auxs)

    # ---- drive ---------------------------------------------------------------
    def _stage_inputs(self, merged: ColumnarBatch):
        pre_cols = self._pre_fn(merged)
        np_ = len(self.spec.partition_exprs)
        no = len(self.spec.orders)
        part_cols = pre_cols[:np_]
        part_cols = [self._encoders[i].encode(c)
                     if i in self._string_part_idx else c
                     for i, c in enumerate(part_cols)]
        part_keys = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                     for c in part_cols]
        order_keys = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                      for c in pre_cols[np_:np_ + no]]
        extras = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                  for c in pre_cols[np_ + no:]]
        payload = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                   for c in merged.columns.values()]
        return part_keys, order_keys, extras, payload

    def _make_batch(self, s_payload, outs, n: int,
                    capacity: int) -> ColumnarBatch:
        names = [nm for nm, _ in self.schema]
        cols: Dict[str, Column] = {}
        for nm, o in zip(names, list(s_payload) + list(outs)):
            values = o.values
            if getattr(values, "ndim", 0) == 0:
                values = jnp.broadcast_to(values, (capacity,))
            cols[nm] = Column(o.dtype, values, n, validity=o.validity,
                              offsets=o.offsets)
        return ColumnarBatch(cols, n)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        if self.presorted and self.spec.partition_exprs:
            yield from self._chunked_execute()
            return
        batches = list(self.child.execute())
        if not batches:
            return
        merged = concat_batches(batches)
        with self.timer(SORT_TIME):
            part_keys, order_keys, extras, payload = \
                self._stage_inputs(merged)
            s_payload, outs, _ = self._kernel(
                part_keys, order_keys, extras, payload,
                jnp.int32(merged.nrows))
        yield self._make_batch(s_payload, outs, merged.nrows,
                               merged.capacity)

    # ---- chunked path (GpuKeyBatchingIterator + running-window analog) --
    def _boundary_indices(self, part_keys, nrows: int,
                          cutoff: Optional[int] = None,
                          order_keys=None):
        """(first, last) partition-start indices after row 0 within
        rows ``[0, cutoff]`` (0 when none): one tiny device->host sync
        per chunk.  With ``order_keys``, boundaries are partition OR
        order-key-run starts (run-aligned split points)."""
        k0 = part_keys[0]
        cap = (int(k0.offsets.shape[0]) - 1 if k0.offsets is not None
               else int(k0.values.shape[0]))
        live = jnp.arange(cap, dtype=jnp.int32) < nrows
        b = _boundaries(part_keys, live, cap)
        if order_keys:
            b = jnp.logical_or(b, _boundaries(order_keys, live, cap))
        b = b.at[0].set(False)
        pos = jnp.arange(cap, dtype=jnp.int32)
        if cutoff is not None:
            b = jnp.logical_and(b, pos <= cutoff)
        first = jnp.min(jnp.where(b, pos, cap))
        last = jnp.max(jnp.where(b, pos, 0))
        import numpy as _np
        first = int(_np.asarray(first))
        return (0 if first >= cap else first), int(_np.asarray(last))

    def _adjust(self, we: WindowExpression, out: ColVal, aux, carry,
                mask):
        """Combine a chunk's outputs with the carried running state for
        rows continuing the previous chunk's last partition."""
        kind = we.kind
        if kind == "row_number":
            return ColVal(out.dtype,
                          jnp.where(mask, out.values + carry[0],
                                    out.values), out.validity)
        if kind == "count":
            return ColVal(out.dtype,
                          jnp.where(mask, out.values + carry[1],
                                    out.values), out.validity)
        if kind in ("sum", "avg"):
            s, n = aux
            cs, cn = carry
            s2 = jnp.where(mask, s + cs, s)
            n2 = jnp.where(mask, n + cn, n)
            if kind == "sum":
                return ColVal(out.dtype, s2, n2 > 0)
            return ColVal(out.dtype,
                          s2 / jnp.maximum(n2, 1).astype(jnp.float64),
                          n2 > 0)
        if kind in ("min", "max"):
            v, n = aux
            cv, cn = carry
            op = jnp.minimum if kind == "min" else jnp.maximum
            both = (n > 0) & (cn > 0)
            v2 = jnp.where(mask & both, op(v, cv),
                           jnp.where(mask & (n == 0) & (cn > 0), cv, v))
            n2 = jnp.where(mask, n + cn, n)
            return ColVal(out.dtype, v2, n2 > 0)
        raise ValueError(kind)

    def _carry_out(self, we: WindowExpression, aux, prev, last: int):
        """New carry after emitting a chunk whose last partition is
        still open: running totals at the chunk's last row, combined
        with the previous carry when the chunk continued it."""
        kind = we.kind
        if kind == "row_number":
            rn = aux[0][last]
            return (rn + (prev[0] if prev is not None else 0),)
        s, n = aux[0][last], aux[1][last]
        if prev is not None:
            if kind in ("min", "max"):
                cv, cn = prev
                op = jnp.minimum if kind == "min" else jnp.maximum
                s = jnp.where((n > 0) & (cn > 0), op(s, cv),
                              jnp.where(n > 0, s, cv))
                n = n + cn
            else:
                s = s + prev[0]
                n = n + prev[1]
        return (s, n)

    @staticmethod
    def _key_at(part_keys, i: int):
        """Host (value, valid) tuple per partition key at row ``i`` (one
        tiny sync; string keys are already stable dictionary codes)."""
        out = []
        for k in part_keys:
            v = np.asarray(k.values[i]).item()
            valid = True if k.validity is None \
                else bool(np.asarray(k.validity[i]))
            out.append((v, valid))
        return out

    @staticmethod
    def _keys_equal(a, b) -> bool:
        for (va, na), (vb, nb) in zip(a, b):
            if na != nb:
                return False
            if na and va != vb:
                if not (isinstance(va, float) and isinstance(vb, float)
                        and va != va and vb != vb):  # NaN == NaN
                    return False
        return True

    def _chunked_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.ops import selection as sel
        buf: List[ColumnarBatch] = []
        rows = 0
        carry: Optional[List] = None  # per-expr carried state
        carry_key = None              # host key values of the open partition
        running_ok = self._running_capable()
        run_aligned = self._needs_run_aligned_split()

        def process(chunk: ColumnarBatch, staged, n_emit: int,
                    ends_open: bool, first_b: int):
            """Run the kernel over chunk[:n_emit]; returns the output
            batch, updating ``carry``.  ``ends_open``: the prefix's last
            partition continues past n_emit; ``first_b``: first
            partition-start index inside the prefix (0 = none — the
            whole prefix continues the carried partition)."""
            nonlocal carry, carry_key
            with self.timer(SORT_TIME):
                part_keys, order_keys, extras, payload = staged
                if carry is not None and not self._keys_equal(
                        self._key_at(part_keys, 0), carry_key):
                    # chunk boundary coincided with a partition boundary
                    # (row 0 is excluded from boundary detection): the
                    # carried partition ended exactly at the previous
                    # chunk's edge — its state must not leak into this one
                    carry = None
                    carry_key = None
                s_payload, outs, auxs = self._kernel(
                    part_keys, order_keys, extras, payload,
                    jnp.int32(n_emit))
                if carry is not None:
                    fb = first_b if first_b > 0 else n_emit
                    mask = jnp.arange(chunk.capacity,
                                      dtype=jnp.int32) < fb
                    outs = [self._adjust(we, o, aux, c, mask)
                            if c is not None else o
                            for (_, we), o, aux, c in
                            zip(self.window_exprs, outs, auxs, carry)]
                if ends_open:
                    # the prefix's open tail partition is the carried one
                    # only when no boundary interrupted it
                    prev = carry if first_b == 0 else None
                    carry = [self._carry_out(we, aux, prev[i]
                                             if prev is not None else None,
                                             n_emit - 1)
                             for i, ((_, we), aux) in enumerate(
                                 zip(self.window_exprs, auxs))]
                    carry_key = self._key_at(part_keys, n_emit - 1)
                else:
                    carry = None
                    carry_key = None
            return self._make_batch(s_payload, outs, n_emit,
                                    chunk.capacity)

        def tail_of(chunk: ColumnarBatch, start: int, total: int
                    ) -> ColumnarBatch:
            n_tail = total - start
            cols = {}
            idx = jnp.arange(chunk.capacity, dtype=jnp.int32) + start
            idx = jnp.clip(idx, 0, chunk.capacity - 1)
            for nm, c in chunk.columns.items():
                cv = ColVal(c.dtype, c.data, c.validity, c.offsets)
                g = sel.gather([cv], idx, jnp.int32(n_tail))[0]
                cols[nm] = Column(g.dtype, g.values, n_tail,
                                  validity=g.validity, offsets=g.offsets)
            return ColumnarBatch(cols, n_tail)

        for batch in self.child.execute():
            if batch.nrows == 0:
                continue
            buf.append(batch)
            rows += batch.nrows
            while rows >= self.batch_rows:
                chunk = concat_batches(buf)
                staged = self._stage_inputs(chunk)
                part_keys, order_keys = staged[0], staged[1]
                first, last = self._boundary_indices(
                    part_keys, rows, cutoff=self.batch_rows)
                if last > 0:
                    # emit up to the last partition boundary within the
                    # target (complete partitions only)
                    e, ends_open = last, False
                elif running_ok:
                    # partition longer than the target: emit a slice
                    # and carry its running state forward; RANGE frames
                    # may only split at an order-key run boundary (a
                    # split inside a tie run would emit rows missing
                    # later run members)
                    if run_aligned:
                        _, rb = self._boundary_indices(
                            part_keys, rows, cutoff=self.batch_rows,
                            order_keys=order_keys)
                        if rb == 0:
                            break  # one tie run fills the target: grow
                        e, ends_open = rb, True
                    else:
                        e, ends_open = min(self.batch_rows, rows), True
                else:
                    first_any, _ = self._boundary_indices(
                        part_keys, rows)
                    if first_any > 0:
                        # the oversized head partition completes later
                        # in the buffer: emit exactly it
                        e, ends_open, first = first_any, False, first_any
                    else:
                        # one open partition fills the whole buffer and
                        # no running carry is possible: keep growing
                        # (the reference's requirement too — a
                        # partition must fit in memory)
                        break
                yield process(chunk, staged, e, ends_open,
                              first if first < e else 0)
                if e < rows:
                    tail = tail_of(chunk, e, rows)
                    buf = [tail]
                    rows = tail.nrows
                else:
                    buf = []
                    rows = 0
        if rows:
            chunk = concat_batches(buf)
            staged = self._stage_inputs(chunk)
            first, _ = self._boundary_indices(staged[0], rows)
            yield process(chunk, staged, rows, False, first)


def _boundaries(cols: List[ColVal], live, capacity: int):
    """True where any key differs from the previous row (or first live)."""
    if not cols:
        return (jnp.arange(capacity, dtype=jnp.int32) == 0) & live
    same = jnp.ones(capacity, dtype=jnp.bool_)
    for c in cols:
        v = c.values
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = jnp.where(v == 0.0, 0.0, v)
            eq = (v == jnp.roll(v, 1)) | (jnp.isnan(v) &
                                          jnp.isnan(jnp.roll(v, 1)))
        else:
            eq = v == jnp.roll(v, 1)
        if c.validity is not None:
            pv = jnp.roll(c.validity, 1)
            eq = jnp.where(c.validity & pv, eq,
                           jnp.logical_not(c.validity | pv))
        same = jnp.logical_and(same, eq)
    boundary = jnp.logical_not(same)
    boundary = boundary.at[0].set(True)
    return jnp.logical_and(boundary, live)
